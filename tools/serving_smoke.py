#!/usr/bin/env python
"""serving_smoke — `make serve-smoke`: prove the decode service end-to-end
on CPU in seconds (docs/serving.md, ISSUE 7 acceptance).

Tiny GPT, 8 concurrent requests with mixed prompt lengths and staggered
arrivals through the continuous-batching service.  Exit 0 requires:

* every request completes, and its greedy tokens are IDENTICAL to a
  single-request ``generate()`` of the same prompt (the parity contract —
  one attention implementation, true positions, same mask);
* ZERO recompile events after warmup (CompileWatcher forensics: one decode
  program + one prefill program per prompt bucket, then pure replays);
* the block pool drains with no leaked blocks;
* telemetry (on for the run) retained ``kind="serving"`` step records with
  occupancy and per-request completion records with TTFT/TPOT.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    import numpy as np

    import accelerate_tpu.nn as nn
    from accelerate_tpu import DecodeService, ServingConfig
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
    from accelerate_tpu.telemetry import Telemetry
    from accelerate_tpu.utils.dataclasses import TelemetryKwargs

    nn.manual_seed(0)
    model = GPTLMHeadModel(GPTConfig.tiny())
    model.eval()
    hub = Telemetry(TelemetryKwargs(enabled=True))
    service = DecodeService(
        model,
        ServingConfig(max_slots=4, block_size=16, prompt_bucket=16),
        telemetry=hub,
    )

    rng = np.random.default_rng(0)
    lengths = [3, 9, 17, 30, 5, 24, 12, 40]
    budgets = [6, 4, 8, 3, 7, 5, 6, 4]
    prompts = [
        rng.integers(0, model.config.vocab_size, (n,), dtype=np.int32)
        for n in lengths
    ]

    # warmup: one request per prefill bucket + the decode program
    from accelerate_tpu.serving import bucket_length

    buckets = sorted({bucket_length(n, 16) for n in lengths})
    for b in buckets:
        service.submit(np.ones(b, np.int32), max_new_tokens=2)
    service.run()
    warm_compiles = service.watcher.compiles_total

    # staggered arrivals: a few requests join per step while earlier ones
    # are mid-decode — the continuous-batching path, not a static batch
    rids = []
    pending = list(zip(prompts, budgets))
    while pending or service.has_work:
        for _ in range(2):
            if pending:
                p, b = pending.pop(0)
                rids.append(service.submit(p, max_new_tokens=b))
        service.step()

    failures = []
    if service.recompile_events != 0:
        failures.append(
            f"{service.recompile_events} recompile event(s) after warmup "
            f"(warmup compiled {warm_compiles})"
        )
    for rid, p, b in zip(rids, prompts, budgets):
        want = np.asarray(model.generate(p[None], max_new_tokens=b))[0]
        got = service.results[rid].output_ids
        if not np.array_equal(got, want):
            failures.append(f"request {rid}: tokens diverge from generate()")
    try:
        service.pool.check_no_leaks()
        if service.pool.free_blocks != service.pool.usable_blocks:
            failures.append("pool did not drain: blocks still reserved")
    except AssertionError as exc:
        failures.append(str(exc))
    records = [r for r in hub.all_records() if r.get("kind") == "serving"]
    steps = [r for r in records if r.get("event") == "step"]
    completes = [r for r in records if r.get("event") == "complete"]
    if not steps or any("occupancy" not in r for r in steps):
        failures.append("no kind='serving' step records with occupancy")
    if len(completes) < len(rids) or any(
        r.get("ttft_ms") is None for r in completes
    ):
        failures.append("missing kind='serving' completion records with TTFT")

    n_done = len([r for r in rids if r in service.results])
    print(
        f"serving_smoke: {n_done}/{len(rids)} requests, "
        f"{service.stats['steps']} steps, mean occupancy "
        f"{service.mean_batch_occupancy:.2f}, {warm_compiles} warmup "
        f"compiles, {service.recompile_events} steady-state recompiles"
    )
    for failure in failures:
        print(f"serving_smoke: FAIL: {failure}", file=sys.stderr)
    print(f"serving_smoke: {'FAILED' if failures else 'ok'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
