"""Offload store tests (mirrors reference tests/test_offload.py)."""

import jax.numpy as jnp
import numpy as np

from accelerate_tpu.utils.offload import (
    OffloadedWeightsLoader,
    extract_submodules_state_dict,
    load_offloaded_weight,
    offload_state_dict,
    offload_weight,
    save_offload_index,
)


def test_offload_weight_roundtrip(tmp_path):
    index = {}
    w = np.random.randn(3, 4).astype(np.float32)
    offload_weight(w, "layer.weight", str(tmp_path), index)
    loaded = load_offloaded_weight(
        str(tmp_path / "layer.weight.dat"), index["layer.weight"]
    )
    np.testing.assert_array_equal(np.asarray(loaded), w)


def test_offload_weight_bfloat16(tmp_path):
    index = {}
    w = jnp.asarray(np.random.randn(4, 2), dtype=jnp.bfloat16)
    offload_weight(np.asarray(w), "w", str(tmp_path), index)
    assert index["w"]["dtype"] == "bfloat16"
    loaded = load_offloaded_weight(str(tmp_path / "w.dat"), index["w"])
    np.testing.assert_array_equal(np.asarray(loaded), np.asarray(w))


def test_offload_weight_scalar(tmp_path):
    index = {}
    offload_weight(np.float32(3.5), "s", str(tmp_path), index)
    loaded = load_offloaded_weight(str(tmp_path / "s.dat"), index["s"])
    assert float(loaded) == 3.5


def test_offloaded_weights_loader(tmp_path):
    disk = {"a": np.ones((2, 2), np.float32)}
    offload_state_dict(str(tmp_path), disk)
    mem = {"b": np.zeros((3,), np.float32)}
    loader = OffloadedWeightsLoader(state_dict=mem, save_folder=str(tmp_path))
    assert sorted(loader.keys()) == ["a", "b"]
    np.testing.assert_array_equal(np.asarray(loader["a"]), disk["a"])
    np.testing.assert_array_equal(loader["b"], mem["b"])


def test_extract_submodules_state_dict():
    sd = {"block.linear.weight": 1, "block.linear.bias": 2, "head.weight": 3}
    sub = extract_submodules_state_dict(sd, ["block.linear"])
    assert sub == {"block.linear.weight": 1, "block.linear.bias": 2}
