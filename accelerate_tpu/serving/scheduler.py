"""Continuous-batching request scheduler over the paged decode engine.

``DecodeService`` is the serving front end (docs/serving.md): construct it
from any model exposing ``_decoder_spec()``, ``submit()`` requests with
arbitrary prompt lengths and token budgets, and drive ``step()`` (or
``run()``).  One ``step()`` is one engine iteration:

1. **Admit** — pop the queue FIFO while a batch slot AND enough pool blocks
   are free: bucket-pad the prompt (``kv_blocks.bucket_length``), reserve
   the request's blocks up front, run the captured prefill (which writes
   the prompt's k/v into the reserved blocks and samples the first token —
   that token's latency is the request's TTFT).
2. **Decode** — one captured call steps EVERY occupied slot
   ``decode_steps`` tokens (default 1): the sampled token feeds the next
   embed and positions advance IN-PROGRAM, so the host pays one dispatch
   and one blocking sync per *n* tokens instead of per token.  Admission
   happens only at these block boundaries, so a joining prompt never
   stalls streaming for in-flight sequences beyond one block.
3. **Evict** — finish detection is host-side post-processing of the
   returned ``(slots, n)`` token block: tokens past a slot's budget/eos
   are discarded (the ≤ n-1 micro-step overrun wrote only into the slot's
   own reservation — ``kv_blocks.blocks_for_request``), and finished
   sequences free their slot and blocks at the block boundary (the freed
   slot is re-admissible next step), instead of riding out the batch.

The host keeps small int mirrors (block tables, positions, last tokens)
for admission math.  On the multi-token path (``decode_steps > 1``) the
arrays the decode program consumes are COMMITTED DEVICE STATE owned by
the service: each call's outputs feed the next call's inputs, and the
mirrors are re-uploaded only when admission or eviction actually changed
them — a steady-state step performs ZERO host→device transfers.  The
default ``decode_steps=1`` path keeps the classic per-step mirror
uploads on purpose: the program must see the exact (uncommitted) avals
it always has, or it lowers to a different HLO module whose
independently-compiled binary can drift a near-tie argmax off
``generate()``'s — see ``step()``.  The pools live on device and are
donated through every call.
Telemetry: when a hub is attached, every step emits a ``kind="serving"``
occupancy record and every completion a per-request TTFT/TPOT record
(docs/telemetry.md).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Optional

import numpy as np

from ..logging import get_logger
from .kv_blocks import BlockPool, blocks_for_request, bucket_length, make_pools

logger = get_logger(__name__)

# metrics() snapshot-retry bound: the scrape thread races the stepping
# thread's deque appends; four attempts at most, then the scrape proceeds
# without percentiles (and says so — see metrics())
_METRICS_SNAPSHOT_RETRIES = 4


@dataclasses.dataclass
class ServingConfig:
    """Service geometry — every field is baked into the captured programs'
    shapes at construction, which is the zero-recompile contract: nothing a
    request carries (length, budget, arrival time) reaches a shape.

    ``prompt_bucket`` must be a multiple of ``block_size`` so a bucketed
    prefill writes whole blocks.  ``max_request_len`` caps prompt+new per
    request (defaults to the model's positional capacity); ``num_blocks``
    sizes the shared pool (default: full reservation — every slot can hold
    a max-length request; set it lower to oversubscribe and exercise
    queue back-pressure).

    ``decode_steps`` is the device-resident hot-loop knob
    (docs/serving.md §device-resident decode): each engine iteration runs
    *n* decode micro-steps inside ONE captured program, feeding sampled
    tokens back on-device, and the host syncs once per n-token block.
    Default 1 (``$ACCELERATE_SERVING_DECODE_STEPS``) is the classic
    one-token-per-step path, byte-identical to the pre-knob service.
    Greedy per-sequence outputs are identical at every n; latency trades
    granularity for dispatch overhead — a request's tokens arrive in
    blocks of n, so small-batch TPOT drops ~n× while per-token streaming
    granularity coarsens to the block."""

    max_slots: int = 8
    block_size: int = 16
    prompt_bucket: int = 32
    num_blocks: Optional[int] = None
    max_request_len: Optional[int] = None
    decode_steps: Optional[int] = None  # None → $ACCELERATE_SERVING_DECODE_STEPS, default 1
    temperature: float = 0.0
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    quantize_weights: Optional[int] = None
    rng_seed: int = 0
    # retained completed Requests in service.results (oldest evicted past
    # the bound): a long-running service must not grow host memory with its
    # request history — streaming consumers take step()'s return value or
    # pop_result() and the bound never bites
    max_retained_results: int = 4096
    # completions retained for the metrics() sliding window (TTFT/TPOT
    # p50/p99 on the live endpoint, docs/telemetry.md §metrics endpoint)
    metrics_window: int = 512
    # fault tolerance (docs/serving.md §fault tolerance): journal_dir arms
    # the request WAL + deterministic recovery + preemption drain; off
    # (the default) the hot path is byte-identical.  None of these reach a
    # program shape, so none ride the AOT service fingerprint — a warm
    # store serves journaled and journal-less replicas alike.
    journal_dir: Optional[str] = None  # None → $ACCELERATE_SERVING_JOURNAL
    # bounded queueing: submits past this depth raise QueueFullError with
    # a retry-after hint instead of growing host memory without bound
    max_queue_depth: Optional[int] = None
    # transient decode-dispatch faults are retried this many times against
    # the SAME compiled program before the batch is evicted-and-requeued
    max_decode_retries: Optional[int] = None  # None → $ACCELERATE_SERVING_MAX_RETRIES
    retry_backoff_s: float = 0.05

    def __post_init__(self):
        from ..utils.dataclasses import env_int

        if self.decode_steps is None:
            # malformed values warn and keep the single-token default —
            # the one shared env-int parser (utils/dataclasses.env_int)
            self.decode_steps = env_int("ACCELERATE_SERVING_DECODE_STEPS", 1)
        if self.journal_dir is None:
            import os

            self.journal_dir = os.environ.get("ACCELERATE_SERVING_JOURNAL") or None
        if self.max_decode_retries is None:
            self.max_decode_retries = env_int("ACCELERATE_SERVING_MAX_RETRIES", 2)


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray  # (prompt_len,) int32
    max_new_tokens: int
    eos_token_id: Optional[int]
    bucket_len: int
    blocks_needed: int
    state: str = "queued"  # queued -> running -> done (or -> shed)
    tokens: list = dataclasses.field(default_factory=list)
    submitted_t: float = 0.0
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None
    # per-request latency budget: a queued request whose age exceeds this
    # is SHED at admission time (state="shed", never prefilled) — an
    # expired request must not burn a slot its caller stopped waiting for
    deadline_ms: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def output_ids(self) -> np.ndarray:
        """prompt + generated tokens (truncated at the stop token, which is
        itself emitted — matching ``generate()``'s convention)."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)]
        )

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return (self.first_token_t - self.submitted_t) * 1e3

    @property
    def tpot_ms(self) -> Optional[float]:
        """Mean per-output-token latency after the first token."""
        if self.done_t is None or self.first_token_t is None or len(self.tokens) < 2:
            return None
        return (self.done_t - self.first_token_t) / (len(self.tokens) - 1) * 1e3


class DecodeService:
    """Continuous-batching decode front end for one model (docs/serving.md).

    Composes with everything the single-request engine composes with: the
    stacked per-mode param cache is SHARED with ``generate()`` (alternating
    serving and one-shot decode never restacks), int8/int4 weight modes ride
    ``quantize_weights``, and params prepared through ``shard_for_inference``
    keep their GSPMD layouts — pools and activations inherit them.
    """

    def __init__(self, model, config: Optional[ServingConfig] = None, telemetry=None,
                 aot_cache=None, kernels=None, preemption_guard=None):
        from ..models.generation import stacked_params_for_mode

        # Pallas paged-attention decode (docs/kernels.md): explicit handle
        # or the process-active policy; None (the default) keeps run_decode
        # on the gather-then-attend path byte-identically
        if kernels is None:
            from ..native.kernels import current_kernel_policy

            kernels = current_kernel_policy()
        self._kernels = (
            kernels
            if (kernels is not None and getattr(kernels, "paged_attention", False))
            else None
        )

        self.config = cfg = config or ServingConfig()
        if cfg.block_size < 1 or cfg.max_slots < 1:
            raise ValueError("block_size and max_slots must be >= 1")
        if cfg.decode_steps < 1:
            raise ValueError(
                f"decode_steps must be >= 1, got {cfg.decode_steps}"
            )
        if cfg.prompt_bucket % cfg.block_size:
            raise ValueError(
                f"prompt_bucket ({cfg.prompt_bucket}) must be a multiple of "
                f"block_size ({cfg.block_size}) so bucketed prefills write "
                "whole blocks"
            )
        if cfg.quantize_weights not in (None, 4, 8):
            raise ValueError(
                f"quantize_weights={cfg.quantize_weights!r}: use None, 8 or 4"
            )
        self.spec = spec = model._decoder_spec()
        self._qbits = cfg.quantize_weights or 0
        self._g, self._layers = stacked_params_for_mode(
            model, self._qbits, spec.stack
        )
        cap = min(cfg.max_request_len or spec.max_len, spec.max_len)
        self.capacity = (cap // cfg.block_size) * cfg.block_size
        if self.capacity < cfg.prompt_bucket:
            raise ValueError(
                f"usable capacity ({self.capacity}) < prompt_bucket "
                f"({cfg.prompt_bucket}): shrink the bucket or the block size"
            )
        blocks_per_slot = self.capacity // cfg.block_size
        num_blocks = cfg.num_blocks or (cfg.max_slots * blocks_per_slot + 1)
        self.pool = BlockPool(
            num_blocks, cfg.block_size, cfg.max_slots, blocks_per_slot
        )

        import jax
        import jax.numpy as jnp

        from .engine import CompileWatcher

        dcfg = spec.cfg
        n_layers = next(iter(self._layers[0].values())).shape[0]
        # activation dtype drives the pool dtype: one tiny eager embed
        # (params may be bf16 under a mixed-precision prepare)
        act_dtype = spec.family.embed(
            self._g, jnp.zeros((1, 1), jnp.int32), jnp.zeros((1,), jnp.int32), dcfg
        ).dtype
        self._k_pool, self._v_pool = make_pools(
            n_layers, num_blocks, dcfg.n_kv_head, cfg.block_size,
            dcfg.head_dim, act_dtype,
        )
        # GSPMD-stable pools: when the params carry a NamedSharding (a
        # prepared / shard_for_inference model), commit the pools replicated
        # on the SAME mesh up front.  Fresh jnp.zeros are uncommitted
        # single-device arrays, and the first captured call would return
        # them re-committed onto the params' mesh — flipping the input
        # sharding for call 2 of the same bucket and silently recompiling
        # the one program the service exists to pin (caught by the
        # CompileWatcher; regression-pinned in test_serving)
        from jax.sharding import NamedSharding, PartitionSpec

        param_sharding = next(
            (
                leaf.sharding
                for leaf in jax.tree_util.tree_leaves((self._g, self._layers))
                if isinstance(getattr(leaf, "sharding", None), NamedSharding)
            ),
            None,
        )
        if param_sharding is not None:
            replicated = NamedSharding(param_sharding.mesh, PartitionSpec())
            self._k_pool = jax.device_put(self._k_pool, replicated)
            self._v_pool = jax.device_put(self._v_pool, replicated)
        self._pool_sharding = (
            replicated if param_sharding is not None else None
        )

        # pool rebuild hook for the retry-exhaustion recovery path: a fault
        # that fires MID-EXECUTION may have consumed the donated pools; the
        # requeue re-prefills every sequence anyway, so fresh zeroed pools
        # (same shape, dtype and sharding) are a complete replacement
        def _rebuild_pools():
            kp, vp = make_pools(
                n_layers, num_blocks, dcfg.n_kv_head, cfg.block_size,
                dcfg.head_dim, act_dtype,
            )
            if self._pool_sharding is not None:
                kp = jax.device_put(kp, self._pool_sharding)
                vp = jax.device_put(vp, self._pool_sharding)
            return kp, vp

        self._pool_factory = _rebuild_pools
        slots = cfg.max_slots
        self._tables = np.zeros((slots, blocks_per_slot), np.int32)
        self._positions = np.zeros(slots, np.int32)
        self._tokens = np.full(slots, cfg.pad_token_id, np.int32)
        # device-resident decode state (docs/serving.md §device-resident
        # decode): the arrays the multi-token (decode_steps > 1) captured
        # decode consumes.  The numpy mirrors above stay the source of
        # truth for admission math; _flush_device_state re-commits them
        # ONLY when the dirty flag says admission/eviction changed a slot —
        # a steady-state step feeds the previous call's outputs straight
        # back, uploading nothing.  (The n=1 path deliberately keeps the
        # legacy per-step uploads — see step().)
        self._dev_tables = None
        self._dev_positions = None
        self._dev_tokens = None
        self._state_dirty = True
        self._slot_req: list[Optional[Request]] = [None] * slots
        self._base_rng = jax.random.PRNGKey(cfg.rng_seed)
        self._rngs = jnp.stack(
            [jax.random.fold_in(self._base_rng, i) for i in range(slots)]
        )
        if self._pool_sharding is not None:
            # same stability argument as the pools: the sampled-decode
            # program returns the per-slot streams re-committed
            self._rngs = jax.device_put(self._rngs, self._pool_sharding)
        self._queue: deque[Request] = deque()
        self._next_rid = 0
        self.results: dict[int, Request] = {}
        if telemetry is None:
            from ..telemetry import current_telemetry

            telemetry = current_telemetry()
        self._hub = telemetry if (telemetry is not None and telemetry.enabled) else None
        self.watcher = CompileWatcher(hub=self._hub)
        # persistent AOT executable cache (docs/aot_cache.md): when armed
        # (explicit handle or the process-active cache), every bucket
        # program this service compiles is serialized, and a FRESH replica
        # of the same geometry+topology warms them all from disk right here
        # — spin-up collapses from per-bucket XLA compiles to disk reads.
        # Off (the default): both run_* calls below dispatch the plain jit
        # path byte-identically to the pre-cache service.
        if aot_cache is None:
            from ..native.aot_cache import current_aot_cache

            aot_cache = current_aot_cache()
        self._aot = None
        # /healthz readiness input: True once the bucket programs exist in
        # this process (warmed from the AOT store, or built by the first
        # admission) — a scrape-ready replica is one that can serve its
        # first token without a cold compile stall
        self._programs_warmed = False
        if aot_cache is not None and aot_cache.enabled:
            import jax as _jax

            from ..native.aot_cache import AOTServingPrograms, _leaf_aval

            service_fingerprint = {
                "family": type(self.spec.family).__name__,
                "cfg": repr(dcfg),
                "qbits": self._qbits,
                # a kernel-armed decode is a different program: flipping the
                # kernel — or forcing the lowering mode — must be a loud
                # serving-cache miss (docs/kernels.md).  Only the kernel the
                # decode path actually consumes rides the key: arming a
                # TRAINING kernel (collective_matmul/quantized_rs) changes
                # nothing about these programs and must not cold-compile a
                # warm replica.
                "kernels": (
                    "paged_attention:"
                    + ("interpret" if self._kernels.interpret else "mosaic")
                    if self._kernels is not None
                    else "none"
                ),
                "temperature": float(cfg.temperature),
                "block_size": cfg.block_size,
                "max_slots": cfg.max_slots,
                "prompt_bucket": cfg.prompt_bucket,
                "capacity": self.capacity,
                "pools": [_leaf_aval(self._k_pool), _leaf_aval(self._v_pool)],
                "params": [
                    _leaf_aval(leaf)
                    for leaf in _jax.tree_util.tree_leaves((self._g, self._layers))
                ],
            }
            if cfg.decode_steps != 1:
                # the n-token decode block is a different program with a
                # different OUTPUT ARITY (token block + advanced state):
                # entries stored by a service of another n must miss
                # loudly, never deserialize into a shape the caller can't
                # unpack.  Keyed CONDITIONALLY so default (n=1) services
                # keep the fingerprint — and the warm entries — they have
                # always had.
                service_fingerprint["decode_steps"] = int(cfg.decode_steps)
            self._aot = AOTServingPrograms(aot_cache, service_fingerprint)
            self._programs_warmed = self._aot.warm() > 0
        # fault tolerance (docs/serving.md §fault tolerance): everything
        # below is None / False when the journal is off — the hot path
        # pays one None-check per site, byte-identical to the pre-recovery
        # service (pinned by tests/test_serving_recovery.py)
        self._draining = False
        self._journal = None
        self._guard = preemption_guard
        if cfg.journal_dir:
            from .recovery import RequestJournal

            self._journal = RequestJournal(cfg.journal_dir, meta={
                # sampling determinism rides these: resume validates them
                # so a mismatched replica fails loudly instead of emitting
                # a silently different continuation
                "temperature": float(cfg.temperature),
                "rng_seed": int(cfg.rng_seed),
                "quantize_weights": cfg.quantize_weights,
                "decode_steps": int(cfg.decode_steps),
            })
            if self._guard is None:
                from ..resilience.preemption import PreemptionGuard

                # sticky-flag SIGTERM/SIGINT guard (resilience pillar 2):
                # step() polls it and drains at its own safe point.
                # install() is a no-op off the main thread — a journaled
                # service on a worker thread still journals, it just
                # relies on an explicit drain() call
                self._guard = PreemptionGuard()
            if not self._guard.installed:
                self._guard.install()
        # deterministic fault injection (resilience pillar 4): armed only
        # when $ACCELERATE_FAULT_PLAN names serving verbs — production
        # runs carry a None here
        from ..resilience.inject import FaultInjector

        self._injector = FaultInjector.from_spec(None)
        self.stats = {
            "steps": 0,
            "admitted": 0,
            "completed": 0,
            "occupancy_sum": 0.0,
            "queue_peak": 0,
            # dispatch-overhead accounting (docs/telemetry.md §serving):
            # host_syncs counts EVERY blocking device→host read (prefill
            # first tokens + decode blocks); decode_syncs counts PER-SLOT
            # sync exposures (each decode sync, once per active slot) so
            # host_syncs_per_token = decode_syncs/decode_tokens reads 1.0
            # on the classic path and ~1/n on n-token blocks independent
            # of batch size; h2d_uploads counts host→device state
            # re-commits (0 in steady state)
            "host_syncs": 0,
            "decode_syncs": 0,
            "decode_tokens": 0,
            "h2d_uploads": 0,
            # fault-tolerance accounting (docs/serving.md §fault
            # tolerance): shed completions, recovered (re-prefilled)
            # admissions, retry attempts, exhaustion requeues, pool
            # rebuilds after a consumed-donation fault, and metrics-scrape
            # snapshot retries that ran the cap dry
            "shed": 0,
            "recovered": 0,
            "decode_retries": 0,
            "requeued": 0,
            "pool_rebuilds": 0,
            "metrics_snapshot_retry_exhausted": 0,
        }
        # sliding (ttft_ms, tpot_ms) window behind metrics() — the live
        # endpoint's SLO percentiles must reflect *recent* traffic, not the
        # whole run
        self._latency_window: deque = deque(maxlen=max(1, cfg.metrics_window))
        # native Prometheus histograms alongside the window percentiles:
        # cumulative _bucket series a server-side histogram_quantile() can
        # rate() over any range and merge across replicas — the window
        # gauges cannot be aggregated (docs/telemetry.md §endpoint)
        from ..telemetry.metrics import LatencyHistogram

        self._ttft_hist = LatencyHistogram()
        self._tpot_hist = LatencyHistogram()
        if self._hub is not None:
            # the hub's metrics endpoint (telemetry/metrics.py) scrapes any
            # provider registered here; latest-constructed service wins the
            # "serving" name (a MetricsServer.add_service call attaches
            # additional services explicitly).  Registered through a
            # weakref: the hub is process-lived, and a strong ref from it
            # would pin this service's params + KV pools after the caller
            # drops it — a dropped service renders as no gauges, silently
            import weakref

            service_ref = weakref.ref(self)

            def _serving_metrics():
                service = service_ref()
                return service.metrics() if service is not None else {}

            self._hub.register_metrics_provider("serving", _serving_metrics)

            def _serving_health():
                service = service_ref()
                return service.health() if service is not None else {}

            # /healthz rides the same endpoint (telemetry/metrics.py): a
            # dropped service renders as an absent section, never a stale
            # "ready"
            self._hub.register_health_provider("serving", _serving_health)

    # -- request intake ------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int,
               eos_token_id: Optional[int] = None,
               arrival_t: Optional[float] = None,
               deadline_ms: Optional[float] = None) -> int:
        """Queue one request; returns its id.  Validation happens here so a
        request that can NEVER be admitted fails loudly at submit time
        instead of deadlocking the queue.

        ``arrival_t`` (a ``time.perf_counter()`` timestamp) backdates the
        TTFT clock to when the request actually ARRIVED rather than when
        the driver got around to calling submit — an open-loop load
        generator must pass it or its p99 TTFT silently excludes the
        queueing delay it exists to measure (coordinated omission).

        ``deadline_ms`` bounds the request's queueing age: a request still
        queued past it is SHED at admission time (a ``state="shed"``
        completion record, never prefilled).  With
        ``ServingConfig(max_queue_depth=...)`` set, a submit against a full
        queue raises :class:`~.recovery.QueueFullError` carrying a
        TPOT-derived ``retry_after_ms`` — bounded host memory under
        overload instead of unbounded queue growth."""
        prompt = np.asarray(
            prompt.data if hasattr(prompt, "data") else prompt, np.int32
        ).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        p_len = int(prompt.size)
        if p_len + max_new_tokens > self.capacity:
            raise ValueError(
                f"prompt ({p_len}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"the service's per-request capacity ({self.capacity})"
            )
        blen = bucket_length(p_len, self.config.prompt_bucket, cap=self.capacity)
        needed = blocks_for_request(
            p_len, max_new_tokens, blen, self.config.block_size,
            decode_steps=self.config.decode_steps,
            blocks_per_slot=self.pool.blocks_per_slot,
        )
        if needed > self.pool.usable_blocks:
            raise ValueError(
                f"request needs {needed} blocks but the pool only has "
                f"{self.pool.usable_blocks}: raise num_blocks"
            )
        if self._draining or (
            self.config.max_queue_depth is not None
            and len(self._queue) >= self.config.max_queue_depth
        ):
            # bounded queueing / drain back-pressure: reject with a
            # retry-after hint — the caller's load balancer re-routes or
            # re-submits, and host memory stays bounded under overload
            from .recovery import QueueFullError

            reason = "draining" if self._draining else "queue_full"
            retry_after = self._retry_after_ms()
            self.stats["shed"] += 1
            from ..telemetry import flightrec

            flightrec.record(
                "serving_shed", reason=reason, queue_depth=len(self._queue),
            )
            if self._hub is not None:
                self._hub.record_serving({
                    "event": "shed", "reason": reason,
                    "queue_depth": len(self._queue),
                    "retry_after_ms": retry_after,
                })
            raise QueueFullError(
                f"submit rejected ({reason}): queue depth "
                f"{len(self._queue)}; retry in ~{retry_after:.0f} ms",
                retry_after_ms=retry_after,
            )
        rid = self._next_rid
        self._next_rid += 1
        req = Request(
            rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
            eos_token_id=(
                eos_token_id if eos_token_id is not None
                else self.config.eos_token_id
            ),
            bucket_len=blen, blocks_needed=needed,
            submitted_t=arrival_t if arrival_t is not None else time.perf_counter(),
            deadline_ms=deadline_ms,
        )
        if self._journal is not None:
            self._journal.log_submit(
                rid, prompt, max_new_tokens, req.eos_token_id,
                deadline_ms=deadline_ms,
            )
        self._queue.append(req)
        self.stats["queue_peak"] = max(self.stats["queue_peak"], len(self._queue))
        return rid

    # -- scheduling ----------------------------------------------------------
    @property
    def active_slots(self) -> int:
        return sum(r is not None for r in self._slot_req)

    @property
    def pool_free_frac(self) -> float:
        """Free fraction of the usable KV block pool — the back-pressure
        gauge the step records, the fleet signal and the metrics endpoint
        all report (one definition, three consumers)."""
        return self.pool.free_blocks / max(1, self.pool.usable_blocks)

    @property
    def has_work(self) -> bool:
        return bool(self._queue) or self.active_slots > 0

    def _free_slot(self) -> Optional[int]:
        for i, r in enumerate(self._slot_req):
            if r is None:
                return i
        return None

    def _admit(self) -> list[Request]:
        """FIFO head-of-line admission: the oldest queued request is always
        next (no shorter request overtakes it — predictable tail latency),
        gated on a free slot AND its block reservation fitting the pool."""
        import jax
        import jax.numpy as jnp

        from .engine import run_prefill

        admitted = []
        while self._queue:
            req = self._queue[0]
            if req.deadline_ms is not None and (
                (time.perf_counter() - req.submitted_t) * 1e3 > req.deadline_ms
            ):
                # expired while queued: shed BEFORE the slot gate — an
                # abandoned request must neither burn a prefill nor block
                # the head of the line
                self._queue.popleft()
                self._shed(req, "deadline")
                continue
            slot = self._free_slot()
            if slot is None or not self.pool.can_alloc(req.blocks_needed):
                break
            self._queue.popleft()
            if req.tokens:
                # journal-recovered (or retry-requeued) request: rebuild
                # its KV by teacher-forced re-prefill over the emitted
                # prefix (docs/serving.md §fault tolerance)
                self._admit_recovering(req, slot)
                admitted.append(req)
                continue
            row = self.pool.alloc(slot, req.blocks_needed)
            table_row = np.zeros(self.pool.blocks_per_slot, np.int32)
            table_row[: len(row)] = row
            padded_ids = np.full((1, req.bucket_len), self.config.pad_token_id, np.int32)
            padded_ids[0, : req.prompt_len] = req.prompt
            self._k_pool, self._v_pool, tok, rng_out = run_prefill(
                self._k_pool, self._v_pool, self._g, self._layers,
                jnp.asarray(padded_ids), jnp.asarray(table_row),
                jnp.asarray(req.prompt_len, jnp.int32),
                jax.random.fold_in(self._base_rng, 2 * req.rid + 1),
                family=self.spec.family, cfg=self.spec.cfg,
                qbits=self._qbits,
                temperature=float(self.config.temperature),
                watcher=self.watcher, aot=self._aot,
            )
            self.stats["host_syncs"] += 1
            self._programs_warmed = True
            first = int(tok)
            req.first_token_t = time.perf_counter()
            req.tokens.append(first)
            req.state = "running"
            self.stats["admitted"] += 1
            if self._journal is not None:
                self._journal.log_tokens(req.rid, [first])
            admitted.append(req)
            if req.max_new_tokens == 1 or (
                req.eos_token_id is not None and first == req.eos_token_id
            ):
                # one-token request (or instant stop): never occupies the
                # decode batch — blocks go straight back
                self.pool.free_slot(slot)
                self._finish(req)
                continue
            self._slot_req[slot] = req
            self._tables[slot] = table_row
            self._positions[slot] = req.prompt_len
            self._tokens[slot] = first
            self._state_dirty = True  # new slot row: re-commit before decode
            self._rngs = self._rngs.at[slot].set(rng_out)
        return admitted

    def _evict(self, slot: int) -> None:
        """Free the slot the moment its request finishes: table back to the
        trash block, blocks back to the pool — next step's admission can
        hand them to a queued request."""
        self.pool.free_slot(slot)
        self._slot_req[slot] = None
        self._tables[slot] = 0
        self._positions[slot] = 0
        self._tokens[slot] = self.config.pad_token_id
        # the device copy of this slot now points at freed blocks (and, at
        # decode_steps>1, overran positions) — re-commit before next decode
        self._state_dirty = True

    def pop_result(self, rid: int) -> Optional[Request]:
        """Take (and drop) one finished request — the streaming-consumer
        API; ``step()``'s return value is the push-style equivalent."""
        return self.results.pop(rid, None)

    def _finish(self, req: Request) -> None:
        req.done_t = time.perf_counter()
        req.state = "done"
        self.results[req.rid] = req
        while len(self.results) > self.config.max_retained_results:
            self.results.pop(next(iter(self.results)))
        if self._journal is not None:
            self._journal.log_complete(req.rid)
        self.stats["completed"] += 1
        self._latency_window.append((req.ttft_ms, req.tpot_ms))
        if req.ttft_ms is not None:
            self._ttft_hist.observe(req.ttft_ms)
        if req.tpot_ms is not None:
            self._tpot_hist.observe(req.tpot_ms)
        if self._hub is not None:
            self._hub.record_serving({
                "event": "complete", "rid": req.rid,
                "prompt_len": req.prompt_len,
                "new_tokens": len(req.tokens),
                "ttft_ms": req.ttft_ms,
                "tpot_ms": req.tpot_ms,
            })

    # -- fault tolerance -----------------------------------------------------
    def _shed(self, req: Request, reason: str) -> None:
        """Complete a request WITHOUT serving it: ``state="shed"``, a
        completion record the caller can poll, a journal entry so a
        recovering replica never resurrects it — and nothing in the
        latency window, which describes served traffic only."""
        req.done_t = time.perf_counter()
        req.state = "shed"
        self.results[req.rid] = req
        while len(self.results) > self.config.max_retained_results:
            self.results.pop(next(iter(self.results)))
        self.stats["shed"] += 1
        if self._journal is not None:
            self._journal.log_shed(req.rid, reason)
        from ..telemetry import flightrec

        flightrec.record("serving_shed", rid=req.rid, reason=reason)
        if self._hub is not None:
            self._hub.record_serving({
                "event": "shed", "rid": req.rid, "reason": reason,
                "queued_ms": (req.done_t - req.submitted_t) * 1e3,
            })

    def _retry_after_ms(self) -> float:
        """Back-pressure hint for rejected submits: roughly one decode
        block at the service's recent median TPOT — when capacity next
        frees up, not a magic constant.  Falls back to 100 ms before any
        completion has been observed."""
        tpots = sorted(
            p for _, p in list(self._latency_window) if p is not None
        )
        if not tpots:
            return 100.0
        return max(1.0, tpots[len(tpots) // 2] * self.config.decode_steps)

    def _queue_recovery(self, reqs: list, front: bool = False) -> None:
        """(Re)queue requests carrying an emitted prefix: recompute each
        one's bucket and block reservation for the RECOVERY sequence
        (prompt + prefix-minus-last re-prefilled, the last journaled token
        re-fed as the next decode input) and restore FIFO order."""
        reqs = sorted(reqs, key=lambda r: r.rid)
        for req in reqs:
            k = len(req.tokens)
            seq_len = req.prompt_len + max(0, k - 1)
            remaining = req.max_new_tokens - k + 1 if k else req.max_new_tokens
            req.bucket_len = bucket_length(
                seq_len, self.config.prompt_bucket, cap=self.capacity
            )
            req.blocks_needed = blocks_for_request(
                seq_len, remaining, req.bucket_len, self.config.block_size,
                decode_steps=self.config.decode_steps,
                blocks_per_slot=self.pool.blocks_per_slot,
            )
            req.state = "queued"
        if front:
            self._queue.extendleft(reversed(reqs))
        else:
            self._queue.extend(reqs)
        self.stats["queue_peak"] = max(self.stats["queue_peak"], len(self._queue))

    def _admit_recovering(self, req: Request, slot: int) -> None:
        """Teacher-forced re-prefill: rebuild the slot's KV by running the
        ordinary bucketed prefill over ``prompt + tokens[:-1]`` — the same
        captured program family the service pins, so a warm-AOT replica
        recovers with zero compiles — then feed the LAST journaled token
        as the next decode input at its true position.  The prefill's own
        sampled token is discarded (the journal is the source of truth),
        and the per-request RNG stream is re-advanced so a sampled
        continuation is bitwise-identical to the uninterrupted run: the
        stream consumes one split per sampled token, so handing prefill
        the stream at position ``k-1`` lands its internal split exactly at
        ``k`` (recovery.advance_rng)."""
        import jax
        import jax.numpy as jnp

        from .engine import run_prefill
        from .recovery import advance_rng

        k = len(req.tokens)
        seq = np.concatenate(
            [req.prompt, np.asarray(req.tokens[:-1], np.int32)]
        )
        seq_len = int(seq.size)
        row = self.pool.alloc(slot, req.blocks_needed)
        table_row = np.zeros(self.pool.blocks_per_slot, np.int32)
        table_row[: len(row)] = row
        padded_ids = np.full((1, req.bucket_len), self.config.pad_token_id, np.int32)
        padded_ids[0, :seq_len] = seq
        rng = jax.random.fold_in(self._base_rng, 2 * req.rid + 1)
        if float(self.config.temperature) > 0.0:
            rng = advance_rng(rng, k - 1)
        self._k_pool, self._v_pool, tok, rng_out = run_prefill(
            self._k_pool, self._v_pool, self._g, self._layers,
            jnp.asarray(padded_ids), jnp.asarray(table_row),
            jnp.asarray(seq_len, jnp.int32), rng,
            family=self.spec.family, cfg=self.spec.cfg,
            qbits=self._qbits,
            temperature=float(self.config.temperature),
            watcher=self.watcher, aot=self._aot,
        )
        self.stats["host_syncs"] += 1
        int(tok)  # block for the prefill; the sample itself is teacher-forced away
        self._programs_warmed = True
        req.state = "running"
        if req.first_token_t is None:
            # resumed from a dead replica's journal: the recovered TTFT
            # clock starts at resubmission (perf_counter doesn't survive
            # a process boundary)
            req.first_token_t = time.perf_counter()
        self.stats["admitted"] += 1
        self.stats["recovered"] += 1
        from ..telemetry import flightrec

        flightrec.record(
            "serving_recovered", rid=req.rid, prefix_tokens=k,
        )
        if self._hub is not None:
            self._hub.record_serving_recovery({
                "event": "recovered_admit", "rid": req.rid,
                "prefix_tokens": k, "seq_len": seq_len,
            })
        last = int(req.tokens[-1])
        if len(req.tokens) >= req.max_new_tokens or (
            req.eos_token_id is not None and last == req.eos_token_id
        ):
            # the journaled prefix already satisfied the budget/stop: the
            # request is complete — nothing left to decode
            self.pool.free_slot(slot)
            self._finish(req)
            return
        self._slot_req[slot] = req
        self._tables[slot] = table_row
        self._positions[slot] = seq_len
        self._tokens[slot] = last
        self._state_dirty = True
        self._rngs = self._rngs.at[slot].set(rng_out)

    def _requeue_active(self, reason: str, error=None) -> None:
        """Decode-retry exhaustion path: evict every active slot and send
        its request back through journal-style recovery (the emitted
        prefixes live in the host Request objects) instead of crashing the
        service.  A mid-execution fault may have consumed the donated
        pools — rebuild them; the re-prefills repopulate everything."""
        reqs = [r for r in self._slot_req if r is not None]
        for slot, r in enumerate(self._slot_req):
            if r is not None:
                self._evict(slot)
        if self._k_pool.is_deleted():
            self._k_pool, self._v_pool = self._pool_factory()
            self.stats["pool_rebuilds"] += 1
        self._queue_recovery(reqs, front=True)
        self.stats["requeued"] += len(reqs)
        from ..telemetry import flightrec

        flightrec.record(
            "serving_requeue", count=len(reqs), reason=reason,
        )
        if self._hub is not None:
            self._hub.record_serving_recovery({
                "event": "requeue", "reason": reason,
                "rids": [r.rid for r in reqs],
                "error": None if error is None else f"{type(error).__name__}: {error}"[:300],
            })

    def drain(self, reason: Optional[str] = None) -> list[int]:
        """Preemption drain: stop admission, finalize the journal, emit
        ``kind="serving_recovery"`` records.  In-flight and queued
        requests stay OPEN in the journal — a fresh replica pointed at the
        same ``journal_dir`` (``resume_from_journal``) completes every one
        of them from its emitted prefix, with warm AOT programs.
        Idempotent; returns the open rids."""
        open_rids = sorted(
            [r.rid for r in self._queue]
            + [r.rid for r in self._slot_req if r is not None]
        )
        if self._draining:
            return open_rids
        self._draining = True
        if reason is None:
            reason = (
                self._guard.signal_name if self._guard is not None else None
            ) or "drain"
        from ..telemetry import flightrec

        flightrec.record(
            "serving_drain", reason=reason, open=len(open_rids),
        )
        if self._hub is not None:
            self._hub.record_serving_recovery({
                "event": "drain", "reason": reason, "open_rids": open_rids,
            })
        if self._journal is not None:
            self._journal.log_drain(open_rids)
            self._journal.close()
        return open_rids

    @property
    def draining(self) -> bool:
        return self._draining

    def resume_from_journal(self, journal_dir: Optional[str] = None) -> list[int]:
        """Resubmit every open request from a journal (default: this
        service's own ``journal_dir``) under its ORIGINAL rid — the rid
        seeds the per-request RNG stream (``fold_in(base, 2*rid+1)``), so
        pinning it is what makes the recovered continuation deterministic.
        Sampling-config mismatches against the journal's metadata fail
        loudly.  Returns the resumed rids (FIFO order preserved)."""
        from .recovery import replay_journal

        path = journal_dir or self.config.journal_dir
        if not path:
            raise ValueError(
                "resume_from_journal needs a journal_dir (argument, "
                "ServingConfig, or $ACCELERATE_SERVING_JOURNAL)"
            )
        state = replay_journal(path)
        for key, ours in (
            ("temperature", float(self.config.temperature)),
            ("rng_seed", int(self.config.rng_seed)),
            ("quantize_weights", self.config.quantize_weights),
        ):
            theirs = state.meta.get(key, ours)
            if theirs != ours:
                raise ValueError(
                    f"journal was written by a service with {key}={theirs!r} "
                    f"but this replica has {key}={ours!r}: recovered "
                    "continuations would silently diverge"
                )
        reqs = []
        for entry in state.open_requests:
            req = Request(
                rid=entry.rid, prompt=entry.prompt,
                max_new_tokens=entry.max_new_tokens,
                eos_token_id=entry.eos_token_id,
                bucket_len=0, blocks_needed=0,  # recomputed by _queue_recovery
                tokens=list(entry.tokens),
                submitted_t=time.perf_counter(),
            )
            reqs.append(req)
        rids = [r.rid for r in reqs]
        if rids:
            self._next_rid = max(self._next_rid, max(rids) + 1)
            own_path = self._journal.path if self._journal is not None else None
            from .recovery import _journal_path

            if self._journal is not None and own_path != _journal_path(path):
                # resuming from ANOTHER journal: re-log into ours so this
                # replica's log is self-contained (same-dir resume skips —
                # the records are already in the file we append to)
                for req in reqs:
                    self._journal.log_submit(
                        req.rid, req.prompt, req.max_new_tokens,
                        req.eos_token_id, tokens=req.tokens,
                    )
        self._queue_recovery(reqs, front=False)
        from ..telemetry import flightrec

        flightrec.record("serving_resume", count=len(rids))
        if self._hub is not None and rids:
            self._hub.record_serving_recovery({
                "event": "resume", "count": len(rids), "rids": rids,
            })
        return rids

    def health(self) -> dict:
        """Readiness + liveness snapshot for the ``/healthz`` probe
        (telemetry/metrics.py): ready = programs warmed ∧ pool allocated ∧
        not draining.  Pure host reads — safe from the endpoint thread."""
        pool_allocated = self.pool.usable_blocks > 0
        return {
            "ready": bool(
                self._programs_warmed and pool_allocated and not self._draining
            ),
            "live": True,
            "programs_warmed": self._programs_warmed,
            "pool_allocated": pool_allocated,
            "draining": self._draining,
            "slots_active": self.active_slots,
            "queue_depth": len(self._queue),
        }

    def _flush_device_state(self) -> None:
        """Re-commit the host mirrors to the device (the ``decode_steps >
        1`` path) — ONLY when admission or eviction changed a slot since
        the last decode.  Steady state (every slot mid-sequence) feeds the
        previous call's outputs straight back: zero host→device transfers
        per step, pinned by the ``jax.transfer_guard`` regression test in
        tests/test_serving.py."""
        if not self._state_dirty and self._dev_tables is not None:
            return
        import jax
        import jax.numpy as jnp

        arrays = (
            jnp.asarray(self._tables),
            jnp.asarray(self._positions),
            jnp.asarray(self._tokens),
        )
        if self._pool_sharding is not None:
            # same stability argument as the pools/rng streams: the decode
            # program returns this state re-committed on the params' mesh,
            # and an uncommitted re-upload would flip the input sharding
            arrays = tuple(
                jax.device_put(a, self._pool_sharding) for a in arrays
            )
        self._dev_tables, self._dev_positions, self._dev_tokens = arrays
        self._state_dirty = False
        self.stats["h2d_uploads"] += 1

    def step(self) -> list[Request]:
        """One engine iteration (admit → decode a ``decode_steps`` token
        block → evict); returns the requests that completed during it."""
        from .engine import run_decode, run_decode_n

        from ..telemetry import flightrec

        n = self.config.decode_steps
        if self._injector is not None:
            # deterministic preemption rehearsal (resilience pillar 4):
            # serving_sigterm:step=N delivers a real SIGTERM before engine
            # step N — the guard's sticky flag is then read right below
            self._injector.maybe_serving_sigterm(self.stats["steps"])
        if not self._draining and self._guard is not None and (
            self._guard.triggered or self._guard.deadline_reached()
        ):
            self.drain()
        if self._draining:
            # admission stopped; in-flight requests stay open in the
            # journal for the successor replica
            return []
        admitted = self._admit()
        if admitted:
            # flight event: admissions (docs/telemetry.md §flight recorder)
            # — in a hang postmortem the last admit/decode_window pair shows
            # whether the engine died admitting or mid-block
            flightrec.record(
                "serving_admit",
                count=len(admitted), queue_depth=len(self._queue),
            )
        completed = [r for r in admitted if r.state == "done"]
        slot_evictions = 0
        emitted = 0
        active = [i for i, r in enumerate(self._slot_req) if r is not None]
        uploads_before = self.stats["h2d_uploads"]
        if active:
            flightrec.record(
                "decode_window",
                step=self.stats["steps"], active=len(active), decode_steps=n,
            )
            if n > 1:
                self._flush_device_state()
            common = dict(
                family=self.spec.family, cfg=self.spec.cfg,
                qbits=self._qbits,
                temperature=float(self.config.temperature),
                watcher=self.watcher, aot=self._aot,
                kernels=self._kernels,
            )
            # transient-fault retry (docs/serving.md §fault tolerance):
            # the injected/classified-transient fault fires BEFORE the
            # dispatch consumes the donated pools, so a retry re-dispatches
            # the SAME compiled program (zero extra compiles).  A real
            # mid-execution fault that consumed the pools skips straight
            # to eviction-and-requeue, whose re-prefills rebuild all KV.
            dispatched = False
            attempt = 0
            while True:
                try:
                    if self._injector is not None:
                        self._injector.maybe_decode_fault(self.stats["steps"])
                    if n == 1:
                        # legacy single-token dispatch, byte-identical to the
                        # pre-multi-token service INCLUDING the per-step mirror
                        # uploads: the program must see the exact avals it always
                        # has (fresh uncommitted int arrays), because inputs
                        # committed with a NamedSharding lower to a DIFFERENT HLO
                        # module — an independently compiled binary whose near-tie
                        # argmaxes can drift 1 ulp from generate()'s programs and
                        # break the bitwise parity contract (caught live on a
                        # prepared single-device run; see engine._decode_jit for
                        # the same argument against a length-1 loop variant).  The
                        # uploads are three tiny int arrays; the per-token cost
                        # that matters — the blocking host sync — is unchanged
                        # here and amortized n-fold on the n>1 path below.
                        import jax.numpy as jnp

                        (self._k_pool, self._v_pool, nxt, self._rngs) = run_decode(
                            self._k_pool, self._v_pool, self._g, self._layers,
                            jnp.asarray(self._tables), jnp.asarray(self._positions),
                            jnp.asarray(self._tokens), self._rngs, **common,
                        )
                        self.stats["h2d_uploads"] += 1
                        self._state_dirty = True  # mirrors stay the source of truth
                        tok_block = nxt  # reshaped host-side below
                    else:
                        (self._k_pool, self._v_pool, tok_block, self._dev_positions,
                         self._dev_tokens, self._rngs) = run_decode_n(
                            self._k_pool, self._v_pool, self._g, self._layers,
                            self._dev_tables, self._dev_positions, self._dev_tokens,
                            self._rngs, decode_steps=n, **common,
                        )
                    dispatched = True
                    break
                except Exception as exc:
                    from ..resilience.backend import backoff_delay
                    from ..resilience.retry import classify_failure

                    if classify_failure(exc) != "transient":
                        raise  # user/program errors propagate unchanged
                    pools_ok = not self._k_pool.is_deleted()
                    if attempt < self.config.max_decode_retries and pools_ok:
                        attempt += 1
                        self.stats["decode_retries"] += 1
                        delay = backoff_delay(
                            attempt, self.config.retry_backoff_s, cap_s=5.0
                        )
                        flightrec.record(
                            "serving_retry", step=self.stats["steps"],
                            attempt=attempt,
                        )
                        if self._hub is not None:
                            self._hub.record_serving_recovery({
                                "event": "retry", "step": self.stats["steps"],
                                "attempt": attempt, "wait_ms": delay * 1e3,
                                "error": f"{type(exc).__name__}: {exc}"[:300],
                            })
                        time.sleep(delay)
                        continue
                    self._requeue_active(
                        "retry_exhausted" if pools_ok else "pools_consumed",
                        error=exc,
                    )
                    break
            if dispatched:
                # THE host sync of the hot loop: one blocking read per
                # n-token block, weighted per active slot for the
                # per-token ratio
                self.stats["host_syncs"] += 1
                self.stats["decode_syncs"] += len(active)
                block_host = np.asarray(tok_block).reshape(
                    self.config.max_slots, n
                )
                for slot in active:
                    req = self._slot_req[slot]
                    emitted_before = len(req.tokens)
                    for j in range(n):
                        tok = int(block_host[slot, j])
                        req.tokens.append(tok)
                        self._positions[slot] += 1
                        self._tokens[slot] = tok
                        emitted += 1
                        if len(req.tokens) >= req.max_new_tokens or (
                            req.eos_token_id is not None
                            and tok == req.eos_token_id
                        ):
                            # tokens past the stop are DISCARDED (never appended
                            # — the block's tail is pad as far as any consumer
                            # can see), and eviction lands at the block
                            # boundary; greedy output stays identical to
                            # generate() at every n
                            if self._journal is not None:
                                self._journal.log_tokens(
                                    req.rid, req.tokens[emitted_before:]
                                )
                            self._evict(slot)
                            self._finish(req)
                            completed.append(req)
                            slot_evictions += 1
                            break
                    else:
                        if self._journal is not None:
                            self._journal.log_tokens(
                                req.rid, req.tokens[emitted_before:]
                            )
        self.stats["decode_tokens"] += emitted
        self.stats["steps"] += 1
        occupancy = len(active) / self.config.max_slots
        self.stats["occupancy_sum"] += occupancy
        if self._hub is not None:
            self._hub.record_serving({
                "event": "step", "step": self.stats["steps"],
                "occupancy": occupancy, "active": len(active),
                "queue_depth": len(self._queue),
                # pool back-pressure rides the step record too: the fleet
                # autopilot's serving signal (docs/elastic.md §autopilot)
                # reads queue depth/occupancy from here, and a full pool is
                # the "queue deep because blocks, not slots" disambiguator
                "pool_free_frac": self.pool_free_frac,
                "admitted": len(admitted),
                # true slot evictions only — a one-token request completing
                # inside _admit never held a decode slot and is visible in
                # "completed", not here (slot-churn consumers cross-check
                # evicted against occupancy)
                "evicted": slot_evictions,
                "completed": len(completed),
                # device-resident hot-loop accounting (docs/telemetry.md):
                # block size, tokens actually emitted to requests this step
                # (overrun tokens past a stop are discarded, not emitted),
                # and whether this step re-committed host state
                "decode_steps": n,
                "emitted": emitted,
                "h2d_upload": self.stats["h2d_uploads"] > uploads_before,
            })
        return completed

    def run(self, max_steps: Optional[int] = None) -> dict[int, Request]:
        """Drive ``step()`` until the queue and every slot drain (or
        ``max_steps``); returns ``{rid: Request}`` for everything finished."""
        steps = 0
        while self.has_work and not self._draining:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return dict(self.results)

    # -- accounting ----------------------------------------------------------
    def fleet_signal(self) -> dict:
        """The serving half of the fleet autopilot's input (docs/elastic.md
        §autopilot): instantaneous queue depth, occupancy and pool
        back-pressure — pure host reads, safe from any thread.  The same
        numbers ride every ``kind="serving"`` step record, which is where a
        training-colocated autopilot actually samples them (the records are
        rank-retained; this accessor is the direct/standalone form)."""
        return {
            "queue_depth": len(self._queue),
            "occupancy": self.active_slots / self.config.max_slots,
            "pool_free_frac": self.pool_free_frac,
        }

    def metrics(self) -> dict:
        """Live scrape snapshot (the metrics endpoint and tests share it):
        instantaneous occupancy/queue/pool gauges plus TTFT/TPOT p50/p99
        over the sliding completion window.  Pure host reads — safe to call
        from the endpoint's thread while the service is stepping."""
        # the stepping thread appends completions concurrently, and a deque
        # raises on mutation-during-iteration — retry the snapshot (capped:
        # a scrape must never spin against a hot completion stream), and
        # surface cap exhaustion as a flight event + counter so a
        # percentile-less scrape is diagnosable, not silent
        window: list = []
        for _ in range(_METRICS_SNAPSHOT_RETRIES):
            try:
                window = list(self._latency_window)
                break
            except RuntimeError:
                continue
        else:
            self.stats["metrics_snapshot_retry_exhausted"] += 1
            from ..telemetry import flightrec

            flightrec.record(
                "metrics_snapshot_retry_exhausted",
                retries=_METRICS_SNAPSHOT_RETRIES,
            )
        out = {
            "occupancy": self.active_slots / self.config.max_slots,
            "slots_active": self.active_slots,
            "slots_total": self.config.max_slots,
            "queue_depth": len(self._queue),
            "queue_peak": self.stats["queue_peak"],
            "block_pool_free_frac": self.pool_free_frac,
            "steps_total": self.stats["steps"],
            "admitted_total": self.stats["admitted"],
            "completed_total": self.stats["completed"],
            "recompile_events_total": self.recompile_events,
            # device-resident decode counters (docs/telemetry.md §serving):
            # syncs/token is the dispatch-overhead gauge — 1.0 on the
            # classic path, ~1/n with an n-token block; h2d uploads stay
            # flat while the batch is steady
            "decode_steps": self.config.decode_steps,
            "decode_tokens_total": self.stats["decode_tokens"],
            "host_syncs_total": self.stats["host_syncs"],
            "h2d_uploads_total": self.stats["h2d_uploads"],
            "host_syncs_per_token": round(self.host_syncs_per_token, 4),
            "latency_window": len(window),
            # fault-tolerance counters (docs/serving.md §fault tolerance)
            "shed_total": self.stats["shed"],
            "recovered_total": self.stats["recovered"],
            "decode_retries_total": self.stats["decode_retries"],
            "requeued_total": self.stats["requeued"],
            "metrics_snapshot_retry_exhausted_total": self.stats[
                "metrics_snapshot_retry_exhausted"
            ],
            "draining": self._draining,
            # native histograms (cumulative over the service lifetime);
            # the p50/p99 gauges below stay for human eyeballs — dashboards
            # should quantile() the _bucket series instead
            "ttft_ms": self._ttft_hist,
            "tpot_ms": self._tpot_hist,
        }
        ttfts = sorted(t for t, _ in window if t is not None)
        tpots = sorted(p for _, p in window if p is not None)
        for name, values in (("ttft_ms", ttfts), ("tpot_ms", tpots)):
            if values:
                out[f"{name}_p50"] = values[int(0.50 * (len(values) - 1))]
                out[f"{name}_p99"] = values[int(0.99 * (len(values) - 1))]
        return out

    @property
    def mean_batch_occupancy(self) -> float:
        return self.stats["occupancy_sum"] / max(1, self.stats["steps"])

    @property
    def host_syncs_per_token(self) -> float:
        """Blocking device→host syncs a sequence experiences per emitted
        DECODE token — the dispatch-overhead gauge the bench A/B and
        serve-smoke assert on: exactly 1.0 on the classic per-token path,
        ~1/n with an n-token device-resident block (slightly above 1/n
        when stops discard overrun tokens).  Each decode sync counts once
        per active slot, so the ratio is batch-size independent; prefill's
        per-request first-token sync is per-request, not per-token, so it
        rides ``stats["host_syncs"]`` but not this ratio."""
        return self.stats["decode_syncs"] / max(1, self.stats["decode_tokens"])

    @property
    def recompile_events(self) -> int:
        """Post-warmup program builds — 0 is the steady-state contract."""
        return self.watcher.recompile_events
