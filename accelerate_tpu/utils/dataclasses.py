"""Config & plugin dataclasses (the L5 layer).

Behavioural counterpart of ``/root/reference/src/accelerate/utils/dataclasses.py``
(2620 LoC).  The big inversion versus the reference: torch's ten
``DistributedType`` backends collapse on TPU into *mesh-axis layouts of one SPMD
program*, so plugins here resolve to mesh axis sizes + sharding rules instead of
wrapper-module configs.  Env-var fallbacks in ``__post_init__`` keep the
launcher↔child env protocol (reference dataclasses.py:1635-1727).
"""

from __future__ import annotations

import enum
import os
import warnings
from dataclasses import dataclass, field
from datetime import timedelta
from typing import Any, Callable, Iterable, Optional

from .environment import parse_flag_from_env, str_to_bool


class BaseEnum(str, enum.Enum):
    def __str__(self) -> str:  # YAML/env round-trip friendly
        return self.value

    @classmethod
    def list(cls) -> list[str]:
        return [e.value for e in cls]


class DistributedType(BaseEnum):
    """How this process participates in distributed execution.

    Reference enum: dataclasses.py:552.  The torch backends (MULTI_GPU,
    DEEPSPEED, MEGATRON_LM, ...) have no meaning on a PJRT stack; what remains
    is NO (single process, possibly many local devices under SPMD) vs
    MULTI_HOST (jax.distributed across hosts), with the parallelism *strategy*
    expressed by `ParallelismConfig` rather than by backend.
    """

    NO = "NO"
    TPU = "TPU"  # single-host SPMD over local TPU devices
    MULTI_HOST = "MULTI_HOST"  # jax.distributed over DCN, SPMD within/across slices


class PrecisionType(BaseEnum):
    NO = "no"
    FP8 = "fp8"
    FP16 = "fp16"
    BF16 = "bf16"


class RNGType(BaseEnum):
    JAX = "jax"
    NUMPY = "numpy"
    PYTHON = "python"
    TORCH = "torch"
    GENERATOR = "generator"


class LoggerType(BaseEnum):
    ALL = "all"
    TENSORBOARD = "tensorboard"
    WANDB = "wandb"
    COMETML = "comet_ml"
    AIM = "aim"
    MLFLOW = "mlflow"
    CLEARML = "clearml"
    DVCLIVE = "dvclive"
    SWANLAB = "swanlab"
    JSONL = "jsonl"  # native dependency-free tracker


class SaveFormat(BaseEnum):
    SAFETENSORS = "safetensors"
    MSGPACK = "msgpack"
    ORBAX = "orbax"


class ComputeBackend(BaseEnum):
    """Where a jitted step should be lowered."""

    AUTO = "auto"
    TPU = "tpu"
    CPU = "cpu"


def env_int(name, default):
    """Integer env knob with the observability-grade failure mode: unset or
    empty reads as the default, and a malformed value WARNS and falls back
    instead of raising mid-``__init__`` — one parser shared by every
    integer env knob (telemetry cadence/ports, serving decode_steps, bench
    A/B legs) so empty-string and typo semantics can never drift apart."""
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    try:
        return int(value)
    except ValueError:
        warnings.warn(f"{name}={value!r} is not an integer; ignoring")
        return default


def env_float(name, default):
    """Float env knob with the same failure mode as :func:`env_int` (the
    watchdog deadline is fractional-seconds-valued in tests)."""
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    try:
        return float(value)
    except ValueError:
        warnings.warn(f"{name}={value!r} is not a number; ignoring")
        return default


# ---------------------------------------------------------------------------
# Kwargs handlers (typed pass-throughs; reference dataclasses.py:62-551)
# ---------------------------------------------------------------------------
class KwargsHandler:
    def to_dict(self) -> dict[str, Any]:
        return {k: v for k, v in self.__dict__.items()}

    def to_kwargs(self) -> dict[str, Any]:
        default = self.__class__()
        return {
            k: v for k, v in self.__dict__.items() if getattr(default, k) != v
        }


@dataclass
class AutocastKwargs(KwargsHandler):
    """Controls the mixed-precision policy applied to jitted computation.

    Reference: AutocastKwargs dataclasses.py:107 (torch.autocast args).  On
    TPU the policy is a dtype trio (param/compute/output) applied at trace
    time — there is no context-manager autocast in XLA.
    """

    enabled: bool = True
    cache_enabled: bool = True  # accepted for API parity; no-op under XLA


@dataclass
class GradScalerKwargs(KwargsHandler):
    """Dynamic loss-scaling config for fp16 (reference dataclasses.py:226).

    bf16 — the TPU default — needs no scaling; these values feed
    ``DynamicLossScale`` only when ``mixed_precision='fp16'`` is requested.
    """

    init_scale: float = 65536.0
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    enabled: bool = True


@dataclass
class InitProcessGroupKwargs(KwargsHandler):
    """jax.distributed.initialize knobs (reference dataclasses.py:257)."""

    backend: Optional[str] = "pjrt"
    init_method: Optional[str] = None
    timeout: Optional[timedelta] = None
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None


@dataclass
class ProfileKwargs(KwargsHandler):
    """jax.profiler trace options (reference ProfileKwargs dataclasses.py:436).

    ``output_trace_dir`` receives a TensorBoard-loadable trace; `on_trace_ready`
    is invoked with the dir after collection.
    """

    output_trace_dir: Optional[str] = None
    with_flops: bool = False
    record_shapes: bool = False
    profile_memory: bool = False
    python_tracer_level: int = 1
    host_tracer_level: int = 2
    device_tracer_level: int = 1
    on_trace_ready: Optional[Callable] = None


@dataclass
class TelemetryKwargs(KwargsHandler):
    """Runtime-telemetry knobs (``accelerator.telemetry``, docs/telemetry.md).

    No reference counterpart — the observability layer is TPU-native.  When
    ``enabled`` is left ``None`` it resolves from ``$ACCELERATE_TELEMETRY``
    (default off); off means the capture path runs its pre-telemetry code
    byte-for-byte (no timers, no ring-buffer writes).

    ``timeline_size`` bounds the per-step ring buffer; ``max_events`` bounds
    each event stream (recompiles / program stats / resource samples);
    ``sample_resources`` additionally snapshots per-device live bytes at
    every capture; ``annotate_spans`` wraps each phase in a
    ``jax.profiler.TraceAnnotation`` so xprof traces show named capture
    phases; ``jsonl_path`` (or ``$ACCELERATE_TELEMETRY_JSONL``) auto-dumps
    the full history at ``end_training``/tracker ``finish``.

    ``profile_every_n`` (or ``$ACCELERATE_TELEMETRY_PROFILE_N``; 0 = off)
    samples device-time attribution: every Nth captured call runs inside a
    ``jax.profiler`` trace session and blocks until the device drains, so
    the sampled step's per-device busy/idle + compute/collective/transfer
    split lands as a ``DeviceStepRecord`` (docs/telemetry.md §device time —
    the sampled call pays the sync, every other call keeps the async
    pipeline).  ``profile_dir`` (``$ACCELERATE_TELEMETRY_PROFILE_DIR``)
    keeps the raw xprof dumps on disk instead of deleting them after
    parsing.  ``metrics_port`` (``$ACCELERATE_METRICS_PORT``; 0 = ephemeral
    port) serves live Prometheus text on ``/metrics``.

    ``watchdog_s`` (``$ACCELERATE_WATCHDOG_S``; default off) arms the hang
    watchdog (telemetry/watchdog.py): a background thread with that many
    seconds of budget around every blocking collective/device sync, dumping
    faulthandler stacks plus the flight-recorder ring to a per-rank JSON
    under ``blackbox_dir`` (``$ACCELERATE_BLACKBOX_DIR``, default
    ``blackbox/``) on stall, fatal signal, or exit.  The watchdog arms even
    when ``enabled`` is off — hang forensics must not require the full
    telemetry pipeline.  ``trace_export_path``
    (``$ACCELERATE_TRACE_EXPORT``; default off) writes the joined
    Chrome/Perfetto timeline (telemetry/trace_export.py) at
    ``end_training``.  The flight recorder itself has no knob here: it is
    on by default process-wide (``$ACCELERATE_FLIGHTREC=0`` kills it).
    """

    enabled: Optional[bool] = None  # None → $ACCELERATE_TELEMETRY, default off
    timeline_size: int = 256
    max_events: int = 256
    sample_resources: bool = True
    annotate_spans: bool = True
    jsonl_path: Optional[str] = None
    profile_every_n: Optional[int] = None  # None → env, default 0 (off)
    profile_dir: Optional[str] = None
    metrics_port: Optional[int] = None  # None → env, default no endpoint
    watchdog_s: Optional[float] = None  # None → env, default off
    blackbox_dir: Optional[str] = None  # None → env, default "blackbox"
    trace_export_path: Optional[str] = None  # None → env, default off

    def __post_init__(self):
        if self.enabled is None:
            value = os.environ.get("ACCELERATE_TELEMETRY")
            self.enabled = bool(str_to_bool(value)) if value is not None else False
        if self.jsonl_path is None:
            self.jsonl_path = os.environ.get("ACCELERATE_TELEMETRY_JSONL")
        # observability knobs must not kill the job: a malformed env value
        # warns and leaves the feature off instead of raising mid-__init__
        if self.profile_every_n is None:
            self.profile_every_n = self._env_int("ACCELERATE_TELEMETRY_PROFILE_N", 0)
        if self.profile_dir is None:
            self.profile_dir = os.environ.get("ACCELERATE_TELEMETRY_PROFILE_DIR")
        if self.metrics_port is None:
            self.metrics_port = self._env_int("ACCELERATE_METRICS_PORT", None)
        if self.watchdog_s is None:
            self.watchdog_s = env_float("ACCELERATE_WATCHDOG_S", None)
        if self.blackbox_dir is None:
            self.blackbox_dir = os.environ.get("ACCELERATE_BLACKBOX_DIR", "blackbox")
        if self.trace_export_path is None:
            self.trace_export_path = os.environ.get("ACCELERATE_TRACE_EXPORT")

    @staticmethod
    def _env_int(name, default):
        return env_int(name, default)


@dataclass
class ResilienceKwargs(KwargsHandler):
    """Resilience-subsystem knobs (``accelerator.resilience``,
    docs/resilience.md).

    No reference counterpart — preemption handling lives in PyTorch/XLA and
    torchelastic externally; here it is library behavior.  When ``enabled``
    is left ``None`` it resolves from ``$ACCELERATE_RESILIENCE`` (default
    off); off means the capture hot path runs its pre-resilience code
    byte-for-byte (one ``None``-check, matching the telemetry precedent).

    ``preemption`` installs SIGTERM/SIGINT sticky-flag handlers read via
    ``resilience.should_save``/``should_exit``; ``deadline_s`` additionally
    trips those flags N seconds after construction (maintenance windows).
    ``retry``/``max_retries``/``retry_backoff_s`` bound the transient-fault
    retry around captured-step dispatch; ``rollback`` restores the last good
    checkpoint on exhaustion and replays.  ``checkpoint_dir`` is the default
    ``resilience.drain()`` target.  ``fault_plan`` wires the test-only
    deterministic injector (``$ACCELERATE_FAULT_PLAN``).  Backend-init
    hardening is its own entry point (``resilience.backend.init_backend`` +
    ``$ACCELERATE_RESILIENCE_INIT`` at state construction) because it must
    run before any jax device call.
    """

    enabled: Optional[bool] = None  # None → $ACCELERATE_RESILIENCE, default off
    preemption: bool = True
    deadline_s: Optional[float] = None  # $ACCELERATE_RESILIENCE_DEADLINE_S
    retry: bool = True
    max_retries: int = 2  # $ACCELERATE_RESILIENCE_MAX_RETRIES
    retry_backoff_s: float = 0.5  # $ACCELERATE_RESILIENCE_RETRY_BACKOFF_S
    rollback: bool = True
    checkpoint_dir: Optional[str] = None  # $ACCELERATE_RESILIENCE_CHECKPOINT_DIR
    fault_plan: Optional[str] = None  # $ACCELERATE_FAULT_PLAN (test-only)

    def __post_init__(self):
        env = os.environ
        if self.enabled is None:
            value = env.get("ACCELERATE_RESILIENCE")
            self.enabled = bool(str_to_bool(value)) if value is not None else False
        if self.deadline_s is None and "ACCELERATE_RESILIENCE_DEADLINE_S" in env:
            self.deadline_s = float(env["ACCELERATE_RESILIENCE_DEADLINE_S"])
        if "ACCELERATE_RESILIENCE_MAX_RETRIES" in env:
            self.max_retries = int(env["ACCELERATE_RESILIENCE_MAX_RETRIES"])
        if "ACCELERATE_RESILIENCE_RETRY_BACKOFF_S" in env:
            self.retry_backoff_s = float(env["ACCELERATE_RESILIENCE_RETRY_BACKOFF_S"])
        if self.checkpoint_dir is None:
            self.checkpoint_dir = env.get("ACCELERATE_RESILIENCE_CHECKPOINT_DIR")
        if self.fault_plan is None:
            self.fault_plan = env.get("ACCELERATE_FAULT_PLAN")


@dataclass
class FleetKwargs(KwargsHandler):
    """Elastic-fleet-runtime knobs (``accelerator.fleet``, docs/elastic.md).

    No reference counterpart — this is the torchelastic-style "survive and
    resize" composition over the resilience/checkpoint/AOT-cache subsystems.
    When ``enabled`` is left ``None`` it resolves from ``$ACCELERATE_FLEET``
    (default off); off means the capture hot path runs its pre-fleet code
    byte-for-byte (one ``None``-check, matching the telemetry/resilience/
    aot-cache precedent).

    ``coordinate_rollback`` arms the multi-host restore protocol: on retry
    exhaustion every rank offers its visible complete checkpoints to a
    gather/vote barrier and all ranks issue the collective ``load_state``
    against the agreed restore point — replacing the resilience layer's
    single-process-only rollback refusal.  ``elastic`` arms dp resize: a
    lost host (``host_lost`` fault-plan verb, or a real reclamation notice)
    trips ``fleet.should_resize`` and ``fleet.resize()`` drains → re-meshes
    at the surviving topology → reshards ZeRO-1 masters/moments (and
    compression residuals) from the spec-carrying checkpoint → prewarms the
    new-topology programs from the AOT cache; a returned host
    (``host_gained``) trips ``fleet.should_grow`` and ``fleet.grow()``
    re-meshes dp *up* through the grow rendezvous.  ``min_dp`` refuses
    resizes below that dp extent.  ``aggregate_every_n`` (dispatches;
    0 = off) graduates ``telemetry.aggregate_fleet()`` to periodic mid-run
    skew/straggler records — the autoscaler/resize signal.  ``autopilot``
    arms the signal-driven autoscaler (docs/elastic.md §autopilot):
    ``True``/``"on"`` for the default policy, a ``"key=value,..."`` spec
    string (``"skew_pct=150,window=4,hysteresis=0.2,cooldown=8"``), a dict
    of the same knobs, or a ready ``fleet.AutopilotPolicy``; resolves from
    ``$ACCELERATE_FLEET_AUTOPILOT`` when left ``None`` (default off) —
    explicit kwargs beat the env, and BAD VALUES RAISE HERE, at
    construction, never at the first fire.  ``checkpoint_dir`` is the
    default drain target for resize; ``fault_plan`` wires the test-only
    injector (``$ACCELERATE_FAULT_PLAN``; the ``host_lost`` /
    ``host_gained`` / ``signal_storm`` verbs are consumed here — the rest
    belong to resilience).
    """

    enabled: Optional[bool] = None  # None → $ACCELERATE_FLEET, default off
    coordinate_rollback: bool = True
    elastic: bool = True
    min_dp: int = 1  # $ACCELERATE_FLEET_MIN_DP
    aggregate_every_n: int = 0  # $ACCELERATE_FLEET_AGGREGATE_N
    autopilot: Optional[object] = None  # None → $ACCELERATE_FLEET_AUTOPILOT, off
    checkpoint_dir: Optional[str] = None  # $ACCELERATE_FLEET_CHECKPOINT_DIR
    fault_plan: Optional[str] = None  # $ACCELERATE_FAULT_PLAN (test-only)

    def __post_init__(self):
        env = os.environ
        if self.enabled is None:
            value = env.get("ACCELERATE_FLEET")
            self.enabled = bool(str_to_bool(value)) if value is not None else False
        if "ACCELERATE_FLEET_MIN_DP" in env:
            self.min_dp = int(env["ACCELERATE_FLEET_MIN_DP"])
        if "ACCELERATE_FLEET_AGGREGATE_N" in env:
            self.aggregate_every_n = int(env["ACCELERATE_FLEET_AGGREGATE_N"])
        if self.autopilot is None:
            self.autopilot = env.get("ACCELERATE_FLEET_AUTOPILOT")
        # resolve (and VALIDATE) the policy now: a bad threshold must raise
        # at Accelerator construction, not at the autopilot's first fire
        from ..fleet.autopilot import AutopilotPolicy

        self.autopilot_policy = AutopilotPolicy.resolve(self.autopilot)
        if self.checkpoint_dir is None:
            self.checkpoint_dir = env.get("ACCELERATE_FLEET_CHECKPOINT_DIR")
        if self.fault_plan is None:
            self.fault_plan = env.get("ACCELERATE_FAULT_PLAN")


@dataclass
class CompressionKwargs(KwargsHandler):
    """dp-axis collective compression knobs (docs/compression.md).

    One surface for BOTH compression stories: ``policy`` selects a
    ``parallel.compress.CompressionPolicy`` —

    * ``"none"`` (default) — every path byte-identical to the
      pre-compression library;
    * ``"int8"`` / ``"fp8"`` — quantize the ZeRO-1 reduce-scatter /
      all-gather pair inside the captured step (per-block scales, dp-sharded
      error-feedback residuals threaded like optax moments);
    * ``"powersgd"`` / ``"batched_powersgd"`` — rank-k + error-feedback
      compression at the backward sync boundary (the reference comm hook,
      now policy-selected; legacy ``DistributedDataParallelKwargs(
      comm_hook=...)`` resolves to the same policy object).

    ``min_size``/``min_block`` are the eligibility gates (tensors below
    them pass through uncompressed); ``error_feedback`` toggles the
    residual; the ``powersgd_*`` knobs mirror torch's ``PowerSGDState``
    options.  When ``policy`` is left ``None`` it resolves from
    ``$ACCELERATE_COMPRESSION`` (default ``"none"``).
    """

    policy: Optional[str] = None  # None → $ACCELERATE_COMPRESSION, default none
    min_size: int = 2048
    min_block: int = 8
    error_feedback: bool = True
    powersgd_rank: int = 1
    powersgd_warm_start: bool = True
    powersgd_wrapper: Optional[str] = None  # "fp16" | "bf16"

    def __post_init__(self):
        if self.policy is None:
            self.policy = os.environ.get("ACCELERATE_COMPRESSION", "none")
        self.policy = str(self.policy).lower()


@dataclass
class CompilationCacheKwargs(KwargsHandler):
    """Persistent AOT executable cache knobs (``accelerator.aot_cache``,
    docs/aot_cache.md).

    No reference counterpart — compiled-program persistence is an XLA-native
    concern.  ``cache_dir`` names the on-disk store; when left ``None`` it
    resolves from ``$ACCELERATE_AOT_CACHE`` (unset = cache off).  Off means
    the capture/serving hot paths run their pre-cache code byte-for-byte
    (one ``None``-check, matching the telemetry/resilience precedent).

    Every compiled captured program (and every serving prefill/decode bucket
    program) is serialized via ``jax.experimental.serialize_executable`` into
    a content-addressed entry keyed on the capture cache key extended with a
    topology/compiler fingerprint (jax/jaxlib version, platform, device
    kind+count, process count, mesh shape, donation split, compression
    policy).  A later process with a matching fingerprint deserializes the
    executable and skips trace+compile entirely; ANY mismatch falls through
    to a normal compile with a loud ``kind="aot_cache"`` miss record.

    ``max_bytes`` bounds the store (LRU eviction, ``$ACCELERATE_AOT_CACHE_
    MAX_BYTES``); ``warm_on_restore`` prefetches matching entries into
    memory during ``load_state`` (the resilience rollback / preemption-resume
    path) so restore-after-fault replays the serialized executable without a
    step-path disk read.  ``jax_cache_dir`` additionally arms jax's own
    persistent XLA compilation cache (``$ACCELERATE_AOT_CACHE_JAX_DIR``) as
    a second layer for programs outside the capture path.
    """

    cache_dir: Optional[str] = None  # None → $ACCELERATE_AOT_CACHE, unset = off
    enabled: Optional[bool] = None  # None → on iff cache_dir resolves
    max_bytes: int = 2 << 30  # $ACCELERATE_AOT_CACHE_MAX_BYTES
    warm_on_restore: bool = True
    jax_cache_dir: Optional[str] = None  # $ACCELERATE_AOT_CACHE_JAX_DIR

    def __post_init__(self):
        env = os.environ
        if self.cache_dir is None:
            value = env.get("ACCELERATE_AOT_CACHE")
            # "0"/"false" must read as "off", not as a relative cache dir
            if value and value.lower() not in ("0", "false", "no", "off"):
                self.cache_dir = value
        if self.enabled is None:
            self.enabled = self.cache_dir is not None
        if "ACCELERATE_AOT_CACHE_MAX_BYTES" in env:
            try:
                self.max_bytes = int(env["ACCELERATE_AOT_CACHE_MAX_BYTES"])
            except ValueError:
                warnings.warn(
                    "ACCELERATE_AOT_CACHE_MAX_BYTES="
                    f"{env['ACCELERATE_AOT_CACHE_MAX_BYTES']!r} is not an "
                    "integer; keeping the default"
                )
        if self.jax_cache_dir is None:
            self.jax_cache_dir = env.get("ACCELERATE_AOT_CACHE_JAX_DIR")


@dataclass
class KernelKwargs(KwargsHandler):
    """Pallas hot-path kernel knobs (``accelerator.kernels``,
    docs/kernels.md).

    No reference counterpart — custom-kernel fusion is an XLA/Mosaic-native
    concern.  ``kernels`` names the armed set: a comma/plus-separated
    subset of ``collective_matmul`` (the ZeRO-1 all-gather as a chunked
    ring feeding partial matmuls), ``quantized_rs`` (compress.py's
    per-block scale+round fused into one kernel region at the shard
    boundary, plus the stochastic-rounding ZeRO-2 wire) and
    ``paged_attention`` (serving decode walks the block table in VMEM
    instead of materializing each slot's full page span); ``all`` arms all
    three.  When left ``None`` it resolves from ``$ACCELERATE_KERNELS``
    (default off) — off means every hot path runs its pre-kernel code
    byte-for-byte, matching the telemetry/resilience/aot-cache/fleet
    precedent.

    ``interpret`` forces the Pallas lowering mode; ``None`` (default)
    resolves to interpreter mode off-TPU (bitwise-testable StableHLO, the
    tier-1 surface) and compiled Mosaic on TPU.  The AOT cache fingerprint
    keys on the armed set, so flipping a kernel is a loud miss, never a
    stale executable.
    """

    kernels: Optional[str] = None  # None → $ACCELERATE_KERNELS, default off
    interpret: Optional[bool] = None  # None → auto (off-TPU: interpreter)

    def __post_init__(self):
        if self.kernels is None:
            self.kernels = os.environ.get("ACCELERATE_KERNELS", "")
        self.kernels = str(self.kernels).lower()
        if self.interpret is None and "ACCELERATE_KERNELS_INTERPRET" in os.environ:
            self.interpret = bool(
                str_to_bool(os.environ["ACCELERATE_KERNELS_INTERPRET"])
            )


@dataclass
class DistributedDataParallelKwargs(KwargsHandler):
    """Accepted for API parity with the reference (dataclasses.py:149).

    Under SPMD there is no DDP wrapper; gradient bucketing/overlap is the XLA
    scheduler's job.  ``gradient_as_bucket_view`` etc. are accepted and
    ignored; ``comm_hook`` ("fp16"/"bf16") compresses synced gradients at
    the backward boundary — half-width grad buffers and downstream
    consumers — and "powersgd"/"batched_powersgd" run rank-k compression
    with error feedback there (utils/powersgd.py); see
    Accelerator._apply_comm_hook for exactly what this does and does not
    change about XLA's collective dtypes.

    ``comm_wrapper`` ("fp16"/"bf16") composes with the PowerSGD hooks the
    way the reference's fp16/bf16 wrappers compose with powerSGD_hook: the
    transported low-rank factors are rounded through that dtype.
    ``comm_state_option`` carries the PowerSGDState options
    (``matrix_approximation_rank``, ``use_error_feedback``, ``warm_start``;
    ``start_powerSGD_iter`` is accepted and ignored — compression runs from
    step 0, see utils/powersgd.py).  Reference: dataclasses.py:137-215.
    """

    bucket_cap_mb: int = 25
    find_unused_parameters: bool = False
    gradient_as_bucket_view: bool = False
    static_graph: bool = False
    comm_hook: Optional[str] = None  # "fp16"|"bf16"|"powersgd"|"batched_powersgd"
    comm_wrapper: Optional[str] = None  # "fp16" | "bf16" wrapper for powersgd
    comm_state_option: dict = field(default_factory=dict)


# ---------------------------------------------------------------------------
# Plugins
# ---------------------------------------------------------------------------
@dataclass
class GradientAccumulationPlugin(KwargsHandler):
    """Reference: dataclasses.py:779."""

    num_steps: Optional[int] = None
    adjust_scheduler: bool = True
    sync_with_dataloader: bool = True
    sync_each_batch: bool = False


@dataclass
class ProjectConfiguration:
    """Checkpoint/logging directory layout (reference dataclasses.py:857)."""

    project_dir: Optional[str] = None
    logging_dir: Optional[str] = None
    automatic_checkpoint_naming: bool = False
    total_limit: Optional[int] = None
    iteration: int = 0
    save_on_each_node: bool = False

    def __post_init__(self):
        if self.logging_dir is None:
            self.logging_dir = self.project_dir

    def set_directories(self, project_dir: Optional[str] = None):
        self.project_dir = project_dir
        if self.logging_dir is None:
            self.logging_dir = project_dir


@dataclass
class DataLoaderConfiguration:
    """Reference: dataclasses.py:789 (DataLoaderConfiguration)."""

    split_batches: bool = False
    dispatch_batches: Optional[bool] = None
    even_batches: bool = True
    use_seedable_sampler: bool = False
    data_seed: Optional[int] = None
    non_blocking: bool = False  # parity; device feed is always async on TPU
    use_stateful_dataloader: bool = False
    prefetch_size: int = 2  # device prefetch depth (MpDeviceLoader analog)


@dataclass
class FullyShardedDataParallelPlugin:
    """ZeRO/FSDP expressed as GSPMD sharding on the ``fsdp`` mesh axis.

    User-facing surface mirrors the reference plugin
    (dataclasses.py:1449-1863); the lowering is a NamedSharding rule-set, not a
    wrapper module.  ``sharding_strategy``:
      FULL_SHARD      → params+grads+optimizer sharded (ZeRO-3)
      SHARD_GRAD_OP   → grads+optimizer sharded, params replicated (ZeRO-2)
      NO_SHARD        → pure DP
      HYBRID_SHARD    → shard within a slice, replicate across slices
    """

    sharding_strategy: str = "FULL_SHARD"
    reshard_after_forward: bool = True
    fsdp_size: Optional[int] = None  # mesh axis size; None → all devices
    auto_wrap_policy: Optional[str] = "transformer_based_wrap"
    transformer_cls_names_to_wrap: Optional[list[str]] = None
    min_num_params: int = 0
    # training-time parameter offload (torch FSDP CPUOffload(offload_params)
    # / DeepSpeed ZeRO-Infinity offload_param, reference
    # dataclasses.py:1082-1090): fsdp-sharded params live in pinned host
    # memory between steps and are staged back by a forward hook traced into
    # the captured step (hooks.ParamOffloadHook).  Env: FSDP_OFFLOAD_PARAMS.
    cpu_offload: bool = False
    state_dict_type: str = "SHARDED_STATE_DICT"  # or FULL_STATE_DICT
    use_orig_params: bool = True  # parity; always true functionally
    # MixedPrecisionPolicy analog (reference dataclasses.py:1449):
    # param_dtype = per-plugin compute dtype for sharded params ("bf16"/
    # "fp16"/"fp32"); reduce_dtype = synced-gradient dtype, applied through
    # the same boundary as DistributedDataParallelKwargs.comm_hook
    param_dtype: Optional[str] = None
    reduce_dtype: Optional[str] = None
    activation_checkpointing: bool = False
    # host-offloaded optimizer state (reference dataclasses.py:1019
    # offload_optimizer via DeepSpeed; torch FSDP CPUOffload): Adam moments
    # and fp32 masters live in pinned host memory, streamed to the chip only
    # for the update — HBM then holds params+grads+activations only.  Pays a
    # host<->device round-trip per sync step; for models whose optimizer
    # state doesn't fit even fsdp-sharded.
    offload_optimizer: bool = False

    _DTYPES = {"bf16": "bfloat16", "fp16": "float16", "fp32": "float32",
               "bfloat16": "bfloat16", "float16": "float16", "float32": "float32"}

    def resolved_dtype(self, field_name: str):
        """jnp dtype for param_dtype/reduce_dtype, or None when unset."""
        value = getattr(self, field_name)
        if value is None:
            return None
        import jax.numpy as jnp

        key = self._DTYPES.get(str(value).lower())
        if key is None:
            raise ValueError(
                f"{field_name}={value!r}: use one of bf16/fp16/fp32"
            )
        return jnp.dtype(key)

    def __post_init__(self):
        env = os.environ
        self.sharding_strategy = env.get(
            "FSDP_SHARDING_STRATEGY", self.sharding_strategy
        ).upper()
        if "FSDP_OFFLOAD_PARAMS" in env:
            self.cpu_offload = bool(str_to_bool(env["FSDP_OFFLOAD_PARAMS"]))
        if "FSDP_OFFLOAD_OPTIMIZER" in env:
            self.offload_optimizer = bool(str_to_bool(env["FSDP_OFFLOAD_OPTIMIZER"]))
        self.state_dict_type = env.get(
            "FSDP_STATE_DICT_TYPE", self.state_dict_type
        ).upper()
        if self.transformer_cls_names_to_wrap is None:
            names = env.get("FSDP_TRANSFORMER_CLS_TO_WRAP", "")
            self.transformer_cls_names_to_wrap = (
                [n.strip() for n in names.split(",") if n.strip()] or None
            )
        if self.fsdp_size is None and "FSDP_SIZE" in env:
            self.fsdp_size = int(env["FSDP_SIZE"])
        if "FSDP_ACTIVATION_CHECKPOINTING" in env:
            self.activation_checkpointing = bool(
                str_to_bool(env["FSDP_ACTIVATION_CHECKPOINTING"])
            )
        # fail on dtype typos at construction, not at the first sync backward
        self.resolved_dtype("param_dtype")
        self.resolved_dtype("reduce_dtype")


@dataclass
class DataParallelPlugin:
    """Knobs for the plain ``dp`` mesh axis.

    ``zero1`` shards the *weight update* cross-replica (ZeRO-1,
    arXiv:2004.13336): fp32 masters and optax moments get a NamedSharding
    over the dp axis, so GSPMD lowers the captured step to reduce-scatter →
    shard-local update → all-gather inside the one XLA program.  Per-replica
    optimizer-state HBM drops to ~1/dp and the update math is deduplicated;
    params, grads and the user-visible API are untouched.

    ``None`` (default) = automatic: on whenever dp > 1 and no ``fsdp`` axis
    already owns the params (FULL_SHARD/HYBRID_SHARD state follows the
    params, making ZeRO-1 redundant there).  Env: ACCELERATE_ZERO1.

    ``zero2`` additionally keeps the *accumulated gradients* reduce-
    scattered between micro-steps under gradient accumulation, so the
    accumulation buffer is also ~1/dp per replica (docs/compression.md).
    Opt-in (default off) because it changes the ``.grad`` layout contract:
    between micro-steps ``param.grad`` is a dp-sharded global array (same
    values, 1/dp resident bytes) rather than a replicated one.  Requires
    ZeRO-1 to be active (sharded grads feed the sharded update directly).
    Env: ACCELERATE_ZERO2.
    """

    zero1: Optional[bool] = None
    zero2: Optional[bool] = None

    def __post_init__(self):
        if self.zero1 is None and "ACCELERATE_ZERO1" in os.environ:
            self.zero1 = bool(str_to_bool(os.environ["ACCELERATE_ZERO1"]))
        if self.zero2 is None and "ACCELERATE_ZERO2" in os.environ:
            self.zero2 = bool(str_to_bool(os.environ["ACCELERATE_ZERO2"]))


@dataclass
class TensorParallelPlugin:
    """Tensor parallelism on the ``tp`` mesh axis.

    Reference: TorchTensorParallelPlugin dataclasses.py:1863-1895 (reads
    TP_SIZE from env, utils/launch.py:303-305).  ``tp_plan`` maps parameter
    path regexes to partition specs; None uses the model's built-in plan
    (`Module.tp_plan`).
    """

    tp_size: int = 1
    tp_plan: Optional[dict[str, Any]] = None

    def __post_init__(self):
        if self.tp_size == 1 and "TP_SIZE" in os.environ:
            self.tp_size = int(os.environ["TP_SIZE"])


@dataclass
class SequenceParallelPlugin:
    """Long-context sequence/context parallelism on the ``sp`` mesh axis.

    New TPU-native capability (absent from the reference natively — see
    SURVEY.md §2.2 SP row): ring attention via shard_map + lax.ppermute over
    ICI, with blockwise-softmax renormalisation.
    """

    sp_size: int = 1
    mode: str = "ring"  # "ring" | "all_to_all" (Ulysses-style)
    chunk_size: Optional[int] = None

    def __post_init__(self):
        if self.sp_size == 1 and "SP_SIZE" in os.environ:
            self.sp_size = int(os.environ["SP_SIZE"])
        if self.mode not in ("ring", "all_to_all"):
            raise ValueError(f"unknown sequence-parallel mode {self.mode!r}")


@dataclass
class PipelineParallelPlugin:
    """Microbatch pipelining over the ``pp`` mesh axis.

    ``schedule``:
      * ``"gpipe"`` — fill-drain: all forwards, then all backwards (JAX AD
        transposes the forward loop).  Peak activation state grows with
        ``num_microbatches``.
      * ``"1f1b"`` — fused one-forward-one-backward: loss and backward run
        INSIDE the pipeline loop, so each stage holds at most ``2·S−1``
        in-flight stage inputs regardless of microbatch count (the
        Megatron-style memory profile; reference delegates to
        megatron.core's get_forward_backward_func, utils/megatron_lm.py:40).
        Requires the loss to be computed by the pipelined program — models
        opt in via their pipelined loss path (PipelinedGPTLMHeadModel).
      * ``"interleaved"`` — interleaved 1F1B (MPMD pipeline-parallelism,
        PAPERS.md #4): each pp device hosts ``virtual_stages`` NON-contiguous
        layer spans and microbatches hop V× around the ring, shrinking the
        fill/drain bubble by the virtual factor while keeping the
        ``2·S−1``-order residual window.  Needs ``num_microbatches``
        divisible by ``pp_size`` and layers divisible by
        ``pp_size × virtual_stages``.

    The resolved values land in the run's ``ParallelPlan``
    (``accelerator.plan.stage`` — docs/parallel_plan.md); consumers read
    the plan, never this plugin directly.
    """

    pp_size: int = 1
    num_microbatches: int = 1
    # None/0 = unset: resolves to $PP_SCHEDULE / $PP_VIRTUAL, then the
    # default.  Sentinels (not concrete defaults) so an EXPLICIT
    # schedule="gpipe" / virtual_stages=1 beats the env var.
    schedule: Optional[str] = None  # "gpipe" | "1f1b" | "interleaved"
    virtual_stages: int = 0  # interleave factor V; 0 = unset
    # stacked-layer-axis layout of record (docs/parallel_plan.md §layout
    # contract).  None = unset: resolves to $PP_LAYOUT, then the plan's
    # default ("plain" at V=1, "committed" at V>1 — prepare() permutes the
    # layer stack once and the step moves zero permutation bytes).
    # "gather" keeps the legacy per-step in-program permutation (A/B arm).
    layout: Optional[str] = None  # "committed" | "gather"

    def __post_init__(self):
        if self.pp_size == 1 and "PP_SIZE" in os.environ:
            self.pp_size = int(os.environ["PP_SIZE"])
        explicit_layout = self.layout is not None
        if self.layout is None:
            self.layout = os.environ.get("PP_LAYOUT", None) or None
        if self.layout is not None and self.layout not in ("committed", "gather"):
            raise ValueError(
                f"unknown pipeline layer layout {self.layout!r}; use "
                "'committed' (prepare-time permute, default) or 'gather' "
                "(legacy per-step in-program permutation)"
            )
        explicit_schedule = self.schedule is not None
        explicit_virtual = self.virtual_stages != 0
        env_schedule = None
        if self.schedule is None:
            env_schedule = os.environ.get("PP_SCHEDULE", None)
            self.schedule = env_schedule
        if self.virtual_stages == 0 and "PP_VIRTUAL" in os.environ:
            self.virtual_stages = int(os.environ["PP_VIRTUAL"])
            if explicit_schedule and (
                (self.schedule in ("gpipe", "1f1b") and self.virtual_stages > 1)
                or (self.schedule == "interleaved" and self.virtual_stages < 2)
            ):
                # kwargs beat env: an env-sourced virtual factor that is
                # incompatible with the EXPLICIT schedule yields back to
                # unset instead of raising or silently changing the
                # schedule — gpipe/fused 1f1b cannot interleave (a
                # different compiled program, fingerprint and M%S
                # constraint), and an explicit interleaved keeps its
                # default factor under an ambient PP_VIRTUAL=1
                self.virtual_stages = 0
        if explicit_virtual and env_schedule is not None:
            # and symmetrically: an env-sourced schedule incompatible with
            # the EXPLICIT virtual factor yields to the factor's canonical
            # schedule (V=1 IS the fused 1f1b, V>1 IS interleaved)
            if env_schedule == "interleaved" and self.virtual_stages == 1:
                self.schedule = "1f1b"
            elif env_schedule == "gpipe" and self.virtual_stages > 1:
                self.schedule = "interleaved"
        if self.virtual_stages == 0:
            # interleaved without an explicit factor means "interleave at
            # all": the smallest real factor
            self.virtual_stages = 2 if self.schedule == "interleaved" else 1
        if self.schedule is None:
            self.schedule = "interleaved" if self.virtual_stages > 1 else "gpipe"
        if self.schedule == "1f1b" and self.virtual_stages > 1:
            # V>1 IS the interleaved schedule; normalize so the plan and the
            # AOT fingerprint carry one canonical name
            self.schedule = "interleaved"
        if self.schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"unknown pipeline schedule {self.schedule!r}; use 'gpipe', "
                "'1f1b' or 'interleaved'"
            )
        if self.virtual_stages < 1:
            raise ValueError(
                f"virtual_stages must be >= 1, got {self.virtual_stages}"
            )
        if self.schedule == "gpipe" and self.virtual_stages > 1:
            raise ValueError(
                "virtual_stages > 1 interleaves the fused 1F1B schedule; it "
                "cannot combine with schedule='gpipe'"
            )
        if self.schedule == "interleaved" and self.virtual_stages < 2:
            raise ValueError(
                "schedule='interleaved' needs virtual_stages >= 2 "
                "(virtual_stages=1 is exactly the fused '1f1b' schedule)"
            )
        if self.virtual_stages == 1 and self.layout is not None:
            if explicit_layout:
                raise ValueError(
                    f"layout={self.layout!r} needs virtual_stages >= 2: at "
                    "V=1 the interleave order is the identity and the only "
                    "layer layout is 'plain'"
                )
            # kwargs beat env: an ambient PP_LAYOUT cannot apply to a run
            # whose (explicit or resolved) factor is V=1 — yield to unset
            # instead of raising on an unrelated fused/gpipe run
            self.layout = None


@dataclass
class ExpertParallelPlugin:
    """MoE expert parallelism on the ``ep`` mesh axis (reference exposes only
    DeepSpeed MoE leaf hints, accelerator.py:1881 — this is first-class here)."""

    ep_size: int = 1

    def __post_init__(self):
        if self.ep_size == 1 and "EP_SIZE" in os.environ:
            self.ep_size = int(os.environ["EP_SIZE"])


@dataclass
class ParallelismConfig:
    """The resolved mesh layout: one SPMD program, many axes.

    dp is inferred as ``num_devices // (fsdp*tp*sp*ep*pp)`` when left at 0.
    """

    dp_size: int = 0
    fsdp_size: int = 1
    tp_size: int = 1
    sp_size: int = 1
    ep_size: int = 1
    pp_size: int = 1

    def axis_sizes(self, num_devices: int) -> dict[str, int]:
        fixed = self.fsdp_size * self.tp_size * self.sp_size * self.ep_size * self.pp_size
        if fixed <= 0 or num_devices % fixed != 0:
            raise ValueError(
                f"mesh axes {self!r} do not divide device count {num_devices}"
            )
        dp = self.dp_size or num_devices // fixed
        if dp * fixed != num_devices:
            raise ValueError(
                f"dp({dp})×fsdp({self.fsdp_size})×tp({self.tp_size})×sp({self.sp_size})"
                f"×ep({self.ep_size})×pp({self.pp_size}) != {num_devices} devices"
            )
        return {
            "dp": dp,
            "fsdp": self.fsdp_size,
            "tp": self.tp_size,
            "sp": self.sp_size,
            "ep": self.ep_size,
            "pp": self.pp_size,
        }

    @classmethod
    def from_env(cls) -> "ParallelismConfig":
        env = os.environ
        return cls(
            dp_size=int(env.get("DP_SIZE", 0)),
            fsdp_size=int(env.get("FSDP_SIZE", 1)),
            tp_size=int(env.get("TP_SIZE", 1)),
            sp_size=int(env.get("SP_SIZE", 1)),
            ep_size=int(env.get("EP_SIZE", 1)),
            pp_size=int(env.get("PP_SIZE", 1)),
        )


# ---------------------------------------------------------------------------
# FP8 recipes (reference dataclasses.py:295-435): on TPU fp8 is native XLA
# dtypes (e8m4/e5m2) rather than TransformerEngine/MSAMP module swaps.
# ---------------------------------------------------------------------------
@dataclass
class FP8RecipeKwargs(KwargsHandler):
    backend: str = "xla"  # only native XLA fp8 on TPU
    use_autocast_during_eval: bool = False
    margin: int = 0
    fp8_format: str = "HYBRID"  # E4M3 fwd / E5M2 bwd
    amax_history_len: int = 1024
    amax_compute_algo: str = "max"


def add_model_config_to_megatron_parser(*args, **kwargs):  # pragma: no cover
    raise NotImplementedError(
        "Megatron-LM delegation does not exist on the TPU stack; its "
        "capabilities (tp/pp/sp degrees, distributed optimizer) are expressed "
        "through ParallelismConfig mesh axes instead."
    )
