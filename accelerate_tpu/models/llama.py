"""Llama-family decoder: RMSNorm / rotary positions / SwiGLU / grouped-query
attention — the BASELINE.json config-4 north-star family ("FSDP-wrapped
Llama-2-7B", reference tests/fsdp + accelerator.py:1421 any-module prepare).

TPU-first structure, one math implementation: every decoder layer's forward
is ONE ``tape_op`` over the pure per-layer functions ``llama_attn_in`` /
``llama_attn_out`` — the exact functions the KV-cache decode engine
(models/generation.py) scans over — so training, sharded inference and
generation cannot drift.  Module/parameter naming mirrors the HF layout
(``layers.N.self_attn.q_proj.weight`` …) so checkpoint ingestion
(utils/hf.py) and the torch bridge are near-identity key maps.

GQA on TPU: k/v are computed with ``n_kv_head`` heads; for training's flash
kernel they broadcast to the full head count (an O(S·d) repeat XLA folds
into the attention fusion), while cached decode attends grouped directly
(generation.cached_attention) so the cache stays at its n_kv_head size —
the whole point of GQA at 7B scale (the 32→32 MHA cache for seq 4096 is
2 GB/layer-group; GQA-8 cuts it 4×).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import Tensor
from .gpt import lm_head_loss, maybe_remat


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Rotary frequency rescaling for long-context Llama variants.

    Mirrors the HF ``rope_scaling`` config block (transformers
    modeling_rope_utils): ``linear`` divides every inverse frequency by
    ``factor`` (positions effectively compressed); ``llama3`` is the
    NTK-by-parts scheme Llama-3.1+ ships — wavelengths longer than
    ``original_max_position_embeddings / low_freq_factor`` are divided by
    ``factor``, wavelengths shorter than ``original / high_freq_factor``
    are kept, and the band between is smoothly interpolated; ``yarn``
    blends interpolated and extrapolated frequencies with a linear ramp
    between the ``beta_fast``/``beta_slow`` correction dims and scales the
    cos/sin tables by ``attention_factor`` (default ``0.1·ln(factor)+1``).
    Frozen (and therefore hashable) so it can ride the static decode cfg
    through jit.
    """

    rope_type: str = "llama3"
    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192
    # yarn-only knobs (transformers _compute_yarn_parameters defaults)
    attention_factor: "float | None" = None
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    # DeepSeek-style mscale pair: when both set (and attention_factor is
    # None) the cos/sin scale is get_mscale(factor, mscale) /
    # get_mscale(factor, mscale_all_dim)
    mscale: "float | None" = None
    mscale_all_dim: "float | None" = None
    truncate: bool = True  # floor/ceil the correction range (HF default)

    @classmethod
    def from_hf(cls, d) -> "RopeScaling | None":
        """Normalize an HF ``rope_scaling`` dict (``rope_type`` new-style or
        ``type`` legacy).  None / "default" → None; unsupported schemes
        (dynamic NTK — seq-length-dependent tables — and longrope) refuse
        loudly — their math would be silently wrong here."""
        if d is None or isinstance(d, cls):
            return d
        kind = d.get("rope_type") or d.get("type") or "default"
        if kind == "default":
            return None
        if kind == "linear":
            return cls(rope_type="linear", factor=float(d.get("factor", 1.0)))
        if kind == "llama3":
            return cls(
                rope_type="llama3",
                factor=float(d.get("factor", 8.0)),
                low_freq_factor=float(d.get("low_freq_factor", 1.0)),
                high_freq_factor=float(d.get("high_freq_factor", 4.0)),
                original_max_position_embeddings=int(
                    d.get("original_max_position_embeddings", 8192)
                ),
            )
        if kind == "yarn":
            af = d.get("attention_factor")
            ms, msad = d.get("mscale"), d.get("mscale_all_dim")
            return cls(
                rope_type="yarn",
                factor=float(d.get("factor", 1.0)),
                original_max_position_embeddings=int(
                    d.get("original_max_position_embeddings", 8192)
                ),
                attention_factor=None if af is None else float(af),
                # HF semantics: falsy (0/None/absent) -> the paper defaults
                beta_fast=float(d.get("beta_fast") or 32.0),
                beta_slow=float(d.get("beta_slow") or 1.0),
                mscale=None if ms is None else float(ms),
                mscale_all_dim=None if msad is None else float(msad),
                truncate=bool(d.get("truncate", True)),
            )
        raise NotImplementedError(
            f"rope_scaling type {kind!r} is not supported; implemented: "
            "'linear', 'llama3', 'yarn' (and 'default' = no scaling)"
        )

    @property
    def resolved_attention_factor(self) -> float:
        """yarn's cos/sin scale (transformers _compute_yarn_parameters):
        explicit ``attention_factor``; else the DeepSeek mscale ratio when
        both mscale knobs are set; else ``get_mscale(factor)``."""
        import math as _math

        if self.attention_factor is not None:
            return self.attention_factor

        def get_mscale(scale, m=1.0):
            if scale <= 1:
                return 1.0
            return 0.1 * m * _math.log(scale) + 1.0

        if self.mscale and self.mscale_all_dim:
            return float(
                get_mscale(self.factor, self.mscale)
                / get_mscale(self.factor, self.mscale_all_dim)
            )
        return get_mscale(self.factor)


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000  # already a 128 multiple (250×128) — MXU-clean
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    # Mistral-style sliding-window attention: 0 = full causal; >0 = each
    # position attends to the previous `sliding_window` positions only (the
    # flash FORWARD visits only in-band k-tiles — cost scales with window;
    # backward gates MXU work per tile, see ops/flash_attention.py)
    sliding_window: int = 0
    # Llama-3.1+ long-context rotary rescaling; accepts an HF-style dict or
    # a RopeScaling and normalizes to the latter (None = plain theta)
    rope_scaling: "RopeScaling | None" = None
    # decoupled per-head width (Mistral-Nemo: 128-dim heads on d_model 5120);
    # None = the usual hidden_size // num_attention_heads
    head_dim: "int | None" = None

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    def __post_init__(self):
        if isinstance(self.rope_scaling, dict):
            d = dict(self.rope_scaling)
            kind = d.get("rope_type") or d.get("type")
            # HF fallback: yarn's original_max_position_embeddings defaults
            # to the model's max_position_embeddings when absent
            if kind == "yarn" and not d.get("original_max_position_embeddings"):
                d["original_max_position_embeddings"] = self.max_position_embeddings
            self.rope_scaling = RopeScaling.from_hf(d)

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        # n_kv < n_head so every test exercises the GQA path
        return cls(
            vocab_size=1024, hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=256,
        )

    @classmethod
    def llama2_7b(cls) -> "LlamaConfig":
        return cls()  # the defaults are Llama-2-7B

    @classmethod
    def mistral_7b(cls) -> "LlamaConfig":
        """Mistral-7B-v0.1: Llama architecture + GQA 4:1 + 4096-token
        sliding window (arXiv:2310.06825)."""
        return cls(
            vocab_size=32000, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, max_position_embeddings=32768,
            rope_theta=10000.0, sliding_window=4096,
        )

    @classmethod
    def llama31_8b(cls) -> "LlamaConfig":
        """Llama-3.1-8B: GQA 4:1, 128k context via llama3 rope scaling,
        128256-vocab (divisible by 128 — MXU-clean as shipped)."""
        return cls(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, max_position_embeddings=131072,
            rms_norm_eps=1e-5, rope_theta=500000.0,
            rope_scaling=RopeScaling(
                rope_type="llama3", factor=8.0, low_freq_factor=1.0,
                high_freq_factor=4.0, original_max_position_embeddings=8192,
            ),
        )

    @classmethod
    def llama2_7b_proxy(cls) -> "LlamaConfig":
        """7B layer geometry at 8-layer depth — same per-layer math/sharding,
        fits one v5e chip for bench/dryrun work."""
        return cls(num_hidden_layers=8, max_position_embeddings=2048)


# ---------------------------------------------------------------------------
# Pure per-layer math — single source of truth for training AND decode.
# Keys: ln1_w, q_w, k_w, v_w, o_w, ln2_w, gate_w, up_w, down_w
# (weights (out, in) like nn.Linear, applied as  x @ w.T; no biases in Llama).
# ---------------------------------------------------------------------------
def _pure_rmsnorm(x, w, eps):
    # HF order: fp32 variance, cast back to activation dtype, THEN scale
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return w * (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype)


def _rope_inv_freq(d: int, theta: float, scaling: "RopeScaling | None"):
    """Per-pair inverse frequencies (d/2,) fp32, optionally rescaled.

    llama3 scheme (transformers modeling_rope_utils
    _compute_llama3_parameters): wavelength 2π/f longer than
    ``original/low_freq_factor`` → f/factor; shorter than
    ``original/high_freq_factor`` → f unchanged; in between → linear
    interpolation in ``smooth = (original/wavelength - low)/(high - low)``.
    """
    inv = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    if scaling is None:
        return inv
    if scaling.rope_type == "linear":
        return inv / scaling.factor
    if scaling.rope_type == "yarn":
        # transformers _compute_yarn_parameters: blend interpolated
        # (inv/factor) and extrapolated (inv) frequencies with a linear ramp
        # between the correction dims of beta_fast/beta_slow rotations
        import math as _math

        orig = scaling.original_max_position_embeddings

        def corr_dim(num_rot):
            return (d * _math.log(orig / (num_rot * 2 * _math.pi))) / (
                2 * _math.log(theta)
            )

        low, high = corr_dim(scaling.beta_fast), corr_dim(scaling.beta_slow)
        if scaling.truncate:
            low, high = _math.floor(low), _math.ceil(high)
        low, high = max(low, 0), min(high, d - 1)
        if low == high:
            high += 0.001  # avoid zero division, per the reference impl
        ramp = jnp.clip(
            (jnp.arange(d // 2, dtype=jnp.float32) - low) / (high - low), 0.0, 1.0
        )
        extrapolation_factor = 1.0 - ramp
        return (inv / scaling.factor) * (1.0 - extrapolation_factor) + (
            inv * extrapolation_factor
        )
    # llama3 NTK-by-parts
    orig = scaling.original_max_position_embeddings
    low_wl = orig / scaling.low_freq_factor
    high_wl = orig / scaling.high_freq_factor
    wl = 2.0 * jnp.pi / inv
    scaled = jnp.where(wl > low_wl, inv / scaling.factor, inv)
    smooth = (orig / wl - scaling.low_freq_factor) / (
        scaling.high_freq_factor - scaling.low_freq_factor
    )
    smoothed = (1.0 - smooth) * inv / scaling.factor + smooth * inv
    in_band = jnp.logical_and(wl <= low_wl, wl >= high_wl)
    return jnp.where(in_band, smoothed, scaled)


def _rope_rotate(x, positions, theta, scaling=None):
    """Rotate-half rotary embedding on (b, h, s, d), positions (s,) global.

    HF convention (transformers LlamaRotaryEmbedding): fp32 angle tables,
    ``emb = cat(freqs, freqs)``, ``x*cos + rotate_half(x)*sin``.
    """
    d = x.shape[-1]
    inv = _rope_inv_freq(d, theta, scaling)
    freqs = positions.astype(jnp.float32)[:, None] * inv[None, :]  # (s, d/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # (s, d)
    cos32, sin32 = jnp.cos(emb), jnp.sin(emb)
    if scaling is not None and scaling.rope_type == "yarn":
        # yarn scales the tables (transformers applies attention_scaling
        # to cos/sin, equivalent to scaling attention logits)
        af = scaling.resolved_attention_factor
        cos32, sin32 = cos32 * af, sin32 * af
    cos = cos32.astype(x.dtype)[None, None]
    sin = sin32.astype(x.dtype)[None, None]
    x1, x2 = x[..., : d // 2], x[..., d // 2 :]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos + rotated * sin


def llama_attn_in(l, x, positions, *, n_head: int, n_kv_head: int, eps: float,
                  theta: float, rope_scaling=None):
    """RMSNorm + q/k/v projections + RoPE: (b,s,c) → q (b,H,s,d), k/v (b,Hkv,s,d).

    head_dim derives from the q projection WEIGHT, not ``c // n_head``, so
    decoupled-head variants (Mistral-Nemo: 128-dim heads on a 5120 model)
    run the same math.
    """
    b, s, c = x.shape
    d = l["q_w"].shape[0] // n_head
    h = _pure_rmsnorm(x, l["ln1_w"], eps)

    def heads(t, n):
        return t.reshape(b, s, n, d).transpose(0, 2, 1, 3)

    q = heads(h @ l["q_w"].T, n_head)
    k = heads(h @ l["k_w"].T, n_kv_head)
    v = heads(h @ l["v_w"].T, n_kv_head)
    return (
        _rope_rotate(q, positions, theta, rope_scaling),
        _rope_rotate(k, positions, theta, rope_scaling),
        v,
    )


def llama_attn_out(l, x, att, *, eps: float):
    """o_proj + residual, then RMSNorm + SwiGLU MLP + residual.

    The attention output flattens to (b, s, H·d) — which equals the model
    width only when head_dim is the derived default; o_proj maps it back
    to ``c`` either way."""
    b, s, c = x.shape
    att = att.transpose(0, 2, 1, 3)
    att = att.reshape(b, s, att.shape[2] * att.shape[3])
    h = x + att @ l["o_w"].T
    h2 = _pure_rmsnorm(h, l["ln2_w"], eps)
    ff = jax.nn.silu(h2 @ l["gate_w"].T) * (h2 @ l["up_w"].T)
    return h + ff @ l["down_w"].T


_LAYER_KEYS = ("ln1_w", "q_w", "k_w", "v_w", "o_w", "ln2_w", "gate_w", "up_w", "down_w")


def _llama_block(l, x, positions, *, n_head, n_kv_head, eps, theta, window=0,
                 rope_scaling=None):
    """Causal (optionally sliding-window) training block: the pure pair
    around flash attention."""
    from ..ops.attention import sdpa_tpu

    q, k, v = llama_attn_in(
        l, x, positions, n_head=n_head, n_kv_head=n_kv_head, eps=eps,
        theta=theta, rope_scaling=rope_scaling,
    )
    group = n_head // n_kv_head
    if group > 1:  # flash kernel wants matched head counts
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    att = sdpa_tpu(q, k, v, is_causal=True, window=window)
    return llama_attn_out(l, x, att, eps=eps)


# ---------------------------------------------------------------------------
# Modules (HF-shaped naming for key-mapped checkpoint load / torch bridge)
# ---------------------------------------------------------------------------
class LlamaAttention(nn.Module):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c, d = config.hidden_size, config.resolved_head_dim
        self.q_proj = nn.Linear(c, config.num_attention_heads * d, bias=False)
        self.k_proj = nn.Linear(c, config.num_key_value_heads * d, bias=False)
        self.v_proj = nn.Linear(c, config.num_key_value_heads * d, bias=False)
        self.o_proj = nn.Linear(config.num_attention_heads * d, c, bias=False)


class LlamaMLP(nn.Module):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c, i = config.hidden_size, config.intermediate_size
        self.gate_proj = nn.Linear(c, i, bias=False)
        self.up_proj = nn.Linear(c, i, bias=False)
        self.down_proj = nn.Linear(i, c, bias=False)


class LlamaDecoderLayer(nn.Module):
    """Parameters live in HF-named submodules; forward is one tape_op over
    the pure block math (llama_attn_in / llama_attn_out)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = nn.RMSNorm(config.hidden_size, eps=config.rms_norm_eps)
        self.post_attention_layernorm = nn.RMSNorm(
            config.hidden_size, eps=config.rms_norm_eps
        )

    def param_tensors(self):
        a, m = self.self_attn, self.mlp
        return [  # order == _LAYER_KEYS
            self.input_layernorm.weight, a.q_proj.weight, a.k_proj.weight,
            a.v_proj.weight, a.o_proj.weight, self.post_attention_layernorm.weight,
            m.gate_proj.weight, m.up_proj.weight, m.down_proj.weight,
        ]

    def forward(self, x):
        cfg = self.config
        s = x.shape[1]
        positions = jnp.arange(s)

        def fn(xv, *flat):
            l = dict(zip(_LAYER_KEYS, flat))
            return _llama_block(
                l, xv, positions,
                n_head=cfg.num_attention_heads,
                n_kv_head=cfg.num_key_value_heads,
                eps=cfg.rms_norm_eps, theta=cfg.rope_theta,
                window=cfg.sliding_window, rope_scaling=cfg.rope_scaling,
            )

        return nn.tape_op(maybe_remat(fn), x, *self.param_tensors())


class LlamaForCausalLM(nn.Module):
    _no_split_modules = ["LlamaDecoderLayer"]  # device_map: keep residuals intact
    tp_plan = {
        # Megatron layout: qkv/gate/up column-parallel, o/down row-parallel
        r".*\.(q_proj|k_proj|v_proj|gate_proj|up_proj)\.weight": ("tp", None),
        r".*\.(o_proj|down_proj)\.weight": (None, "tp"),
        r"embed_tokens\.weight": ("tp", None),
        r"lm_head\.weight": ("tp", None),
    }

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.layers = nn.ModuleList(
            [LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)]
        )
        self.norm = nn.RMSNorm(config.hidden_size, eps=config.rms_norm_eps)
        from ..nn.meta import is_meta, meta_init

        if config.tie_word_embeddings:
            with meta_init():
                self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias=False)
            self.lm_head.weight = self.embed_tokens.weight
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias=False)
        # HF init: N(0, initializer_range) for all matmul weights, ones for norms
        from ..nn import random as nn_random

        std = config.initializer_range
        for name, p in self.named_parameters():
            if is_meta(p.data) or p.ndim < 2:
                continue
            p.data = std * jax.random.normal(nn_random.next_key(), p.shape, p.dtype)

    def forward(self, input_ids, labels=None):
        from ..parallel.sharding import constrain_activation

        ids = jnp.asarray(input_ids.data if isinstance(input_ids, Tensor) else input_ids)
        x = self.embed_tokens(ids)
        x = constrain_activation(x)
        for layer in self.layers:
            x = constrain_activation(layer(x))
        x = self.norm(x)
        if labels is not None:
            loss, logits = lm_head_loss(
                x, self.lm_head, labels, self.config.vocab_size
            )
            return {"loss": loss, "logits": logits}
        return {"logits": self.lm_head(x)}

    def generate(self, input_ids, max_new_tokens: int, temperature: float = 0.0,
                 rng=None, quantize_weights=None, **kwargs):
        from .generation import generate

        return generate(self, input_ids, max_new_tokens, temperature, rng,
                        quantize_weights=quantize_weights, **kwargs)

    @property
    def num_flops_per_token(self) -> float:
        n = self.num_parameters
        c = self.config
        # attention width is H*d, which equals hidden_size only for the
        # derived default (decoupled-head variants like Mistral-Nemo differ)
        attn_width = c.num_attention_heads * c.resolved_head_dim
        attn = 12 * c.num_hidden_layers * attn_width * c.max_position_embeddings
        return 6 * n + attn

    # -- cached decode hooks (generic engine in models/generation.py) -------
    def _decoder_spec(self):
        from .generation import DecoderSpec

        cfg = self.config
        return DecoderSpec(
            family=LLAMA_DECODER,
            cfg=_LlamaDecodeCfg(
                n_head=cfg.num_attention_heads,
                n_kv_head=cfg.num_key_value_heads,
                head_dim=cfg.resolved_head_dim,
                eps=cfg.rms_norm_eps,
                theta=cfg.rope_theta,
                rope_scaling=cfg.rope_scaling,
            ),
            max_len=cfg.max_position_embeddings,
            stack=self._stack_decoder_params,
        )

    def _stack_decoder_params(self) -> tuple[dict, dict]:
        layer_stacks = [layer.param_tensors() for layer in self.layers]
        layers = {
            key: jnp.stack([ts[i].data for ts in layer_stacks])
            for i, key in enumerate(_LAYER_KEYS)
        }
        g = {
            "wte": self.embed_tokens.weight.data,
            "norm_w": self.norm.weight.data,
            "head_w": self.lm_head.weight.data,
        }
        return g, layers


@dataclasses.dataclass(frozen=True)
class _LlamaDecodeCfg:
    n_head: int
    n_kv_head: int
    head_dim: int
    eps: float
    theta: float
    rope_scaling: "RopeScaling | None" = None


def _dec_embed(g, ids, positions, cfg):
    return g["wte"][ids]


def _dec_attn_in(l, x, positions, cfg):
    return llama_attn_in(
        l, x, positions,
        n_head=cfg.n_head, n_kv_head=cfg.n_kv_head, eps=cfg.eps, theta=cfg.theta,
        rope_scaling=cfg.rope_scaling,
    )


def _dec_attn_out(l, x, att, cfg):
    return llama_attn_out(l, x, att, eps=cfg.eps)


def _dec_finalize(g, x, cfg):
    x = _pure_rmsnorm(x[:, -1], g["norm_w"], cfg.eps)
    return x @ g["head_w"].T


def _make_llama_decoder():
    from .generation import DecoderFamily

    return DecoderFamily(
        embed=_dec_embed,
        attn_in=_dec_attn_in,
        attn_out=_dec_attn_out,
        finalize=_dec_finalize,
    )


LLAMA_DECODER = _make_llama_decoder()
