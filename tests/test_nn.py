import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
from accelerate_tpu.nn import F, Tensor


@pytest.fixture(autouse=True)
def _seed():
    nn.manual_seed(0)


def test_tensor_basic_ops():
    x = Tensor(jnp.arange(4.0))
    y = (x + 1) * 2 - 0.5
    np.testing.assert_allclose(y.numpy(), (np.arange(4.0) + 1) * 2 - 0.5)
    assert (x @ x).item() == pytest.approx(14.0)
    assert x.reshape(2, 2).shape == (2, 2)
    assert x.unsqueeze(0).shape == (1, 4)


def test_backward_simple():
    x = Tensor(jnp.array(3.0), requires_grad=True)
    y = x * x + 2 * x  # dy/dx = 2x + 2 = 8
    y.backward()
    assert float(x.grad) == pytest.approx(8.0)


def test_backward_matches_jax_grad():
    w = jax.random.normal(jax.random.key(1), (4, 3))
    b = jax.random.normal(jax.random.key(2), (3,))
    x = jax.random.normal(jax.random.key(3), (5, 4))

    def loss_fn(w_, b_):
        return jnp.tanh(x @ w_ + b_).sum()

    gw, gb = jax.grad(loss_fn, argnums=(0, 1))(w, b)

    tw = Tensor(w, requires_grad=True)
    tb = Tensor(b, requires_grad=True)
    loss = (Tensor(x) @ tw + tb).tanh().sum()
    loss.backward()
    np.testing.assert_allclose(tw.grad, gw, rtol=1e-5)
    np.testing.assert_allclose(tb.grad, gb, rtol=1e-5)


def test_grad_accumulates():
    x = Tensor(jnp.array(2.0), requires_grad=True)
    (x * x).backward()
    (x * x).backward()
    assert float(x.grad) == pytest.approx(8.0)  # 4 + 4


def test_diamond_graph():
    x = Tensor(jnp.array(2.0), requires_grad=True)
    a = x * 3
    b = x + 1
    y = a * b  # y = 3x(x+1) = 3x^2+3x → dy/dx = 6x+3 = 15
    y.backward()
    assert float(x.grad) == pytest.approx(15.0)


def test_no_grad():
    x = Tensor(jnp.array(2.0), requires_grad=True)
    with nn.no_grad():
        y = x * x
    assert y._node is None
    y2 = x * x
    assert y2._node is not None


def test_integer_input_no_grad_crash():
    ids = Tensor(jnp.array([0, 1]))
    emb = Tensor(jnp.ones((3, 2)), requires_grad=True)
    out = F.embedding(ids, emb)
    out.sum().backward()
    assert emb.grad is not None
    np.testing.assert_allclose(np.asarray(emb.grad).sum(), 4.0)


def test_linear_layer_grads():
    layer = nn.Linear(4, 2)
    x = Tensor(jnp.ones((3, 4)))
    out = layer(x)
    assert out.shape == (3, 2)
    out.sum().backward()
    assert layer.weight.grad.shape == (2, 4)
    assert layer.bias.grad.shape == (2,)
    np.testing.assert_allclose(layer.weight.grad, np.ones((2, 4)) * 3, rtol=1e-6)


def test_module_traversal_and_state_dict():
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    names = [n for n, _ in model.named_parameters()]
    assert names == ["0.weight", "0.bias", "2.weight", "2.bias"]
    sd = model.state_dict()
    assert sd["0.weight"].shape == (8, 4)
    model2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model2.load_state_dict(sd)
    np.testing.assert_array_equal(model2.state_dict()["2.bias"], sd["2.bias"])


def test_load_state_dict_strict_mismatch():
    model = nn.Linear(2, 2)
    with pytest.raises(KeyError):
        model.load_state_dict({"nope": jnp.ones(2)})


def test_train_eval_dropout():
    drop = nn.Dropout(0.5)
    x = Tensor(jnp.ones((100,)))
    drop.eval()
    np.testing.assert_array_equal(drop(x).numpy(), np.ones(100))
    drop.train()
    out = drop(x).numpy()
    assert (out == 0).any() and (out > 1).any()


def test_cross_entropy_matches_manual():
    logits = jnp.array([[2.0, 0.5, 0.1], [0.1, 3.0, 0.2]])
    labels = jnp.array([0, 1])
    loss = F.cross_entropy(Tensor(logits), labels)
    expected = -np.mean(
        [
            jax.nn.log_softmax(logits[0])[0],
            jax.nn.log_softmax(logits[1])[1],
        ]
    )
    assert loss.item() == pytest.approx(float(expected), rel=1e-6)


def test_cross_entropy_ignore_index():
    logits = jnp.array([[2.0, 0.5], [0.1, 3.0], [1.0, 1.0]])
    labels = jnp.array([0, 1, -100])
    loss = F.cross_entropy(Tensor(logits), labels, ignore_index=-100)
    expected = -np.mean(
        [jax.nn.log_softmax(logits[0])[0], jax.nn.log_softmax(logits[1])[1]]
    )
    assert loss.item() == pytest.approx(float(expected), rel=1e-6)


def test_layer_norm_stats():
    ln = nn.LayerNorm(16)
    x = Tensor(jax.random.normal(jax.random.key(0), (4, 16)) * 5 + 3)
    out = ln(x).numpy()
    np.testing.assert_allclose(out.mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1, atol=1e-2)


def test_sdpa_causal():
    q = jax.random.normal(jax.random.key(0), (2, 2, 8, 4))
    out = F.scaled_dot_product_attention(Tensor(q), Tensor(q), Tensor(q), is_causal=True)
    assert out.shape == (2, 2, 8, 4)
    # first position can only attend to itself → output == v[..., 0, :]
    np.testing.assert_allclose(out.numpy()[:, :, 0], q[:, :, 0], rtol=2e-5)


def test_sdpa_grads_flow():
    q = Tensor(jax.random.normal(jax.random.key(0), (1, 1, 4, 4)), requires_grad=True)
    out = F.scaled_dot_product_attention(q, q, q)
    out.sum().backward()
    assert q.grad is not None and q.grad.shape == (1, 1, 4, 4)


def test_conv2d_shapes_and_grads():
    conv = nn.Conv2d(3, 8, 3, stride=1, padding=1)
    x = Tensor(jnp.ones((2, 3, 8, 8)))
    out = conv(x)
    assert out.shape == (2, 8, 8, 8)
    out.mean().backward()
    assert conv.weight.grad.shape == (8, 3, 3, 3)


def test_functional_call_restores():
    layer = nn.Linear(2, 2)
    orig = layer.param_pytree()
    new_params = {k: jnp.zeros_like(v) for k, v in orig.items()}
    out = layer._functional_call(new_params, Tensor(jnp.ones((1, 2))))
    np.testing.assert_array_equal(out.numpy(), np.zeros((1, 2)))
    np.testing.assert_array_equal(layer.weight.data, orig["weight"])


def test_tape_under_jit_capture():
    """The same imperative code traced under jax.jit must produce a fused
    step: params in, (loss, grads) out."""
    layer = nn.Linear(4, 1)

    def step(params, x, y):
        layer.bind_params(params)
        pred = layer(Tensor(x))
        loss = F.mse_loss(pred.squeeze(-1), Tensor(y))
        loss.backward()
        grads = {name: p.grad for name, p in layer.named_parameters()}
        for p in layer.parameters():
            p.grad = None
        return loss.data, grads

    jitted = jax.jit(step)
    x = jax.random.normal(jax.random.key(0), (8, 4))
    y = jax.random.normal(jax.random.key(1), (8,))
    params = layer.param_pytree()
    loss, grads = jitted(params, x, y)

    def pure_loss(p):
        return jnp.mean((x @ p["weight"].T + p["bias"])[:, 0] - y) ** 2 if False else jnp.mean(((x @ p["weight"].T)[:, 0] + p["bias"][0] - y) ** 2)

    expected_grads = jax.grad(pure_loss)(params)
    np.testing.assert_allclose(grads["weight"], expected_grads["weight"], rtol=1e-4)
    np.testing.assert_allclose(grads["bias"], expected_grads["bias"], rtol=1e-4)


def test_meta_init_consumes_no_rng():
    """init_empty_weights must not advance the RNG stream or allocate
    (code-review regression), in both include_buffers modes."""
    import accelerate_tpu.nn.random as nn_random
    from accelerate_tpu.big_modeling import init_empty_weights

    for include_buffers in (True, False):
        nn.manual_seed(123)
        before = nn_random.default_rng._counter
        with init_empty_weights(include_buffers=include_buffers):
            nn.Linear(64, 64)
        assert nn_random.default_rng._counter == before, include_buffers


def test_tensor_jax_and_numpy_conversion():
    """jnp.asarray/np.asarray on a Tensor unwrap the data directly — the
    sequence-iteration fallback cost one tape op PER ELEMENT (found via a
    BERT forward that hung for minutes on a (2,16) batch)."""
    import time

    import jax.numpy as jnp
    import numpy as np

    from accelerate_tpu.nn import Tensor

    t = Tensor(jnp.arange(64, dtype=jnp.int32).reshape(4, 16))
    t0 = time.perf_counter()
    a = jnp.asarray(t)
    b = np.asarray(t)
    c = np.asarray(t, dtype=np.float32)
    assert time.perf_counter() - t0 < 1.0  # element-walk took minutes
    assert a.shape == (4, 16) and a.dtype == jnp.int32
    np.testing.assert_array_equal(b, np.arange(64).reshape(4, 16))
    assert c.dtype == np.float32
