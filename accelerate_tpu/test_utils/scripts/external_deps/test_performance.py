"""Accuracy-parity across execution modes (analog of reference
test_utils/scripts/external_deps/test_performance.py).

The reference trains bert-base on MRPC under each distributed backend and
asserts the eval accuracy/F1 stay above a threshold.  Zero-egress analog:
a tiny native BERT classifier on a deterministic, linearly-separable token
task, trained three ways —

* eager tape loop (the debugging path),
* ``compile_step``-captured loop (the perf path),
* captured loop with gradient accumulation (2 micro-steps),

— all from identical seeds.  Final train accuracy must clear an absolute
floor AND the three runs must agree within a tolerance, which is the same
contract the reference enforces between backends.
"""

from __future__ import annotations

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, set_seed
from accelerate_tpu.models.bert import BertConfig, BertForSequenceClassification
from accelerate_tpu.state import PartialState

VOCAB = 64
SEQ = 16
N = 256
BATCH = 32
EPOCHS = 8
ACC_FLOOR = 0.80
PARITY_TOL = 0.08


def _tiny_config() -> BertConfig:
    return BertConfig(
        vocab_size=VOCAB,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=SEQ,
        hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
        num_labels=2,
    )


def _make_data(seed: int = 0):
    """Label = whether tokens from the 'positive' half dominate."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, VOCAB, size=(N, SEQ), dtype=np.int32)
    labels = (np.sum(ids >= VOCAB // 2, axis=1) > SEQ // 2).astype(np.int64)
    return ids, labels


def _train(mode: str) -> float:
    set_seed(42)
    accum = 2 if mode == "captured_accum" else 1
    acc = Accelerator(gradient_accumulation_steps=accum)
    model = BertForSequenceClassification(_tiny_config())
    opt = optim.AdamW(model.parameters(), lr=5e-3)
    model, opt = acc.prepare(model, opt)
    ids, labels = _make_data()

    def loop_body(batch_ids, batch_labels):
        out = model(batch_ids, labels=batch_labels)
        acc.backward(out["loss"])
        opt.step()
        opt.zero_grad()
        return out["loss"]

    step = acc.compile_step(loop_body) if mode.startswith("captured") else loop_body

    micro = BATCH // accum
    for _ in range(EPOCHS):
        for start in range(0, N, micro):
            with acc.accumulate(model):
                step(ids[start : start + micro], labels[start : start + micro])

    model.eval()
    logits = model(ids)["logits"]
    preds = np.asarray(logits.data).argmax(-1)
    accuracy = float((preds == labels).mean())
    PartialState._reset_state()
    return accuracy


def main():
    results = {m: _train(m) for m in ("eager", "captured", "captured_accum")}
    print("accuracies:", results)
    for mode, accuracy in results.items():
        assert accuracy >= ACC_FLOOR, f"{mode}: {accuracy:.3f} < floor {ACC_FLOOR}"
    spread = max(results.values()) - min(results.values())
    assert spread <= PARITY_TOL, f"parity spread {spread:.3f} > {PARITY_TOL}"
    print("All performance-parity checks passed")


if __name__ == "__main__":
    main()
