"""1F1B fused pipeline schedule: gradient parity with GPipe + memory window.

Round-2 verdict Missing #4: GPipe fill-drain holds num_microbatches stage
inputs alive through the backward; the reference gets 1F1B from
megatron.core's get_forward_backward_func (reference utils/megatron_lm.py:40,
train_step :1035).  Here 1F1B is a fused fwd+bwd shard_map loop
(parallel/pipeline.py): loss computed inside the last stage, cotangents hop
down-ring while later microbatches still flow up, and each stage stores only
``2·S−1`` inputs regardless of M.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.models import GPTConfig, PipelinedGPTLMHeadModel
from accelerate_tpu.parallel.pipeline import (
    bubble_fraction,
    bubble_ticks,
    residual_window,
    schedule_ticks,
)
from accelerate_tpu.utils.dataclasses import PipelineParallelPlugin


def test_memory_window_beats_gpipe_at_m8_s2():
    """At M=8, S=2 the 1F1B window is 3 stage inputs vs GPipe's 8."""
    assert residual_window(2) == 3
    assert residual_window(4) == 7
    # bubble profile: M + 2S - 2 fused cycles (each = 1 fwd + 1 bwd slot)
    assert schedule_ticks(8, 2) == 10


def test_interleaved_profile_m8_s2_v2():
    """The virtual factor's analytic profile (ISSUE 15 acceptance): at
    M=8, S=2, V=2 the interleaved schedule shows STRICTLY fewer bubble
    ticks than the fused one (compared in a common chunk granularity),
    the bubble fraction drops from (S−1)/M to (S−1)/(V·M), the lockstep
    trip count is M·V + S·V + S − 2 chunk ticks, and the residual window
    keeps the 2·S−1 order per hosted span (V·(2S−1) chunk inputs, each
    1/V the fused activation)."""
    fused = bubble_ticks(8, 2, virtual=1, granularity=2)
    interleaved = bubble_ticks(8, 2, virtual=2, granularity=2)
    assert interleaved < fused, (interleaved, fused)
    assert (fused, interleaved) == (4, 2)
    assert bubble_fraction(8, 2, 2) < bubble_fraction(8, 2, 1)
    assert bubble_fraction(8, 2, 2) == (2 - 1) / (2 * 8)
    assert schedule_ticks(8, 2, virtual=2) == 20
    assert residual_window(2, virtual=2) == 6
    # degenerate V=1 reproduces the fused profile exactly
    assert schedule_ticks(8, 2, virtual=1) == schedule_ticks(8, 2)
    assert residual_window(2, virtual=1) == residual_window(2)


def _train(schedule: str, steps: int = 3, microbatches: int = 8,
           n_layer: int = 2, virtual: int = 0):
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(pp_size=2),
        pp_plugin=PipelineParallelPlugin(
            pp_size=2, num_microbatches=microbatches, schedule=schedule,
            virtual_stages=virtual,
        ),
        mixed_precision="no",
    )
    cfg = GPTConfig.tiny()
    if n_layer != cfg.n_layer:
        import dataclasses as _dc

        cfg = _dc.replace(cfg, n_layer=n_layer)
    model = PipelinedGPTLMHeadModel(cfg, num_microbatches=microbatches)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    ids = batch_to_global_array(
        jnp.asarray(
            np.random.default_rng(0).integers(0, 1024, (32, 32)), jnp.int32
        ),
        mesh=acc.mesh,
    )
    losses = [float(step(ids)) for _ in range(steps)]
    params = {n: np.asarray(p.data) for n, p in model.named_parameters()}
    return losses, params


def test_loss_and_grad_parity_with_gpipe():
    """Same init, same data: 1F1B must train identically to GPipe — loss
    trajectory AND updated parameters (grads) agree."""
    l_g, p_g = _train("gpipe")
    l_f, p_f = _train("1f1b")
    np.testing.assert_allclose(l_f, l_g, rtol=2e-5, atol=2e-5)
    for name in p_g:
        np.testing.assert_allclose(
            p_f[name], p_g[name], rtol=3e-4, atol=3e-5, err_msg=name
        )


def test_ignore_index_parity():
    """-100 padded labels must drop out of the fused loss exactly like the
    gpipe path's F.cross_entropy ignore_index."""
    import jax

    from accelerate_tpu.models.gpt import (
        _pure_lm_head_loss,
        lm_shift_loss,
    )
    from accelerate_tpu.nn import Tensor

    rng = np.random.default_rng(0)
    b, s, c, v = 2, 8, 16, 32
    h = jnp.asarray(rng.normal(size=(b, s, c)), jnp.float32)
    labels = rng.integers(0, v, (b, s)).astype(np.int32)
    labels[:, -3:] = -100  # padded tail
    ln_w, ln_b = jnp.ones((c,)), jnp.zeros((c,))
    head_w = jnp.asarray(rng.normal(size=(v, c)), jnp.float32)
    lsum, w = _pure_lm_head_loss(
        h, jnp.asarray(labels), (ln_w, ln_b, head_w), eps=1e-5
    )
    got = float(lsum) / float(w)
    # reference: the tape-path math on the same arrays
    from accelerate_tpu.models.gpt import _pure_layernorm

    logits = Tensor(_pure_layernorm(h, ln_w, ln_b, 1e-5) @ head_w.T)
    want = float(lm_shift_loss(logits, jnp.asarray(labels), v).data)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_padded_label_parity_between_schedules():
    """UNEVEN -100 padding across microbatches: the fused loss must still be
    the global token mean, not a mean of per-microbatch means (which would
    over-weight heavily-padded microbatches)."""
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 1024, (32, 32)).astype(np.int32)
    labels = ids.copy()
    # ragged padding: rows get anywhere from 0 to 24 trailing -100s
    for i in range(32):
        pad = int(rng.integers(0, 25))
        if pad:
            labels[i, -pad:] = -100

    def run(schedule):
        Accelerator._reset_state()
        nn.manual_seed(0)
        acc = Accelerator(
            parallelism_config=ParallelismConfig(pp_size=2),
            pp_plugin=PipelineParallelPlugin(
                pp_size=2, num_microbatches=8, schedule=schedule
            ),
            mixed_precision="no",
        )
        model = PipelinedGPTLMHeadModel(GPTConfig.tiny(), num_microbatches=8)
        opt = optim.SGD(model.parameters(), lr=0.1)
        model, opt = acc.prepare(model, opt)

        def step_fn(x, y):
            opt.zero_grad()
            out = model(x, labels=y)
            acc.backward(out["loss"])
            opt.step()
            return out["loss"]

        step = acc.compile_step(step_fn)
        x = batch_to_global_array(jnp.asarray(ids), mesh=acc.mesh)
        y = batch_to_global_array(jnp.asarray(labels), mesh=acc.mesh)
        losses = [float(step(x, y)) for _ in range(2)]
        return losses, {n: np.asarray(p.data) for n, p in model.named_parameters()}

    l_g, p_g = run("gpipe")
    l_f, p_f = run("1f1b")
    np.testing.assert_allclose(l_f, l_g, rtol=2e-5, atol=2e-5)
    for name in p_g:
        np.testing.assert_allclose(
            p_f[name], p_g[name], rtol=3e-4, atol=3e-5, err_msg=name
        )


def test_1f1b_loss_decreases():
    losses, _ = _train("1f1b", steps=4)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_interleaved_grad_parity_with_gpipe_at_v2():
    """ISSUE 15 acceptance: the interleaved schedule (V=2, each device
    hosting two non-contiguous layer spans) trains identically to GPipe —
    loss trajectory AND updated parameters agree on a 4-layer trunk."""
    l_g, p_g = _train("gpipe", n_layer=4)
    l_i, p_i = _train("interleaved", n_layer=4, virtual=2)
    np.testing.assert_allclose(l_i, l_g, rtol=2e-5, atol=2e-5)
    for name in p_g:
        np.testing.assert_allclose(
            p_i[name], p_g[name], rtol=3e-4, atol=3e-5, err_msg=name
        )


def test_interleaved_matches_fused_1f1b():
    """Same seed/data: interleaving is a schedule/layout change, not a
    numerics change — V=2 must track the fused 1F1B trajectory."""
    l_f, p_f = _train("1f1b", n_layer=4)
    l_i, p_i = _train("interleaved", n_layer=4, virtual=2)
    np.testing.assert_allclose(l_i, l_f, rtol=2e-5, atol=2e-5)
    for name in p_f:
        np.testing.assert_allclose(
            p_i[name], p_f[name], rtol=3e-4, atol=3e-5, err_msg=name
        )


def test_interleaved_rejects_indivisible_shapes():
    """Bad geometry fails loudly at construction (plan resolution), not
    mid-first-step: M not divisible by S, layers not divisible by S·V."""
    with pytest.raises(ValueError, match="divisible"):
        _train("interleaved", microbatches=3, n_layer=4, virtual=2)
    # layers 2 vs S·V = 4: the layer-order derivation refuses
    from accelerate_tpu.parallel.plan import StagePlan

    with pytest.raises(ValueError, match="not divisible"):
        StagePlan(
            num_stages=2, virtual=2, num_microbatches=8,
            schedule="interleaved",
        ).layer_order(2)


def test_1f1b_rejects_sequence_parallel():
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(pp_size=2, sp_size=2),
        pp_plugin=PipelineParallelPlugin(pp_size=2, schedule="1f1b"),
    )
    model = PipelinedGPTLMHeadModel(GPTConfig.tiny(), num_microbatches=2)
    model, = (acc.prepare(model),)
    ids = batch_to_global_array(
        jnp.zeros((8, 32), jnp.int32), mesh=acc.mesh
    )
    with pytest.raises(NotImplementedError, match="sequence parallelism"):
        model(ids, labels=ids)


def test_bad_schedule_name_rejected():
    with pytest.raises(ValueError, match="gpipe"):
        PipelineParallelPlugin(pp_size=2, schedule="zigzag")
    # interleaving is a 1F1B property: gpipe can't take a virtual factor,
    # and 'interleaved' with V=1 is a contradiction
    with pytest.raises(ValueError, match="gpipe"):
        PipelineParallelPlugin(pp_size=2, schedule="gpipe", virtual_stages=2)
    with pytest.raises(ValueError, match="virtual_stages"):
        PipelineParallelPlugin(pp_size=2, schedule="interleaved", virtual_stages=1)
