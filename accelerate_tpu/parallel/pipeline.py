"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

Counterpart of the reference's PiPPy integration (inference.py:124
``prepare_pippy`` — trace, split at layer boundaries, ScheduleGPipe) rebuilt
as SPMD: stage parameters carry a leading stage axis sharded over ``pp``;
under ``shard_map`` each device runs its own stage and activations hop to the
next stage with ``lax.ppermute`` each tick.  ``T = num_microbatches +
num_stages - 1`` ticks fill and drain the pipeline; everything is pure jnp so
JAX transposes it for training as well as inference.

On TPU slices GSPMD tensor/data sharding usually beats PP (ICI is fast and
XLA overlaps collectives); PP earns its keep across slices (DCN) — which is
why it is a mesh axis here and composes with dp/fsdp/tp rather than being a
separate engine.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _gpipe_local(stage_params, x_mb, *, stage_fn, axis_name: str, num_microbatches: int):
    """Per-device GPipe schedule under shard_map.

    stage_params: this stage's params (leading stage axis already split away).
    x_mb: (M, mb, ...) microbatched input (only stage 0 reads it).
    Returns (M, mb, ...) outputs (only the last stage's are meaningful).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage_idx = jax.lax.axis_index(axis_name)
    M = num_microbatches
    T = M + n_stages - 1

    # activation probe to get output shape/dtype of one stage
    sample_out = jax.eval_shape(lambda p, x: stage_fn(p, x), stage_params, x_mb[0])
    act0 = jnp.zeros(sample_out.shape, sample_out.dtype)
    outputs0 = jnp.zeros((M,) + sample_out.shape, sample_out.dtype)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(t, carry):
        incoming, outputs = carry
        mb_idx = t - stage_idx
        active = jnp.logical_and(mb_idx >= 0, mb_idx < M)
        # stage 0 reads its microbatch; later stages use the ring input
        x_idx = jnp.clip(mb_idx, 0, M - 1)
        my_input = jnp.where(
            stage_idx == 0,
            jax.lax.dynamic_index_in_dim(x_mb, x_idx, keepdims=False).astype(incoming.dtype)
            if x_mb.shape[1:] == incoming.shape
            else incoming,
            incoming,
        )
        out = stage_fn(stage_params, my_input)
        out = jnp.where(active, out, jnp.zeros_like(out))
        # last stage records its finished microbatch
        outputs = jax.lax.cond(
            jnp.logical_and(active, stage_idx == n_stages - 1),
            lambda o: jax.lax.dynamic_update_index_in_dim(o, out, x_idx, 0),
            lambda o: o,
            outputs,
        )
        # all stages forward their activation to the next stage
        nxt = jax.lax.ppermute(out, axis_name, perm)
        return nxt, outputs

    _, outputs = jax.lax.fori_loop(0, T, tick, (act0, outputs0))
    # only the last stage holds real outputs; broadcast them around the ring
    # so the result is replicated over pp (callers slice/psum as needed)
    outputs = jax.lax.psum(
        jnp.where(stage_idx == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    return outputs


def gpipe(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = "pp",
    batch_axes: tuple = ("dp", "fsdp"),
):
    """Run ``stage_fn(params_i, x)`` as a pipeline over the ``pp`` axis.

    ``stacked_params``: pytree whose leaves have a leading ``num_stages`` axis
    (stage i's slice feeds device i).  ``x``: (batch, ...) global input —
    reshaped to (num_microbatches, batch/M, ...).

    Constraint (GPipe classic): every stage must map activations to the same
    shape/dtype.  Embedding/head layers live outside the pipelined trunk.
    """
    if mesh is None:
        from ..state import AcceleratorState

        mesh = AcceleratorState().mesh
    n_stages = mesh.shape.get(axis_name, 1)
    if n_stages == 1:
        # degenerate: sequential scan over stages on one device group
        def body(h, p):
            return stage_fn(p, h), None

        out, _ = jax.lax.scan(body, x, stacked_params)
        return out

    b = x.shape[0]
    if b % num_microbatches != 0:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches {num_microbatches}"
        )
    x_mb = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])

    from jax.experimental.shard_map import shard_map

    batch_spec = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1) or None
    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params
    )
    x_spec = P(None, batch_spec)
    out_spec = P(None, batch_spec)

    fn = shard_map(
        functools.partial(
            _gpipe_local,
            stage_fn=lambda p, h: stage_fn(
                jax.tree_util.tree_map(lambda a: a[0], p), h
            ),
            axis_name=axis_name,
            num_microbatches=num_microbatches,
        ),
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=out_spec,
        check_rep=False,
    )
    out_mb = fn(stacked_params, x_mb)
    return out_mb.reshape(b, *out_mb.shape[2:])
