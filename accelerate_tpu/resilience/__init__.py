"""Resilience subsystem (``accelerator.resilience``) — docs/resilience.md.

Four pillars, all default-OFF (off = byte-identical capture hot path, one
``None``-check, matching the telemetry precedent):

1. **Hardened backend init** (`backend.py`) — subprocess-isolated PJRT probe
   with retry/backoff/jitter and an ordered platform fallback chain, emitting
   a structured :class:`~.backend.InitReport`.
2. **Preemption-safe checkpointing** (`preemption.py`) — SIGTERM/SIGINT set a
   sticky flag read via ``resilience.should_save`` / ``should_exit``
   (``check_trigger()``-style, collective on multi-process);
   :meth:`Resilience.drain` checkpoints through the existing async
   ``save_state``/``wait_for_checkpoint`` machinery so a preempted run always
   exits with a complete checkpoint.  An optional wall-clock deadline covers
   scheduled maintenance windows.
3. **Step retry with rollback** (`retry.py`) — transient dispatch failures
   are retried with bounded backoff; on exhaustion the last good checkpoint
   is restored and the step replayed against the same compiled program.
4. **Deterministic fault injection** (`inject.py`) — ``ACCELERATE_FAULT_PLAN``
   simulates init hangs, transient dispatch faults and mid-step SIGTERM so
   all of the above is testable on CPU.

Enable with ``ACCELERATE_RESILIENCE=1`` or
``Accelerator(kwargs_handlers=[ResilienceKwargs(enabled=True)])``.
"""

from __future__ import annotations

from typing import Optional

from .backend import InitReport, init_backend, probe_backend_once
from .inject import FaultInjector, FaultPlan, InjectedTransientError
from .preemption import PreemptionGuard
from .retry import StepRetrier, classify_failure


class Resilience:
    """Per-Accelerator resilience hub; inert when disabled."""

    def __init__(self, handler=None, telemetry=None):
        if handler is None:
            from ..utils.dataclasses import ResilienceKwargs

            handler = ResilienceKwargs()
        self.handler = handler
        self.enabled = bool(handler.enabled)
        # events always land here (tests / diagnostics need them with
        # telemetry off); they additionally flow into the telemetry export
        # stream as kind="resilience" records when telemetry is on
        self.telemetry = (
            telemetry if (telemetry is not None and getattr(telemetry, "enabled", False)) else None
        )
        self.events: list[dict] = []
        # the owning Accelerator's enabled Fleet hub, when the elastic fleet
        # runtime is armed (docs/elastic.md): the retrier consults it to
        # turn the historical multi-process rollback refusal into the
        # coordinated all-ranks restore protocol
        self.fleet = None
        self.injector: Optional[FaultInjector] = None
        self.guard: Optional[PreemptionGuard] = None
        self.retrier: Optional[StepRetrier] = None
        self.last_checkpoint: Optional[str] = None
        self.dispatch_calls = 0
        # preemption-poll memo: (dispatch_calls at poll time, result) — the
        # collective gather runs at most once per step even when the loop
        # reads both should_save and should_exit; a positive result is
        # sticky forever (the flags never un-trip)
        self._poll_cache: Optional[tuple[int, bool]] = None
        self._poll_resolved = False
        if not self.enabled:
            return
        self.injector = FaultInjector.from_spec(handler.fault_plan)
        if handler.preemption:
            self.guard = PreemptionGuard(
                deadline_s=handler.deadline_s, on_trigger=self._on_signal
            )
            self.guard.install()
        if handler.retry:
            self.retrier = StepRetrier(
                self,
                max_retries=handler.max_retries,
                backoff_s=handler.retry_backoff_s,
                rollback=handler.rollback,
            )
        # an init that ran before this hub existed (PartialState hardening,
        # bench.py's probe) still lands in the event stream; consumed on
        # pickup so a later hub in the same process doesn't re-emit a stale
        # report as its own
        from . import backend as _backend

        if _backend.LAST_INIT_REPORT is not None:
            self.record_event(**_backend.LAST_INIT_REPORT.to_event())
            _backend.LAST_INIT_REPORT = None

    # -- events --------------------------------------------------------------
    def record_event(self, event: str, **fields) -> dict:
        payload = {"event": event, **fields}
        self.events.append(payload)
        if self.telemetry is not None:
            self.telemetry.record_resilience(dict(payload))
        # scalar mirror into the flight ring: preemption / retry / rollback
        # phases are exactly what a postmortem needs, and the ring survives
        # where an unflushed telemetry JSONL does not (docs/telemetry.md)
        from ..telemetry import flightrec

        flightrec.record(
            "resilience",
            event=event,
            **{k: v for k, v in fields.items()
               if v is None or isinstance(v, (bool, int, float, str))},
        )
        return payload

    def _on_signal(self, signum: int) -> None:
        self.record_event(
            "preemption",
            signal=self.guard.signal_name if self.guard is not None else signum,
            dispatch_calls=self.dispatch_calls,
        )

    # -- capture-path hook ---------------------------------------------------
    def begin_dispatch(self) -> int:
        """Called by CapturedStep right before each dispatch; counts calls
        (the fault plan's step axis) and fires any scheduled SIGTERM."""
        index = self.dispatch_calls
        self.dispatch_calls += 1
        if self.injector is not None:
            self.injector.maybe_sigterm(index)
            self.injector.maybe_hang(index)
        return index

    # -- preemption flags ----------------------------------------------------
    def _poll(self) -> bool:
        if self._poll_resolved:
            return True  # sticky: a tripped flag never un-trips
        local = bool(
            self.guard is not None
            and (self.guard.triggered or self.guard.deadline_reached())
        )
        from ..state import PartialState

        if PartialState._shared_state and PartialState().num_processes > 1:
            # collective (check_trigger-style): ANY preempted rank means every
            # rank must drain — the save's gathers need all of them anyway.
            # Memoized per dispatch: reading should_save AND should_exit in
            # one loop iteration costs one gather, not two (every rank runs
            # the same loop, so the gather count stays aligned).
            if (
                self._poll_cache is not None
                and self._poll_cache[0] == self.dispatch_calls
            ):
                return self._poll_cache[1]
            from ..utils import operations as ops

            result = any(bool(flag) for flag in ops.gather_object([local]))
            self._poll_cache = (self.dispatch_calls, result)
        else:
            result = local
        if result:
            self._poll_resolved = True
        return result

    @property
    def should_save(self) -> bool:
        """True once a preemption signal landed or the deadline passed.
        Collective on multi-process — call it on every rank."""
        return self._poll()

    @property
    def should_exit(self) -> bool:
        """Alias flag for loop structure (save at should_save, break at
        should_exit); both read the same sticky trigger."""
        return self._poll()

    # -- checkpoint bookkeeping ----------------------------------------------
    def note_checkpoint(self, path: Optional[str]) -> None:
        """Record a durable checkpoint (rollback target).  Accelerator calls
        this after every successful ``save_state``."""
        if path:
            self.last_checkpoint = path

    def drain(self, accelerator, output_dir: Optional[str] = None) -> str:
        """Save a complete checkpoint NOW and block until it is durable —
        the preemption exit path.  Uses the async save machinery (prepare on
        the main thread, write on the writer, finalize on join) and returns
        the checkpoint directory."""
        target = output_dir or self.handler.checkpoint_dir
        out = accelerator.save_state(target, async_save=True)
        accelerator.wait_for_checkpoint()
        self.note_checkpoint(out)
        self.record_event(
            "drain",
            checkpoint=out,
            signal=self.guard.signal_name if self.guard is not None else None,
        )
        return out

    def close(self) -> None:
        """Restore signal handlers (end_training / test teardown)."""
        if self.guard is not None:
            self.guard.uninstall()


__all__ = [
    "FaultInjector",
    "FaultPlan",
    "InitReport",
    "InjectedTransientError",
    "PreemptionGuard",
    "Resilience",
    "StepRetrier",
    "classify_failure",
    "init_backend",
    "probe_backend_once",
]
