"""Chunked fused LM-head + cross-entropy: exact parity with the dense path.

The op replaces ``cross_entropy(h @ W^T, labels)`` without materializing the
(N, V) logits (nn/functional.py:_chunked_head_ce) — these tests pin the
value AND both gradients to the dense reference, across chunk sizes that
divide, exceed, and straddle the vocab, with ignore_index masking.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
from accelerate_tpu.nn import functional as F
from accelerate_tpu.nn.tape import Tensor


def _setup(n=24, c=16, v=37, seed=0):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.normal(size=(n, c)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(v, c)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, size=(n,)), jnp.int32)
    # mask a tail like the LM shift does
    labels = labels.at[-3:].set(-100)
    return h, w, labels


def _dense(h, w, labels):
    def loss_fn(h, w):
        logits = h @ w.T
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        mask = labels != -100
        safe = jnp.where(mask, labels, 0)
        ll = jnp.take_along_axis(logits.astype(jnp.float32), safe[:, None], 1)[:, 0]
        return jnp.where(mask, lse - ll, 0.0).sum() / jnp.maximum(mask.sum(), 1)

    val = loss_fn(h, w)
    gh, gw = jax.grad(loss_fn, argnums=(0, 1))(h, w)
    return float(val), np.asarray(gh), np.asarray(gw)


@pytest.mark.parametrize("chunk", [8, 16, 37, 64, 13])
def test_value_and_grads_match_dense(chunk):
    h, w, labels = _setup()
    want_val, want_gh, want_gw = _dense(h, w, labels)

    fused = F._chunked_head_ce(labels, -100, w.shape[0], chunk)
    got_val = float(fused(h, w))
    gh, gw = jax.grad(lambda h, w: fused(h, w), argnums=(0, 1))(h, w)
    assert got_val == pytest.approx(want_val, rel=1e-6)
    np.testing.assert_allclose(np.asarray(gh), want_gh, atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gw), want_gw, atol=1e-6, rtol=1e-5)


def test_all_labels_ignored_is_zero_and_finite():
    h, w, _ = _setup()
    labels = jnp.full((h.shape[0],), -100, jnp.int32)
    fused = F._chunked_head_ce(labels, -100, w.shape[0], 16)
    val = float(fused(h, w))
    gh, gw = jax.grad(lambda h, w: fused(h, w), argnums=(0, 1))(h, w)
    assert val == 0.0
    assert np.isfinite(np.asarray(gh)).all() and np.isfinite(np.asarray(gw)).all()
    assert np.abs(np.asarray(gh)).max() == 0.0


def test_tape_level_matches_dense_reference():
    """chunked_lm_head_ce through the tape: the loss value and the head
    weight's ``.grad`` (what training actually consumes) must match the
    dense reference."""
    h, w, labels = _setup(n=16, c=8, v=21)
    wt = nn.Parameter(w)
    loss = F.chunked_lm_head_ce(Tensor(h), wt, labels, 21, chunk=8)
    want_val, _, want_gw = _dense(h, w, labels)
    assert float(loss.item()) == pytest.approx(want_val, rel=1e-6)
    loss.backward()
    np.testing.assert_allclose(np.asarray(wt.grad), want_gw, atol=1e-6, rtol=1e-5)


@pytest.mark.parametrize("precision", ["no", "bf16"])
def test_gpt_forward_flag_parity(precision):
    """With ACCELERATE_TPU_CE_CHUNK set, GPT training losses match the
    dense path (the flagship bench runs bf16 — cover both precisions)."""
    import os

    from accelerate_tpu import Accelerator
    import accelerate_tpu.optim as optim
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel

    def run(chunk_env):
        Accelerator._reset_state()
        if chunk_env:
            os.environ["ACCELERATE_TPU_CE_CHUNK"] = chunk_env
        else:
            os.environ.pop("ACCELERATE_TPU_CE_CHUNK", None)
        try:
            nn.manual_seed(0)
            acc = Accelerator(mixed_precision=precision)
            model = GPTLMHeadModel(GPTConfig.tiny())
            opt = optim.SGD(model.parameters(), lr=0.1)
            model, opt = acc.prepare(model, opt)
            ids = jnp.asarray(
                np.random.default_rng(0).integers(0, 1024, (8, 16)), jnp.int32
            )

            def fn(b):
                opt.zero_grad()
                out = model(b, labels=b)
                acc.backward(out["loss"])
                opt.step()
                return out["loss"]

            step = acc.compile_step(fn)
            return [float(step(nn.Tensor(ids))) for _ in range(3)]
        finally:
            os.environ.pop("ACCELERATE_TPU_CE_CHUNK", None)

    dense = run(None)
    chunked = run("256")
    tol = 1e-5 if precision == "no" else 2e-2  # bf16 matmul rounding differs
    np.testing.assert_allclose(chunked, dense, rtol=tol)


def test_biased_head_matches_dense():
    """GPT-J-style biased head: value and all three grads match dense."""
    h, w, labels = _setup(n=20, c=12, v=29, seed=4)
    b = jnp.asarray(np.random.default_rng(5).normal(size=(29,)), jnp.float32)

    def dense(h, w, b):
        logits = (h @ w.T + b[None, :]).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        mask = labels != -100
        safe = jnp.where(mask, labels, 0)
        ll = jnp.take_along_axis(logits, safe[:, None], 1)[:, 0]
        return jnp.where(mask, lse - ll, 0.0).sum() / jnp.maximum(mask.sum(), 1)

    want = float(dense(h, w, b))
    wgh, wgw, wgb = jax.grad(dense, argnums=(0, 1, 2))(h, w, b)

    fused = F._chunked_head_ce(labels, -100, 29, 8, has_bias=True)
    got = float(fused(h, w, b))
    gh, gw, gb = jax.grad(lambda h, w, b: fused(h, w, b), argnums=(0, 1, 2))(h, w, b)
    assert got == pytest.approx(want, rel=1e-6)
    np.testing.assert_allclose(np.asarray(gh), np.asarray(wgh), atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(wgw), atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(wgb), atol=1e-6, rtol=1e-5)
