"""Mixture-of-Experts feed-forward with expert parallelism over ``ep``.

The reference has no MoE layer at all — only a DeepSpeed leaf-module hint
(reference accelerator.py:1881, SURVEY.md §2.2 row EP) — so this is new
capability, built the TPU way (GShard/Switch formulation):

* routing, dispatch and combine are DENSE one-hot einsums over static shapes
  (tokens × experts × capacity) — no gathers, no dynamic shapes, everything
  tiles onto the MXU and ``jit`` sees one fixed program;
* the stacked expert weights carry a leading expert axis that the sharding
  planner lays on the ``ep`` mesh axis (see ``tp_plan`` entries in models
  using the layer); GSPMD then inserts the all_to_all pair around the expert
  computation — the manual NCCL alltoall of GPU MoE stacks is compiled in;
* tokens beyond an expert's capacity are dropped (their combine weight is
  zero and the residual stream carries them unchanged) — Switch semantics;
* the load-balancing auxiliary loss (Switch eq. 4: E · Σ_e f_e · P_e) is
  stashed on the module as ``last_aux_loss`` after every forward; training
  loops (e.g. models/gpt.py) add it into the objective with a small weight.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from . import init
from .module import Module, Parameter
from .tape import Tensor, tape_op


def _switch_moe_forward(
    x,  # (tokens, d_model)
    router_w,  # (E, d_model)
    router_b,  # (E,)
    w_in,  # (E, d_ff, d_model)
    b_in,  # (E, d_ff)
    w_out,  # (E, d_model, d_ff)
    b_out,  # (E, d_model)
    *,
    capacity: int,
    top_k: int,
):
    """Dense Switch/top-k MoE over flattened tokens. Returns y."""
    g, d = x.shape
    E = router_w.shape[0]

    logits = x @ router_w.T + router_b  # (g, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    combine = jnp.zeros((g, E, capacity), dtype=jnp.float32)
    remaining = probs
    # per-expert slot counters evolve as each top-k choice claims capacity
    fill = jnp.zeros((E,), dtype=jnp.int32)
    for _ in range(top_k):
        choice = jnp.argmax(remaining, axis=-1)  # (g,)
        gate = jnp.take_along_axis(remaining, choice[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.float32)  # (g, E)
        # position of each token within its chosen expert's buffer
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (g, E)
        pos = jnp.sum(pos_in_expert, axis=-1) + jnp.take(fill, choice)  # (g,)
        keep = pos < capacity
        slot = jax.nn.one_hot(
            jnp.where(keep, pos, capacity).astype(jnp.int32),
            capacity + 1,
            dtype=jnp.float32,
        )[:, :capacity]  # (g, capacity); dropped tokens hit the phantom slot
        combine = combine + (gate * keep)[:, None, None] * (
            onehot[:, :, None] * slot[:, None, :]
        )
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)  # next choice excludes this one

    dispatch = (combine > 0.0).astype(x.dtype)  # (g, E, capacity)

    # all_to_all pair happens here under GSPMD when w_in/w_out are ep-sharded
    expert_in = jnp.einsum("gec,gd->ecd", dispatch, x)  # (E, capacity, d)
    h = jnp.einsum("ecd,efd->ecf", expert_in, w_in) + b_in[:, None, :]
    h = jax.nn.gelu(h, approximate=True)
    expert_out = jnp.einsum("ecf,edf->ecd", h, w_out) + b_out[:, None, :]
    return jnp.einsum("gec,ecd->gd", combine.astype(x.dtype), expert_out)


def _switch_aux_loss(x, router_w, router_b):
    """Switch load-balancing loss (eq. 4): E · Σ_e f_e · P_e.

    Recomputes the (cheap) router probs so it can live in its own tape op —
    grads w.r.t. the router flow from both the gates (main path) and here.
    """
    E = router_w.shape[0]
    logits = x.reshape(-1, x.shape[-1]) @ router_w.T + router_b
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top1 = jax.nn.one_hot(jnp.argmax(probs, axis=-1), E, dtype=jnp.float32)
    f = top1.mean(axis=0)
    p = probs.mean(axis=0)
    return E * jnp.sum(f * p)


class MixtureOfExperts(Module):
    """Drop-in MoE replacement for an MLP block (Switch top-1 / top-2).

    Stacked expert weights ``w_in/w_out`` carry the leading expert axis —
    shard it over ``ep`` via the owning model's ``tp_plan`` (e.g.
    ``r".*moe\\.w_in": ("ep", None, None)``).
    """

    def __init__(
        self,
        d_model: int,
        d_ff: int,
        num_experts: int,
        top_k: int = 1,
        capacity_factor: float = 1.25,
        dropout: float = 0.0,
        dtype=jnp.float32,
    ):
        super().__init__()
        if top_k not in (1, 2):
            raise ValueError(f"top_k must be 1 or 2, got {top_k}")
        from .layers import Dropout

        self.dropout = Dropout(dropout)
        self.d_model = d_model
        self.d_ff = d_ff
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        bound_in = 1.0 / math.sqrt(d_model)
        bound_out = 1.0 / math.sqrt(d_ff)
        self.router = Parameter(init.uniform((num_experts, d_model), bound_in, dtype))
        self.router_bias = Parameter(init.zeros((num_experts,), dtype))
        self.w_in = Parameter(init.uniform((num_experts, d_ff, d_model), bound_in, dtype))
        self.b_in = Parameter(init.zeros((num_experts, d_ff), dtype))
        self.w_out = Parameter(init.uniform((num_experts, d_model, d_ff), bound_out, dtype))
        self.b_out = Parameter(init.zeros((num_experts, d_model), dtype))
        self.last_aux_loss: Optional[Tensor] = None

    def capacity(self, tokens: int) -> int:
        cap = int(math.ceil(tokens * self.top_k / self.num_experts * self.capacity_factor))
        return max(cap, self.top_k)

    def forward(self, x):
        xv = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        # GShard-style routing groups: route independently per leading-axis
        # group (sequence row) so capacity — and with it the (tokens, E,
        # capacity) dispatch tensors — stays CONSTANT per group instead of
        # scaling with the global batch (O(tokens) total memory, not O(g²))
        group_tokens = xv.shape[-2] if xv.ndim >= 3 else xv.shape[0]
        cap = self.capacity(int(group_tokens))

        def _moe(v, rw, rb, wi, bi, wo, bo):
            def one_group(t):
                return _switch_moe_forward(
                    t, rw, rb, wi, bi, wo, bo, capacity=cap, top_k=self.top_k
                )

            if v.ndim == 2:
                return one_group(v)
            groups = v.reshape(-1, v.shape[-2], v.shape[-1])
            return jax.vmap(one_group)(groups).reshape(v.shape)

        y = tape_op(
            _moe, x, self.router, self.router_bias,
            self.w_in, self.b_in, self.w_out, self.b_out,
        )
        self.last_aux_loss = tape_op(
            _switch_aux_loss, x, self.router, self.router_bias
        )
        return self.dropout(y)

    def __repr__(self):
        return (
            f"MixtureOfExperts(d_model={self.d_model}, d_ff={self.d_ff}, "
            f"experts={self.num_experts}, top_k={self.top_k})"
        )
