"""Device-time attribution + fleet observability (docs/telemetry.md).

The acceptance contract (ISSUE 8): sampled steps produce DeviceStepRecords
whose busy+idle split accounts for >=80% of the step's measured wall clock,
joined 1:1 to host StepRecords by step index; profiling off leaves the
capture hot path untouched (and bitwise-identical losses); the multi-host
merge produces per-rank skew stats; the metrics endpoint serves valid
Prometheus text with live serving gauges — all on the CPU mesh.
"""

import urllib.request

import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, TelemetryKwargs
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.telemetry import (
    DeviceStepRecord,
    StepRecord,
    Telemetry,
    _set_active,
)
from accelerate_tpu.telemetry.aggregate import fleet_skew, merge_rank_records
from accelerate_tpu.telemetry.profiler import (
    classify_op,
    derive_mfu,
    parse_trace_events,
)


@pytest.fixture(autouse=True)
def _reset_active_telemetry():
    yield
    _set_active(None)


def _tiny_cfg():
    return GPTConfig(vocab_size=256, n_positions=64, n_embd=32, n_layer=1, n_head=2)


def _make_step(**tel_kwargs):
    nn.manual_seed(0)
    acc = Accelerator(
        kwargs_handlers=[TelemetryKwargs(enabled=True, **tel_kwargs)]
    )
    model = GPTLMHeadModel(_tiny_cfg())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    return acc, acc.compile_step(step_fn)


def _batch(acc, seq=32, seed=0):
    import jax.numpy as jnp

    ids = np.random.default_rng(seed).integers(0, 256, (8, seq), dtype=np.int32)
    return batch_to_global_array(jnp.asarray(ids), mesh=acc.mesh)


# ---------------------------------------------------------------------------
# trace parsing (pure host code, synthetic events)
# ---------------------------------------------------------------------------

def test_parse_trace_events_classifies_and_unions():
    events = [
        {"ph": "M", "pid": 1, "name": "process_name", "args": {"name": "/host:CPU"}},
        # two overlapping compute ops on different worker threads: busy is
        # the interval UNION (10µs), not the duration sum (15µs)
        {"ph": "X", "pid": 1, "tid": 10, "ts": 100.0, "dur": 10.0,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},
        {"ph": "X", "pid": 1, "tid": 11, "ts": 105.0, "dur": 5.0,
         "name": "fusion.2", "args": {"hlo_op": "fusion.2"}},
        {"ph": "X", "pid": 1, "tid": 10, "ts": 130.0, "dur": 4.0,
         "name": "all-reduce.3", "args": {"hlo_op": "all-reduce.3"}},
        {"ph": "X", "pid": 1, "tid": 10, "ts": 140.0, "dur": 2.0,
         "name": "copy.4", "args": {"hlo_op": "copy.4"}},
        # host noise: python frame without hlo_op on a host process
        {"ph": "X", "pid": 1, "tid": 12, "ts": 100.0, "dur": 50.0,
         "name": "PjitFunction(step)"},
    ]
    parsed = parse_trace_events(events)
    assert parsed["op_events"] == 4
    dev = parsed["devices"]["/host:CPU"]
    assert dev["busy_ms"] == pytest.approx((10.0 + 4.0 + 2.0) / 1e3)
    assert dev["compute_ms"] == pytest.approx(15.0 / 1e3)
    assert dev["collective_ms"] == pytest.approx(4.0 / 1e3)
    assert dev["transfer_ms"] == pytest.approx(2.0 / 1e3)
    assert parsed["top_ops"][0][0] == "dot.1"


def test_parse_trace_events_tpu_device_pids():
    """Carried ROADMAP item: a synthetic chrome-trace in the TPU layout —
    ops live under ``/device:TPU:N`` processes and carry NO ``hlo_op`` arg
    — exercises the same classification path CI otherwise only hits with
    CPU traces.  The device-pid route alone must classify, split per
    device, and ignore host processes."""
    events = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 8, "name": "process_name",
         "args": {"name": "/device:TPU:1"}},
        {"ph": "M", "pid": 1, "name": "process_name", "args": {"name": "python"}},
        # TPU op events: bare names, no args.hlo_op — the /device: process
        # name is the only marker.  Two overlap on TPU:0 (union = 1500µs).
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0.0, "dur": 1000.0,
         "name": "fusion.123"},
        {"ph": "X", "pid": 7, "tid": 2, "ts": 500.0, "dur": 1000.0,
         "name": "all-reduce.7"},
        {"ph": "X", "pid": 8, "tid": 1, "ts": 0.0, "dur": 400.0,
         "name": "copy-done.2"},
        # host-side python frame on a non-device pid without hlo_op: noise
        {"ph": "X", "pid": 1, "tid": 3, "ts": 0.0, "dur": 5000.0,
         "name": "ExecuteOnDevice"},
    ]
    parsed = parse_trace_events(events)
    assert set(parsed["devices"]) == {"/device:TPU:0", "/device:TPU:1"}
    assert parsed["op_events"] == 3
    tpu0 = parsed["devices"]["/device:TPU:0"]
    assert tpu0["busy_ms"] == pytest.approx(1.5)  # union, not 2.0 sum
    assert tpu0["compute_ms"] == pytest.approx(1.0)
    assert tpu0["collective_ms"] == pytest.approx(1.0)
    tpu1 = parsed["devices"]["/device:TPU:1"]
    assert tpu1["transfer_ms"] == pytest.approx(0.4)
    assert tpu1["busy_ms"] == pytest.approx(0.4)
    # the host frame must not appear as a device nor in the top ops
    assert all(name != "ExecuteOnDevice" for name, _ in parsed["top_ops"])


def test_split_phases_joins_scope_map_and_buckets_unscoped():
    """Per-phase device attribution (docs/telemetry.md): sampled op
    durations joined to the program's HLO op->scope map, with ops outside
    every atpu scope in 'unscoped' — regression pin for the ROADMAP
    carried item."""
    from accelerate_tpu.telemetry.profiler import split_phases

    op_detail = {
        "dot.1": ["compute", 2.0],
        "all-reduce.3": ["collective", 1.5],
        "fusion.9": ["compute", 0.5],
        "copy.4": ["transfer", 0.25],
    }
    scope_map = {
        "dot.1": "atpu_captured_body",
        "all-reduce.3": "atpu_update",
        "fusion.9": "atpu_update",
    }
    phases = split_phases(op_detail, scope_map)
    assert phases["atpu_captured_body"] == {
        "total_ms": 2.0, "compute_ms": 2.0, "collective_ms": 0.0,
        "transfer_ms": 0.0, "ops": 1,
    }
    assert phases["atpu_update"]["collective_ms"] == 1.5
    assert phases["atpu_update"]["compute_ms"] == 0.5
    assert phases["atpu_update"]["ops"] == 2
    assert phases["unscoped"]["transfer_ms"] == 0.25


def test_sampled_run_splits_device_time_per_named_scope():
    """Integration: a sampled captured run splits its device timeline by
    the atpu named scopes (forward body / backward / optimizer update),
    each phase carrying its own compute/collective split — what makes the
    kernel A/B legible per phase (docs/kernels.md).

    Uses the standard tiny GPT rather than the 1-layer micro model: with a
    handful of ops XLA fuses whole phases into one fusion whose metadata
    names a single representative scope — the split is honest but
    single-phase, and the pin would be vacuous.

    The suite's persistent XLA compilation cache is disabled for this test:
    a cache-DESERIALIZED executable drops its HLO op_name metadata, so the
    scope map is empty and the split (correctly, documented) fail-softs to
    none — the pin needs a fresh compile."""
    import jax

    prev_cache = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        _run_phase_split_assertions()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_cache)


def _run_phase_split_assertions():
    nn.manual_seed(0)
    acc = Accelerator(
        mixed_precision="bf16",
        kwargs_handlers=[TelemetryKwargs(enabled=True, profile_every_n=1)],
    )
    model = GPTLMHeadModel(GPTConfig.tiny())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    batch = _batch(acc)
    for _ in range(3):
        step(batch)
    replay = list(acc.telemetry.device_records)[-1]
    assert replay.phases, "sampled replay carried no per-phase split"
    names = set(replay.phases)
    assert {"atpu_captured_body", "atpu_backward", "atpu_update"} <= names, names
    for name in ("atpu_captured_body", "atpu_backward", "atpu_update"):
        split = replay.phases[name]
        assert split["total_ms"] > 0 and split["ops"] > 0
    # the export dict carries the (rounded) split
    exported = replay.to_dict()["phases"]
    assert set(exported) == names
    # the phase sum accounts for the classified op time (same op universe)
    phase_total = sum(s["total_ms"] for s in replay.phases.values())
    op_total = sum(ms for _, ms in replay.op_detail.values())
    assert phase_total == pytest.approx(op_total, rel=1e-6)


def test_classify_op_names():
    assert classify_op("fused_all-gather.7") == "collective"
    assert classify_op("reduce-scatter.1") == "collective"
    assert classify_op("copy-start.2") == "transfer"
    assert classify_op("dot_general.9") == "compute"


def test_derive_mfu_uses_peak_override(monkeypatch):
    monkeypatch.setenv("ACCELERATE_PEAK_FLOPS", "1e12")
    # 1e9 FLOPs in 1 ms against a 1 TFLOP/s chip = 100% MFU
    assert derive_mfu(1e9, 1.0) == pytest.approx(1.0)
    assert derive_mfu(1e9, 1.0, n_devices=2) == pytest.approx(0.5)
    monkeypatch.delenv("ACCELERATE_PEAK_FLOPS")
    # CPU has no table entry: MFU is honestly underivable
    assert derive_mfu(1e9, 1.0) is None


# ---------------------------------------------------------------------------
# sampled capture: DeviceStepRecord <-> StepRecord join + coverage
# ---------------------------------------------------------------------------

def test_sampled_steps_join_host_records_and_cover_wall_clock(tmp_path):
    acc, step = _make_step(profile_every_n=2)
    assert step._telemetry.profiler is not None
    batch = _batch(acc)
    for _ in range(4):
        loss = step(batch)
    assert np.isfinite(float(loss))
    device_records = list(acc.telemetry.device_records)
    # cadence 2 over steps 0..3 samples steps 0 and 2
    assert [r.step for r in device_records] == [0, 2]
    # sampling must not perturb the capture cache (forensics-asserted)
    assert acc.telemetry.recompiles_total == 0
    host = {r.step: r for r in acc.telemetry.timeline.records()}
    for rec in device_records:
        joined = host[rec.step]  # 1:1 by step index
        assert rec.key == joined.key
        assert rec.window_ms > 0 and rec.op_events > 0
        assert rec.compute_ms > 0  # nonempty device split
        assert rec.top_ops and rec.top_ops[0][1] > 0
        assert rec.flops and rec.flops > 0  # joined from cost_analysis
    # ISSUE 8 acceptance on the replay sample: busy+idle accounts for >=80%
    # of the measured step wall clock (profiler stop/parse overhead is
    # recorded separately and excluded — it is not device time)
    replay = device_records[1]
    joined = host[replay.step]
    assert not joined.built
    covered = (replay.busy_ms + replay.idle_ms) / (
        joined.total_ms - replay.overhead_ms
    )
    assert covered >= 0.8, (replay, joined)
    # the JSONL roundtrip renders the new section and stays schema-valid
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        from telemetry_report import load_records, render, validate
    finally:
        sys.path.pop(0)
    path = str(tmp_path / "run.jsonl")
    acc.telemetry.write_jsonl(path)
    records = load_records(path)
    assert validate(records, min_steps=4) == []
    report = render(records)
    assert "device-time attribution" in report
    assert "top ops" in report


def test_profiling_off_is_inert_and_bitwise_identical():
    def run(profile_every_n):
        Accelerator._reset_state()
        _set_active(None)
        acc, step = _make_step(profile_every_n=profile_every_n)
        batch = _batch(acc)
        losses = [float(step(batch)) for _ in range(2)]
        return acc, step, losses

    acc_off, step_off, losses_off = run(0)
    # off = the pre-profiler hot path: no profiler object, no records, no
    # stray trace state — the same pin discipline as telemetry/resilience
    assert acc_off.telemetry.profiler is None
    assert step_off._telemetry.profiler is None
    assert len(acc_off.telemetry.device_records) == 0
    _, _, losses_on = run(1)
    assert losses_on == losses_off  # sampling must not change the math


# ---------------------------------------------------------------------------
# multi-host aggregation (merge math is host-only; gather degenerates at 1)
# ---------------------------------------------------------------------------

def _rank_records(dispatch_ms, n=4, rank_tag=None):
    return [
        {"kind": "step", "step": i, "built": i == 0, "total_ms": 2.0 + dispatch_ms,
         "assembly_ms": 1.0, "trace_ms": 0.0, "compile_ms": 0.0,
         "dispatch_ms": dispatch_ms, "dataloader_wait_ms": 1.0,
         "retry_wait_ms": 0.0}
        for i in range(n)
    ]


def test_merge_rank_records_tags_and_attributes_straggler():
    fast, slow = _rank_records(5.0), _rank_records(9.0)
    merged = merge_rank_records([fast, slow])
    # every record is rank-tagged, inputs are not mutated
    assert {r.get("rank") for r in merged if r.get("kind") == "step"} == {0, 1}
    assert "rank" not in fast[0]
    fleet = [r for r in merged if r.get("kind") == "fleet"]
    assert len(fleet) == 1
    skew = fleet[0]
    assert skew["ranks"] == 2
    assert skew["slowest_rank"] == 1 and skew["fastest_rank"] == 0
    assert skew["skew_ms"] == pytest.approx(4.0)
    # the straggler's extra time sits in dispatch — named, not guessed
    assert skew["straggler_phase"] == "dispatch_ms"
    assert skew["straggler_phase_delta_ms"] == pytest.approx(4.0)


def test_fleet_skew_handles_replay_free_ranks():
    skew = fleet_skew([[{"kind": "meta"}], _rank_records(3.0)])
    assert skew["ranks"] == 2
    assert skew["per_rank"][0]["replay_steps"] == 0
    assert "slowest_rank" not in skew  # <2 usable ranks: no comparison


def test_aggregate_fleet_single_process_tags_rank_zero():
    hub = Telemetry(_EnabledKwargs())
    from accelerate_tpu.telemetry import StepRecord

    for i in range(3):
        hub.record_step(
            StepRecord(step=i, key="k", built=i == 0, total_ms=2.0,
                       assembly_ms=1.0, trace_ms=0.0, compile_ms=0.0,
                       dispatch_ms=1.0, dataloader_wait_ms=0.0)
        )
    merged = hub.aggregate_fleet()
    assert merged is not None
    steps = [r for r in merged if r.get("kind") == "step"]
    assert len(steps) == 3 and all(r["rank"] == 0 for r in steps)
    assert any(r.get("kind") == "fleet" for r in merged)
    # the JSONL dump now describes the fleet view
    assert hub.export_records() is merged


def _EnabledKwargs():
    return TelemetryKwargs(enabled=True)


# ---------------------------------------------------------------------------
# metrics endpoint: valid Prometheus text, live serving gauges
# ---------------------------------------------------------------------------

# the renderer's own sample-line grammar (incl. histogram `le` labels) —
# shared with tools/profile_smoke.py so every validator tracks the format
from accelerate_tpu.telemetry.metrics import SAMPLE_LINE_RE as _SAMPLE_RE


def _scrape(url):
    body = urllib.request.urlopen(url, timeout=10).read().decode("utf-8")
    for line in body.splitlines():
        if line.startswith("#") or not line:
            continue
        assert _SAMPLE_RE.match(line), f"invalid exposition line: {line!r}"
    return body


def test_metrics_endpoint_scrapes_training_hub():
    acc, step = _make_step()
    batch = _batch(acc)
    for _ in range(2):
        step(batch)
    server = acc.telemetry.serve_metrics(port=0)
    try:
        assert server is acc.telemetry.serve_metrics()  # idempotent
        body = _scrape(server.url)
        assert "# TYPE atpu_telemetry_steps_total counter" in body
        assert "atpu_telemetry_steps_total 2" in body
        assert "atpu_telemetry_recompiles_total 0" in body
        assert "atpu_telemetry_replay_dispatch_ms_mean" in body
        # native step-latency histogram: _bucket series, not percentiles
        assert "# TYPE atpu_telemetry_step_latency_ms histogram" in body
        assert 'atpu_telemetry_step_latency_ms_bucket{le="+Inf"} 1' in body
        assert "atpu_telemetry_step_latency_ms_count 1" in body  # replay only
    finally:
        acc.telemetry.close_metrics()
    assert acc.telemetry.metrics_server is None


def test_latency_histogram_cumulative_and_replay_scoped():
    """ROADMAP carried item: native Prometheus `_bucket` series replace the
    point-in-time percentile gauges — bucket counts are CUMULATIVE (le is
    inclusive), sum/count track every observation, and the hub's step
    histogram observes replays only (a build's compile time would park the
    whole mass in the top bucket)."""
    from accelerate_tpu.telemetry.metrics import (
        LatencyHistogram,
        render_prometheus,
    )

    hist = LatencyHistogram(buckets=(1.0, 10.0, 100.0))
    for value in (0.5, 1.0, 5.0, 50.0, 5000.0):
        hist.observe(value)
    assert hist.cumulative_counts() == [2, 3, 4, 5]  # le="1" includes 1.0
    assert hist.count == 5 and hist.sum == 5056.5
    body = render_prometheus([("t", {"lat_ms": hist})])
    assert '# TYPE atpu_t_lat_ms histogram' in body
    assert 'atpu_t_lat_ms_bucket{le="1"} 2' in body
    assert 'atpu_t_lat_ms_bucket{le="+Inf"} 5' in body
    assert "atpu_t_lat_ms_count 5" in body
    # hub scoping: builds excluded from the step histogram
    def _record(step, built, total_ms):
        return StepRecord(
            step=step, key="k", built=built, total_ms=total_ms,
            assembly_ms=0.0, trace_ms=0.0, compile_ms=0.0,
            dispatch_ms=total_ms, dataloader_wait_ms=0.0,
        )

    hub = Telemetry(_EnabledKwargs())
    hub.record_step(_record(0, built=True, total_ms=5000.0))
    hub.record_step(_record(1, built=False, total_ms=3.0))
    assert hub.step_hist.count == 1 and hub.step_hist.sum == 3.0


def test_decode_service_metrics_snapshot_and_scrape():
    from accelerate_tpu.serving import DecodeService, ServingConfig
    from accelerate_tpu.telemetry.metrics import MetricsServer

    nn.manual_seed(0)
    model = GPTLMHeadModel(GPTConfig.tiny())
    model.eval()
    service = DecodeService(
        model, ServingConfig(max_slots=2, block_size=16, prompt_bucket=16)
    )
    rng = np.random.default_rng(0)
    for n in (5, 12, 9):
        service.submit(rng.integers(0, 1024, (n,), dtype=np.int32), 6)
    server = MetricsServer()
    server.add_service(service)
    server.start()
    try:
        mid_metrics = None
        while service.has_work:
            service.step()
            if mid_metrics is None:
                mid_metrics = service.metrics()  # live mid-flight snapshot
        assert mid_metrics["occupancy"] > 0
        done = service.metrics()
        assert done["completed_total"] == 3
        assert done["queue_depth"] == 0
        assert done["block_pool_free_frac"] == 1.0  # all blocks back
        assert done["recompile_events_total"] == 0
        assert done["ttft_ms_p50"] > 0 and done["ttft_ms_p99"] >= done["ttft_ms_p50"]
        assert done["tpot_ms_p50"] > 0
        body = _scrape(server.url)
        assert "atpu_serving_completed_total 3" in body
        assert "atpu_serving_occupancy" in body
        assert "atpu_serving_queue_depth" in body
        assert "atpu_serving_block_pool_free_frac" in body
        assert "atpu_serving_ttft_ms_p50" in body
        assert "atpu_serving_ttft_ms_p99" in body
        # native TTFT/TPOT histograms alongside the window percentiles:
        # one observation per completed request, cumulative over lifetime
        assert "# TYPE atpu_serving_ttft_ms histogram" in body
        assert 'atpu_serving_ttft_ms_bucket{le="+Inf"} 3' in body
        assert "atpu_serving_ttft_ms_count 3" in body
        assert 'atpu_serving_tpot_ms_bucket{le="+Inf"} 3' in body
    finally:
        server.close()


def test_service_with_hub_registers_metrics_provider():
    """A DecodeService built on a telemetry hub self-registers: the hub's
    endpoint scrapes its gauges without extra wiring."""
    hub = Telemetry(_EnabledKwargs())

    class _FakeService:
        def metrics(self):
            return {"occupancy": 0.5, "queue_depth": 2}

    hub.register_metrics_provider("serving", _FakeService().metrics)
    server = hub.serve_metrics(port=0)
    try:
        body = _scrape(server.url)
        assert "atpu_serving_occupancy 0.5" in body
        assert "atpu_serving_queue_depth 2" in body
    finally:
        hub.close_metrics()


def test_render_prometheus_drops_duplicates_and_non_numbers():
    from accelerate_tpu.telemetry.metrics import render_prometheus

    body = render_prometheus([
        ("a", {"x": 1, "nested": {"y": 2.5}, "skip": None, "name": "str",
               "flag": True}),
        ("a", {"x": 99}),  # duplicate name: first sample wins
    ])
    lines = [l for l in body.splitlines() if not l.startswith("#")]
    assert "atpu_a_x 1" in lines
    assert "atpu_a_nested_y 2.5" in lines
    assert "atpu_a_flag 1" in lines
    assert not any(l.startswith("atpu_a_x 99") for l in lines)
    assert not any("skip" in l or "name" in l for l in lines)


def test_device_step_record_to_dict_schema():
    rec = DeviceStepRecord(
        step=3, key="kabc", window_ms=10.0, busy_ms=6.0, idle_ms=4.0,
        compute_ms=5.0, collective_ms=1.5, transfer_ms=0.5,
        devices={"/host:CPU": {"busy_ms": 6.0}}, top_ops=[["dot.1", 4.2]],
        op_events=7,
    )
    d = rec.to_dict()
    assert d["kind"] == "device_step"
    assert d["collective_share"] == pytest.approx(1.5 / 7.0, abs=1e-4)
    assert d["devices"]["/host:CPU"]["busy_ms"] == 6.0
    assert d["top_ops"] == [["dot.1", 4.2]]
