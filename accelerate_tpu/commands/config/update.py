"""``accelerate-tpu config update`` — rewrite an existing config file with the
current schema (drops unknown keys, fills new defaults).

Counterpart of ``/root/reference/src/accelerate/commands/config/update.py``.
"""

from __future__ import annotations

import argparse
from typing import Optional

import yaml

from .config_args import Config, default_config_file


def update_config(args) -> str:
    config_file = args.config_file or default_config_file
    with open(config_file, encoding="utf-8") as f:
        if config_file.endswith(".json"):
            import json

            data = json.load(f)
        else:
            data = yaml.safe_load(f) or {}
    known = set(Config.__dataclass_fields__)
    dropped = sorted(set(data) - known)
    config = Config(**{k: v for k, v in data.items() if k in known})
    config.save(config_file)
    if dropped:
        print(f"dropped legacy keys: {', '.join(dropped)}")
    return config_file


def update_command_parser(subparsers: Optional[argparse._SubParsersAction] = None):
    description = "Update an existing config file to the current schema"
    if subparsers is not None:
        parser = subparsers.add_parser("update", description=description)
    else:
        parser = argparse.ArgumentParser(
            "accelerate-tpu config update", description=description
        )
    parser.add_argument("--config_file", default=None)
    if subparsers is not None:
        parser.set_defaults(func=update_config_command)
    return parser


def update_config_command(args) -> None:
    path = update_config(args)
    print(f"configuration at {path} updated")
