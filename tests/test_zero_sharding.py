"""ZeRO memory proof: optimizer state and fp32 masters must live on the
``fsdp`` axis after ``prepare()`` (reference FSDP shards optimizer state with
the params, accelerator.py:1555-1679; here it is a GSPMD layout decision).

Round-1 verdict flagged this as asserted-by-docstring-only: ``tx.init`` runs
before ``prepare()`` shards the params, so without an explicit re-layout the
Adam moments stay on the construction-time (replicated) layout and "ZeRO"
saves no optimizer memory.  These tests measure actual per-device bytes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.nn import F, Tensor


@pytest.fixture(autouse=True)
def _fresh():
    nn.manual_seed(0)
    yield
    Accelerator._reset_state()


# single source of truth for per-replica residency accounting (also used by
# tests/test_zero1.py and bench.py)
from accelerate_tpu.utils.memory import opt_state_bytes_per_replica as _per_device_opt_bytes  # noqa: E402


def _n_dev() -> int:
    # device-count agnostic: the default suite forces 8 virtual devices,
    # `make multichip` re-runs this file at 4
    return len(jax.devices())


def _build(fsdp_size: int):
    from accelerate_tpu import DataParallelPlugin

    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(fsdp_size=fsdp_size),
        mixed_precision="bf16",
        # fsdp_size=1 leaves a dp axis, and ZeRO-1 defaults ON there
        # (tests/test_zero1.py) — opt out so this file keeps measuring the
        # fsdp axis against a genuinely replicated baseline
        dp_plugin=DataParallelPlugin(zero1=False),
    )
    model = nn.Sequential(nn.Linear(256, 256), nn.ReLU(), nn.Linear(256, 256))
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)
    return acc, model, opt


def test_opt_state_bytes_shrink_with_fsdp_size():
    _, _, opt_repl = _build(fsdp_size=1)
    repl_bytes = _per_device_opt_bytes(opt_repl.optimizer)

    n = _n_dev()
    _, _, opt_sharded = _build(fsdp_size=n)
    sharded_bytes = _per_device_opt_bytes(opt_sharded.optimizer)

    # every param axis here (256, 256) and bias (256) divides the device
    # count exactly, so per-device optimizer bytes must be total/n (tiny
    # scalar counts aside)
    assert sharded_bytes <= repl_bytes / n + 4096, (
        f"optimizer state not ZeRO-sharded: {sharded_bytes}B per device vs "
        f"{repl_bytes}B replicated (expected ~{repl_bytes // n}B)"
    )


def test_masters_follow_param_sharding():
    acc, model, opt = _build(fsdp_size=_n_dev())
    inner = opt.optimizer
    for p, m in zip(inner.param_list, inner.master_params):
        assert m is not None  # bf16 params ⇒ fp32 masters exist
        assert m.sharding == p.data.sharding, (
            f"master copy sharding {m.sharding} != param {p.data.sharding}"
        )


def test_opt_state_sharded_after_steps():
    acc, model, opt = _build(fsdp_size=_n_dev())

    def step_fn(x, y):
        opt.zero_grad()
        pred = model(x)
        loss = F.mse_loss(pred, y)
        acc.backward(loss)
        opt.step()
        return loss

    step = acc.compile_step(step_fn)
    from accelerate_tpu.data_loader import batch_to_global_array

    rng = np.random.default_rng(0)
    x = batch_to_global_array(
        jnp.asarray(rng.normal(size=(8, 256)).astype(np.float32)), mesh=acc.mesh
    )
    y = batch_to_global_array(
        jnp.asarray(rng.normal(size=(8, 256)).astype(np.float32)), mesh=acc.mesh
    )
    before = _per_device_opt_bytes(opt.optimizer)
    step(x, y)
    step(x, y)
    after = _per_device_opt_bytes(opt.optimizer)
    assert after <= before, (
        f"optimizer state grew through the captured step: {before}B -> {after}B "
        "(jit outputs lost the fsdp sharding)"
    )
