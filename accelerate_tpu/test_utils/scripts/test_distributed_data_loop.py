"""Distributed data-loop semantics on a live mesh (analog of reference
test_utils/scripts/test_distributed_data_loop.py).

Where the reference runs one process per rank and compares each rank's
batches, the SPMD loader builds ONE global batch per step laid over the
mesh's data axes — so the checks here are about the global program:

* every step's global batch is identical no matter how the data axes are
  factored (dp×fsdp splits of the same world size);
* per-device shards tile the global batch exactly (no overlap, no gap);
* the uneven tail loops back and ``GradientState.remainder`` reports the
  duplicate count on the final step only;
* ``split_batches`` halves the step count, not the global batch;
* mid-epoch ``skip_first_batches`` resumes on the exact next batch.
"""

from __future__ import annotations

import numpy as np

from accelerate_tpu import Accelerator
from accelerate_tpu.state import GradientState, PartialState
from accelerate_tpu.utils.dataclasses import DataLoaderConfiguration, ParallelismConfig


def _dataset(n: int):
    return [{"x": np.float32([i, i + 0.5]), "y": np.int64(i % 2)} for i in range(n)]


def _global_batches(acc, dl):
    """Collect global batches and the final step's remainder (the loader
    publishes it in GradientState only while the last batch is live)."""
    out, remainder = [], 0
    for batch in dl:
        out.append(np.asarray(batch["x"]))
        remainder = GradientState().remainder
    return out, remainder


def _run_epoch(fsdp_size: int, n: int, batch_size: int, **dl_kwargs):
    import torch.utils.data as tud

    acc = Accelerator(
        parallelism_config=ParallelismConfig(fsdp_size=fsdp_size),
        dataloader_config=DataLoaderConfiguration(**dl_kwargs) if dl_kwargs else None,
    )
    dl = acc.prepare(tud.DataLoader(_dataset(n), batch_size=batch_size))
    batches, remainder = _global_batches(acc, dl)
    PartialState._reset_state()
    return batches, remainder


def main():
    import jax

    n_dev = len(jax.devices())
    assert n_dev in (1, 2, 4, 8), n_dev

    # 1. mesh factoring must not change the data the model sees
    n, bs = 45, 4
    batches_dp, rem_dp = _run_epoch(1, n, bs)
    if n_dev > 1:
        batches_mixed, rem_mixed = _run_epoch(2, n, bs)
        assert len(batches_dp) == len(batches_mixed)
        for a, b in zip(batches_dp, batches_mixed):
            np.testing.assert_array_equal(a, b)
        assert rem_dp == rem_mixed

    # 2. shards tile the global batch: flat coverage of the dataset + looped
    # tail counted by remainder
    flat = np.concatenate([b[:, 0] for b in batches_dp])
    seen = {int(v) for v in flat}
    assert seen == set(range(n)), sorted(seen ^ set(range(n)))
    assert len(flat) - n == rem_dp, (len(flat), n, rem_dp)

    # 3. split_batches: same global content, read as pre-split global batches
    batches_split, _ = _run_epoch(1, n, bs * max(n_dev, 1), split_batches=True)
    flat_split = np.concatenate([b[:, 0] for b in batches_split])
    assert {int(v) for v in flat_split} == set(range(n))

    # 4. mid-epoch resume
    import torch.utils.data as tud

    acc = Accelerator()
    # enough steps to skip into the middle: 96 samples / (2 x n_dev) per step
    dl = acc.prepare(tud.DataLoader(_dataset(96), batch_size=2))
    all_batches, _ = _global_batches(acc, dl)
    skip = len(all_batches) // 2
    resumed = acc.skip_first_batches(dl, skip)
    resumed_batches, _ = _global_batches(acc, resumed)
    assert len(resumed_batches) == len(all_batches) - skip
    for a, b in zip(all_batches[skip:], resumed_batches):
        np.testing.assert_array_equal(a, b)
    PartialState._reset_state()

    print("All distributed data-loop checks passed")


if __name__ == "__main__":
    main()
