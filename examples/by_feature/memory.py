"""Feature: OOM-retry with ``find_executable_batch_size``.

Counterpart of /root/reference/examples/by_feature/memory.py: the inner
training loop is decorated so an XLA RESOURCE_EXHAUSTED restarts it with the
batch size halved until it fits.  Lines marked `# New Code #` are what this
feature adds to nlp_example.py.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

import accelerate_tpu.nn as nn  # noqa: E402
import accelerate_tpu.optim as optim  # noqa: E402
from accelerate_tpu import Accelerator  # noqa: E402
from accelerate_tpu.models import BertConfig, BertForSequenceClassification  # noqa: E402
from accelerate_tpu.utils.memory import find_executable_batch_size  # noqa: E402


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    nn.manual_seed(args.seed)

    # New Code #
    # on RESOURCE_EXHAUSTED the decorator frees state and reruns the whole
    # inner loop at half the batch size (reference utils/memory.py:120)
    @find_executable_batch_size(starting_batch_size=args.batch_size)
    def inner_training_loop(batch_size):
        nonlocal accelerator
        accelerator.free_memory()
        train_dl, val_dl, vocab = get_dataloaders(accelerator, batch_size, args.seed)
        cfg = BertConfig.small() if args.small else BertConfig.base()
        cfg.vocab_size = max(cfg.vocab_size, vocab)
        model = BertForSequenceClassification(cfg)
        optimizer = optim.AdamW(model.parameters(), lr=args.lr)
        scheduler = optim.get_linear_schedule_with_warmup(
            optimizer, 100, len(train_dl) * args.num_epochs * accelerator.num_devices
        )
        model, optimizer, train_dl, val_dl, scheduler = accelerator.prepare(
            model, optimizer, train_dl, val_dl, scheduler
        )
        for epoch in range(args.num_epochs):
            model.train()
            for step, batch in enumerate(train_dl):
                optimizer.zero_grad()
                out = model(
                    batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                    labels=batch["labels"],
                )
                accelerator.backward(out["loss"])
                optimizer.step()
                scheduler.step()
            accelerator.print(
                f"epoch {epoch} (batch_size={batch_size}): "
                f"loss={float(out['loss'].item()):.4f}"
            )
        return model

    # New Code #
    return inner_training_loop()


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", type=str, default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=2e-5)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--small", action="store_true")
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
