"""``accelerate-tpu tpu-config`` — run setup commands on every pod worker.

Counterpart of ``/root/reference/src/accelerate/commands/tpu.py:29-157``
(gcloud alpha compute tpus tpu-vm ssh --worker all).
"""

from __future__ import annotations

import argparse
import subprocess
from typing import Optional

__all__ = ["tpu_command_parser", "tpu_command_launcher"]

_DEFAULT_INSTALL = "pip install -U accelerate_tpu"


def tpu_command_parser(subparsers: Optional[argparse._SubParsersAction] = None):
    description = "Run commands on all workers of a TPU pod (setup/install)"
    if subparsers is not None:
        parser = subparsers.add_parser("tpu-config", help=description)
    else:
        parser = argparse.ArgumentParser(
            "accelerate-tpu tpu-config", description=description
        )
    config_args = parser.add_argument_group("Config Arguments")
    config_args.add_argument("--config_file", default=None)
    config_args.add_argument("--tpu_name", default=None)
    config_args.add_argument("--tpu_zone", default=None)
    pod_args = parser.add_argument_group("TPU Arguments")
    pod_args.add_argument(
        "--command",
        action="append",
        help="Command to run on each worker (repeatable)",
    )
    pod_args.add_argument(
        "--command_file", default=None, help="File with one command per line"
    )
    pod_args.add_argument(
        "--install_accelerate",
        action="store_true",
        help=f"Prepend `{_DEFAULT_INSTALL}`",
    )
    pod_args.add_argument("--debug", action="store_true", help="Print, don't run")
    if subparsers is not None:
        parser.set_defaults(func=tpu_command_launcher)
    return parser


def tpu_command_launcher(args) -> None:
    if args.config_file or (args.tpu_name is None or args.tpu_zone is None):
        from .config.config_args import default_config_file, load_config_from_file
        import os

        path = args.config_file or default_config_file
        if os.path.isfile(path):
            config = load_config_from_file(path)
            args.tpu_name = args.tpu_name or config.tpu_name
            args.tpu_zone = args.tpu_zone or config.tpu_zone
    if not args.tpu_name or not args.tpu_zone:
        raise ValueError("tpu-config needs --tpu_name and --tpu_zone (or a config file)")

    commands = []
    if args.install_accelerate:
        commands.append(_DEFAULT_INSTALL)
    if args.command_file:
        with open(args.command_file) as f:
            commands.extend(line.strip() for line in f if line.strip())
    commands.extend(args.command or [])
    if not commands:
        raise ValueError("no commands given (--command / --command_file)")

    command = "; ".join(commands)
    gcloud_cmd = [
        "gcloud",
        "compute",
        "tpus",
        "tpu-vm",
        "ssh",
        args.tpu_name,
        f"--zone={args.tpu_zone}",
        f"--command={command}",
        "--worker=all",
    ]
    if args.debug:
        print(f"Running {' '.join(gcloud_cmd)}")
        return
    subprocess.run(gcloud_cmd, check=True)
    print("Successfully setup pod.")


def main():
    args = tpu_command_parser().parse_args()
    tpu_command_launcher(args)


if __name__ == "__main__":
    main()
