"""Rank-gated tqdm (reference /root/reference/src/accelerate/utils/tqdm.py):
only the main (or local-main) process renders a bar; other ranks get a
transparent pass-through iterator."""

from __future__ import annotations


class _PassthroughTqdm:
    """Iterator wrapper exposing the tqdm surface as no-ops."""

    def __init__(self, iterable=None, **kwargs):
        self.iterable = iterable

    def __iter__(self):
        return iter(self.iterable if self.iterable is not None else ())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def update(self, n: int = 1):
        pass

    def set_description(self, *a, **k):
        pass

    def set_postfix(self, *a, **k):
        pass

    def write(self, *a, **k):
        pass

    def close(self):
        pass


def tqdm(*args, main_process_only: bool = True, **kwargs):
    """Drop-in ``tqdm`` that renders only on the main process.

    Matches the reference signature (utils/tqdm.py:23): first positional arg
    may be the iterable, or legacy ``tqdm(main_process_only, iterable)``.
    """
    from ..state import PartialState

    if args and isinstance(args[0], bool):  # legacy positional form
        main_process_only, *args = args
    should_render = PartialState().is_main_process or not main_process_only
    if not should_render:
        return _PassthroughTqdm(args[0] if args else kwargs.get("iterable"))
    try:
        from tqdm.auto import tqdm as _tqdm
    except ImportError:
        return _PassthroughTqdm(args[0] if args else kwargs.get("iterable"))
    return _tqdm(*args, **kwargs)
