"""GPT-NeoX family decoder — the reference's largest benchmark family
(GPT-Neo-X-20B, reference benchmarks/big_model_inference/README.md:33-34).

Parallel-residual decoder with TWO layer norms per block
(``x + attn(ln_attn(x)) + mlp(ln_mlp(x))`` when ``use_parallel_residual``,
the 20B default), fused per-head-interleaved qkv projection with bias,
rotate-half rotary on the first ``rotary_pct`` of head dims, exact (erf)
GELU, untied bias-free LM head.  Same one-math structure as
models/llama.py; parameter naming mirrors HF
(``layers.N.attention.query_key_value`` …).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import Tensor
from .gpt import _pure_layernorm, lm_head_loss, maybe_remat


@dataclasses.dataclass
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 6144
    num_hidden_layers: int = 44
    num_attention_heads: int = 64
    intermediate_size: int = 24576
    max_position_embeddings: int = 2048
    rotary_pct: float = 0.25
    rotary_emb_base: float = 10000.0
    layer_norm_eps: float = 1e-5
    use_parallel_residual: bool = True
    initializer_range: float = 0.02

    @classmethod
    def tiny(cls) -> "GPTNeoXConfig":
        return cls(
            vocab_size=1024, hidden_size=128, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=256,
            max_position_embeddings=256,
        )

    @classmethod
    def neox_20b(cls) -> "GPTNeoXConfig":
        return cls()  # the defaults are GPT-NeoX-20B

    def __post_init__(self):
        if not self.use_parallel_residual:
            raise NotImplementedError(
                "use_parallel_residual=False NeoX variants (pythia-70m-v0 era) "
                "are not supported; every standard NeoX size is parallel"
            )


# ---------------------------------------------------------------------------
# Pure per-layer math.  Keys: ln1_{w,b} (input_layernorm),
# qkv_{w,b} (fused, PER-HEAD interleaved [q|k|v] like HF), o_{w,b},
# ln2_{w,b} (post_attention_layernorm), fcin_{w,b}, fcout_{w,b}.
# ---------------------------------------------------------------------------
_LAYER_KEYS = (
    "ln1_w", "ln1_b", "qkv_w", "qkv_b", "o_w", "o_b",
    "ln2_w", "ln2_b", "fcin_w", "fcin_b", "fcout_w", "fcout_b",
)


def _rope_half(x, positions, rotary_ndims: int, base: float):
    """Rotate-half rotary on the first ``rotary_ndims`` dims (NeoX/Llama
    convention), the rest pass through."""
    rot, pas = x[..., :rotary_ndims], x[..., rotary_ndims:]
    inv = 1.0 / (
        base ** (jnp.arange(0, rotary_ndims, 2, dtype=jnp.float32) / rotary_ndims)
    )
    freqs = positions.astype(jnp.float32)[:, None] * inv[None, :]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    cos = jnp.cos(emb).astype(x.dtype)[None, None]
    sin = jnp.sin(emb).astype(x.dtype)[None, None]
    r1, r2 = rot[..., : rotary_ndims // 2], rot[..., rotary_ndims // 2 :]
    rotated = jnp.concatenate([-r2, r1], axis=-1)
    return jnp.concatenate([rot * cos + rotated * sin, pas], axis=-1)


def neox_attn_in(l, x, positions, *, n_head: int, rotary_ndims: int, base: float, eps: float):
    b, s, c = x.shape
    d = c // n_head
    h = _pure_layernorm(x, l["ln1_w"], l["ln1_b"], eps)
    qkv = h @ l["qkv_w"].T + l["qkv_b"]
    # HF NeoX fused layout: (b, s, H, 3*d) with [q|k|v] per head
    qkv = qkv.reshape(b, s, n_head, 3 * d)
    q = qkv[..., :d].transpose(0, 2, 1, 3)
    k = qkv[..., d : 2 * d].transpose(0, 2, 1, 3)
    v = qkv[..., 2 * d :].transpose(0, 2, 1, 3)
    q = _rope_half(q, positions, rotary_ndims, base)
    k = _rope_half(k, positions, rotary_ndims, base)
    return q, k, v


def neox_attn_out(l, x, att, *, eps: float):
    """Parallel residual with separate norms: x + dense(att) + mlp(ln2(x))."""
    b, s, c = x.shape
    att = att.transpose(0, 2, 1, 3).reshape(b, s, c)
    h2 = _pure_layernorm(x, l["ln2_w"], l["ln2_b"], eps)
    ff = jax.nn.gelu(h2 @ l["fcin_w"].T + l["fcin_b"], approximate=False)
    return x + (att @ l["o_w"].T + l["o_b"]) + (ff @ l["fcout_w"].T + l["fcout_b"])


class GPTNeoXLayer(nn.Module):
    def __init__(self, config: GPTNeoXConfig):
        super().__init__()
        self.config = config
        c = config.hidden_size
        self.input_layernorm = nn.LayerNorm(c, eps=config.layer_norm_eps)
        self.post_attention_layernorm = nn.LayerNorm(c, eps=config.layer_norm_eps)

        class _Attn(nn.Module):
            def __init__(self):
                super().__init__()
                self.query_key_value = nn.Linear(c, 3 * c)
                self.dense = nn.Linear(c, c)

        class _MLP(nn.Module):
            def __init__(self):
                super().__init__()
                self.dense_h_to_4h = nn.Linear(c, config.intermediate_size)
                self.dense_4h_to_h = nn.Linear(config.intermediate_size, c)

        self.attention = _Attn()
        self.mlp = _MLP()

    def param_tensors(self):
        a, m = self.attention, self.mlp
        return [  # order == _LAYER_KEYS
            self.input_layernorm.weight, self.input_layernorm.bias,
            a.query_key_value.weight, a.query_key_value.bias,
            a.dense.weight, a.dense.bias,
            self.post_attention_layernorm.weight, self.post_attention_layernorm.bias,
            m.dense_h_to_4h.weight, m.dense_h_to_4h.bias,
            m.dense_4h_to_h.weight, m.dense_4h_to_h.bias,
        ]

    def forward(self, x):
        cfg = self.config
        positions = jnp.arange(x.shape[1])
        d = cfg.hidden_size // cfg.num_attention_heads
        rotary_ndims = int(d * cfg.rotary_pct)

        def fn(xv, *flat):
            from ..ops.attention import sdpa_tpu

            l = dict(zip(_LAYER_KEYS, flat))
            q, k, v = neox_attn_in(
                l, xv, positions,
                n_head=cfg.num_attention_heads, rotary_ndims=rotary_ndims,
                base=cfg.rotary_emb_base, eps=cfg.layer_norm_eps,
            )
            att = sdpa_tpu(q, k, v, is_causal=True)
            return neox_attn_out(l, xv, att, eps=cfg.layer_norm_eps)

        return nn.tape_op(maybe_remat(fn), x, *self.param_tensors())


class GPTNeoXForCausalLM(nn.Module):
    _no_split_modules = ["GPTNeoXLayer"]
    tp_plan = {
        r".*\.query_key_value\.weight": ("tp", None),
        r".*\.query_key_value\.bias": ("tp",),
        r".*\.dense\.weight": (None, "tp"),
        r".*\.dense_h_to_4h\.weight": ("tp", None),
        r".*\.dense_h_to_4h\.bias": ("tp",),
        r".*\.dense_4h_to_h\.weight": (None, "tp"),
        r"embed_in\.weight": ("tp", None),
        r"embed_out\.weight": ("tp", None),
    }

    def __init__(self, config: GPTNeoXConfig):
        super().__init__()
        self.config = config
        self.embed_in = nn.Embedding(config.vocab_size, config.hidden_size)
        self.layers = nn.ModuleList(
            [GPTNeoXLayer(config) for _ in range(config.num_hidden_layers)]
        )
        self.final_layer_norm = nn.LayerNorm(
            config.hidden_size, eps=config.layer_norm_eps
        )
        self.embed_out = nn.Linear(config.hidden_size, config.vocab_size, bias=False)
        from ..nn import random as nn_random
        from ..nn.meta import is_meta

        std = config.initializer_range
        for name, p in self.named_parameters():
            if is_meta(p.data):
                continue
            if p.ndim >= 2:
                p.data = std * jax.random.normal(nn_random.next_key(), p.shape, p.dtype)
            elif name.endswith("bias"):
                p.data = jnp.zeros_like(p.data)

    def forward(self, input_ids, labels=None):
        from ..parallel.sharding import constrain_activation

        ids = jnp.asarray(input_ids.data if isinstance(input_ids, Tensor) else input_ids)
        x = self.embed_in(ids)
        x = constrain_activation(x)
        for layer in self.layers:
            x = constrain_activation(layer(x))
        x = self.final_layer_norm(x)
        if labels is not None:
            loss, logits = lm_head_loss(
                x, self.embed_out, labels, self.config.vocab_size
            )
            return {"loss": loss, "logits": logits}
        return {"logits": self.embed_out(x)}

    def generate(self, input_ids, max_new_tokens: int, temperature: float = 0.0,
                 rng=None, quantize_weights=None, **kwargs):
        from .generation import generate

        return generate(self, input_ids, max_new_tokens, temperature, rng,
                        quantize_weights=quantize_weights, **kwargs)

    @property
    def num_flops_per_token(self) -> float:
        n = self.num_parameters
        c = self.config
        return 6 * n + 12 * c.num_hidden_layers * c.hidden_size * c.max_position_embeddings

    def _decoder_spec(self):
        from .generation import DecoderSpec

        cfg = self.config
        d = cfg.hidden_size // cfg.num_attention_heads
        return DecoderSpec(
            family=NEOX_DECODER,
            cfg=_NeoXDecodeCfg(
                n_head=cfg.num_attention_heads,
                n_kv_head=cfg.num_attention_heads,
                head_dim=d,
                rotary_ndims=int(d * cfg.rotary_pct),
                base=cfg.rotary_emb_base,
                eps=cfg.layer_norm_eps,
            ),
            max_len=cfg.max_position_embeddings,
            stack=self._stack_decoder_params,
        )

    def _stack_decoder_params(self) -> tuple[dict, dict]:
        stacks = [b.param_tensors() for b in self.layers]
        layers = {
            key: jnp.stack([ts[i].data for ts in stacks])
            for i, key in enumerate(_LAYER_KEYS)
        }
        g = {
            "wte": self.embed_in.weight.data,
            "ln_f_w": self.final_layer_norm.weight.data,
            "ln_f_b": self.final_layer_norm.bias.data,
            "head_w": self.embed_out.weight.data,
        }
        return g, layers


@dataclasses.dataclass(frozen=True)
class _NeoXDecodeCfg:
    n_head: int
    n_kv_head: int
    head_dim: int
    rotary_ndims: int
    base: float
    eps: float


def _dec_embed(g, ids, positions, cfg):
    return g["wte"][ids]


def _dec_attn_in(l, x, positions, cfg):
    return neox_attn_in(
        l, x, positions,
        n_head=cfg.n_head, rotary_ndims=cfg.rotary_ndims,
        base=cfg.base, eps=cfg.eps,
    )


def _dec_attn_out(l, x, att, cfg):
    return neox_attn_out(l, x, att, eps=cfg.eps)


def _dec_finalize(g, x, cfg):
    x = _pure_layernorm(x[:, -1], g["ln_f_w"], g["ln_f_b"], cfg.eps)
    return x @ g["head_w"].T


def _make_decoder():
    from .generation import DecoderFamily

    return DecoderFamily(
        embed=_dec_embed,
        attn_in=_dec_attn_in,
        attn_out=_dec_attn_out,
        finalize=_dec_finalize,
    )


NEOX_DECODER = _make_decoder()
