"""graftlint engine: discovery, suppression comments, baseline, rule runner.

Pure-stdlib AST analysis — importing this module must never import jax (the
CLI runs it in a few hundred milliseconds so it can sit inside ``make test``).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import time
import tokenize
from typing import Iterable, Optional, Sequence, Union

# Suppression comment grammar (the leading hash is spelled \x23 here so this
# very comment can't register itself): "\x23 graftlint: disable=rule-a,rule-b"
# on (or as the comment line above) the offending line;
# "\x23 graftlint: disable-file=rule-a" anywhere silences a whole file.  A
# bare "disable" with no =list silences every rule.  Anything after the rule
# list (a justification like "-- profiling only") is ignored.
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<scope>-file)?(?:\s*=\s*(?P<rules>[A-Za-z0-9_,\- ]+))?"
)
_RULE_TOKEN_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_-]*$")

# Bumping this invalidates every on-disk cache entry (cache.py keys on it):
# bump whenever a rule or the graph machinery changes what it reports for
# unchanged source.  v3: dtype-widen gained the quantized-payload check.
# v4: recompile-hazard gained the serving bucketing contract (raw request
# lengths into run_prefill/run_decode).
# v5: blocking-in-hot-loop gained the profiler-session check
# (jax.profiler start/stop_trace in a loop without sampled-cadence
# evidence; a profiling-knob guard alone no longer exempts those calls).
# v6: recompile-hazard gained the AOT executable cache-key contract
# (deserialize_and_load of a serialized executable without a fingerprint/
# cache-key check in scope — a stale entry from another topology or jax
# version must fall through to a compile, never dispatch; docs/aot_cache.md).
# v7: the call graph resolves instance-method dispatch through cheap type
# inference over single-assignment locals (`obj = SomeClass(); obj.method(x)`
# links to SomeClass.method, same-module and through imports), so every
# reachability rule sees traced code calling into helper-object methods.
# v8: new pallas-hazard rule — host callbacks / python-side branches on ref
# parameters inside pl.pallas_call kernel bodies, and pallas_call sites
# without an interpret=/policy-gated fallback in scope (docs/kernels.md).
# v9: instance-dispatch inference joins over branches — a receiver rebound
# across branches to the SAME class (`obj = Cls() if fast else Cls(opts)`)
# now links `obj.method` to Cls.method; receivers rebound to different
# classes (or to non-constructor values) stay uninferred.
# v10: instance-dispatch inference through factory returns — a receiver
# bound from a same-module TOP-LEVEL function whose returns are ALL
# `SomeClass(...)` constructors of one class (`obj = make_runner();
# obj.work(x)`) resolves to SomeClass.work, joining over branches with
# direct constructor binds.  Mixed-class or non-constructor returns,
# same-named factories that disagree, methods/nested defs (bare name not
# module-callable), and locally-shadowed names (an injected callable
# parameter is DATA, not the module factory) all leave the receiver
# uninferred.
# v12: (a) new collective-divergence rule family — an interprocedural
# rank-divergence taint pass (taint.py: rank-identity/rank-local-record/
# fs-probe/wall-clock/per-host-env sources, gather/agree_* symmetry kills,
# single-process world-size exemption) feeds three checks: a collective
# sink guarded by rank-divergent control flow, early return/raise on a
# tainted branch before a later collective, and mismatched collective
# counts across sibling branches of a tainted conditional; the program
# graph grew divergent-return and reaches-collective closures
# (divergent_aliases / collective_aliases) to carry both facts across
# modules.  (b) factory-return dispatch inference now chases
# factory→factory delegation chains (same-module pre-resolution in
# callgraph.py, cross-module chasing in program.py) and multi-hop
# re-export paths, closing the v11 single-hop carve-out.
# v11: (a) new stage-boundary-vs-plan rule — pp axis sizes / stage layer
# spans derived outside the resolved ParallelPlan (mesh.shape pp reads,
# literal P('pp') specs, hand-sliced layers-per-stage arithmetic) fire in
# consumer modules (docs/parallel_plan.md); (b) factory-return dispatch
# inference through SINGLE-HOP imports — `from mod import make_thing;
# obj = make_thing(); obj.m(x)` resolves through mod's v10 factory map to
# the constructed class (factory→factory chains and re-exported factories
# stay uninferred); (c) a bare-name constructor call whose name is locally
# bound (parameter/assignment) now records NO ctor bind at all, so
# shadowed names can never mis-resolve through the new import hop.
# v13: stage-boundary-vs-plan learned the prepare-time layer-layout
# contract — jnp.take/jnp.argsort driven by a layer-order index (an
# in-program stacked-layer permutation inside a captured pipeline body)
# fires in consumer modules with a commit-at-prepare fix hint
# (docs/parallel_plan.md §layout contract).
ANALYSIS_VERSION = "13"

# Names that mark a branch/function as profiling/benchmark plumbing, where a
# deliberate host sync is legitimate.  Shared by blocking-in-hot-loop and the
# whole-program transitive-blocking closure (program.py).
GUARD_NAME_RE = re.compile(
    r"profil|debug|verbose|bench|warmup|timing|timeit|trace|sync_every|"
    r"sync_each|log_every|barrier|measure",
    re.IGNORECASE,
)


def is_guard_expr(test: ast.AST) -> bool:
    """True when a test expression mentions a profiling/debug knob."""
    for node in ast.walk(test):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and GUARD_NAME_RE.search(name):
            return True
    return False


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    symbol: str = ""  # enclosing function qualname (stable across line drift)

    def fingerprint(self) -> str:
        """Line-number-free identity used by the baseline file, so grandfathered
        findings survive unrelated edits above them."""
        key = "|".join((self.rule, self.path.replace(os.sep, "/"), self.symbol, self.message))
        return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{loc}: {self.rule}: {self.message}{sym}"


class Rule:
    """Base class: subclasses set ``id``/``description``/``kind`` and
    implement check().  ``kind`` is "reachability" when the rule consumes the
    traced-region call graph (so it benefits from cross-module analysis) and
    "syntactic" when it fires on local syntax alone — `--list-rules` prints
    it so suppression triage knows which findings can shift when
    whole-program mode is toggled."""

    id: str = ""
    description: str = ""
    kind: str = "syntactic"
    # one-line remediation shown in SARIF output (rule help + appended to
    # each result message) so CI annotations carry the fix, not just the
    # diagnosis
    fix_hint: str = ""

    def check(self, module: "ModuleInfo", ctx: "AnalysisContext") -> list[Finding]:
        raise NotImplementedError


def _dotted(node: ast.AST) -> Optional[str]:
    from .callgraph import dotted_name

    return dotted_name(node)


def _collect_aliases(tree: ast.AST) -> dict[str, str]:
    """alias -> canonical dotted prefix, from every import in the file.

    ``import jax.numpy as jnp`` → jnp: jax.numpy; ``from jax import lax`` →
    lax: jax.lax; relative imports keep their module tail (suffix matching in
    the rules absorbs the missing package prefix).
    """
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                full = f"{base}.{a.name}" if base else a.name
                aliases[a.asname or a.name] = full
    return aliases


def _collect_import_records(tree: ast.AST) -> list[dict]:
    """Raw import statements with their relative level preserved — the
    program graph resolves these against the package layout on disk
    (``_collect_aliases`` flattens levels away, which is fine for dotted-name
    canonicalization but loses what ``from ..x import f`` points at)."""
    records: list[dict] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            records.append(
                {
                    "kind": "import",
                    "names": [[a.name, a.asname] for a in node.names],
                }
            )
        elif isinstance(node, ast.ImportFrom):
            records.append(
                {
                    "kind": "from",
                    "module": node.module or "",
                    "level": node.level,
                    "names": [[a.name, a.asname] for a in node.names if a.name != "*"],
                }
            )
    return records


def _parse_rule_list(raw: Optional[str]) -> set[str]:
    """Rule ids from the text after `disable=`, tolerating a trailing
    justification: each comma part contributes its first word, and parsing
    stops at the first word that isn't a rule-shaped token (`-- because...`)."""
    if raw is None:
        return {"all"}
    rules: set[str] = set()
    for part in raw.split(","):
        words = part.split()
        if not words or not _RULE_TOKEN_RE.match(words[0]):
            break
        rules.add(words[0])
    return rules or {"all"}


def _collect_suppressions(source: str):
    """Suppressions from real COMMENT tokens only — a docstring that merely
    *mentions* the syntax must not disable anything, so the raw-line regex
    approach is out; we tokenize."""
    per_line: dict[int, set[str]] = {}
    per_file: set[str] = set()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return per_line, per_file  # ast.parse already vets the file upstream
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = _parse_rule_list(m.group("rules"))
        if m.group("scope"):
            per_file |= rules
        else:
            line = tok.start[0]
            per_line.setdefault(line, set()).update(rules)
            if tok.line[: tok.start[1]].strip() == "":
                # comment-only line: also covers the next line (pylint-style)
                per_line.setdefault(line + 1, set()).update(rules)
    return per_line, per_file


class ModuleInfo:
    """One parsed file plus the derived maps every rule shares."""

    def __init__(self, path: str, rel_path: str, source: str):
        self.path = path
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.aliases = _collect_aliases(self.tree)
        self.import_records = _collect_import_records(self.tree)
        self.line_suppressions, self.file_suppressions = _collect_suppressions(source)
        # module-level `NAME = "literal"` string constants (axis-name rule
        # resolves bare-Name axis arguments through this)
        self.str_constants: dict[str, str] = {}
        for node in self.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                self.str_constants[node.targets[0].id] = node.value.value
        self._callgraph = None

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of an expression, with import aliases applied
        to the head segment (``jnp.zeros`` → ``jax.numpy.zeros``)."""
        d = _dotted(node)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        base = self.aliases.get(head, head)
        return f"{base}.{rest}" if rest else base

    @property
    def callgraph(self):
        if self._callgraph is None:
            from .callgraph import CallGraph

            self._callgraph = CallGraph(self)
        return self._callgraph

    def is_suppressed(self, finding: Finding) -> bool:
        if {"all", finding.rule} & self.file_suppressions:
            return True
        rules = self.line_suppressions.get(finding.line, ())
        return "all" in rules or finding.rule in rules


@dataclasses.dataclass
class AnalysisContext:
    """Cross-file facts collected in a first pass before rules run."""

    axis_universe: set[str] = dataclasses.field(default_factory=set)
    axis_sources: dict[str, str] = dataclasses.field(default_factory=dict)
    # tensor → recorded PartitionSpec (JSON form) from a checkpoint
    # index.json, when the caller passed one (sharding-spec-drift input)
    ckpt_specs: dict[str, list] = dataclasses.field(default_factory=dict)
    # whole-program facts (program.ProgramGraph output), keyed by rel_path.
    # Filled from the per-module summaries in both modes; with cross-module
    # analysis off the maps only carry same-module entries.
    cross_module: bool = True
    # extra traced functions per module, beyond its own local roots:
    # rel_path -> {qualname: reason}
    cross_reached: dict = dataclasses.field(default_factory=dict)
    # rel_path -> {visible callable name (bare or dotted): donated positions}
    donor_aliases: dict = dataclasses.field(default_factory=dict)
    # rel_path -> {visible callable name: {"positions": [...], "where": ...}}
    # for helpers that STORE a parameter beyond the call (transitive-donation)
    escape_aliases: dict = dataclasses.field(default_factory=dict)
    # rel_path -> {visible callable name: chain} for functions that
    # transitively hit block_until_ready/effects_barrier (blocking rule)
    blocking_aliases: dict = dataclasses.field(default_factory=dict)
    # rel_path -> {visible callable name / Cls.method qualname: chain} for
    # functions whose RETURN VALUE is rank-divergent (taint.py sources
    # propagated through the program graph's divergence closure)
    divergent_aliases: dict = dataclasses.field(default_factory=dict)
    # rel_path -> {visible callable name / qualname: chain} for functions
    # that transitively issue a collective op (collective-divergence sinks)
    collective_aliases: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AnalysisResult:
    findings: list[Finding]
    new_findings: list[Finding]  # findings minus the baseline
    files_analyzed: int
    duration_s: float
    suppressed: int
    cross_module: bool = True
    cache_hits: int = 0
    cache_misses: int = 0
    # baseline fingerprints that matched NO current finding: the grand-
    # fathered debt was paid (or the code moved), so the stale entry must
    # leave the baseline — "exits 0 on exact matches only"
    baseline_stale: list = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "files_analyzed": self.files_analyzed,
            "duration_s": round(self.duration_s, 3),
            "suppressed": self.suppressed,
            "baseline_filtered": len(self.findings) - len(self.new_findings),
            "baseline_stale": list(self.baseline_stale),
            "cross_module": self.cross_module,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "findings": [f.to_dict() for f in self.new_findings],
        }


_SKIP_DIRS = {"__pycache__", ".git", ".hg", "node_modules", "build", "dist"}


def discover_files(paths: Iterable[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs if d not in _SKIP_DIRS and not d.startswith(".")
                )
                out.extend(
                    os.path.join(root, f) for f in sorted(files) if f.endswith(".py")
                )
        else:
            raise FileNotFoundError(p)
    return out


# ---------------------------------------------------------------------------
# axis-universe collection (first pass; consumed by the axis-name rule)
# ---------------------------------------------------------------------------

# Fallback when the analyzed tree declares no mesh at all (e.g. a lone
# fixture file): the framework's canonical axes from utils/constants.py.
# Named so the harvester below does NOT match it ("AXES"/"MESH_AXIS"
# patterns) — the linter's own fallback must never feed the harvested
# universe when this package is itself the analysis target.
FALLBACK_AXIS_UNIVERSE = ("dp", "fsdp", "tp", "sp", "ep", "pp")


def _literal_strs(node: ast.AST) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def collect_axes(module: ModuleInfo) -> list[tuple[str, str]]:
    """Harvest ``(axis, why)`` declarations from one module.  Pure so the
    result can live in the per-module summary cache."""
    out: list[tuple[str, str]] = []

    def add(name: str, why: str) -> None:
        out.append((name, why))

    for node in ast.walk(module.tree):
        # MESH_AXIS_DP = "dp" / ALL_MESH_AXES = (MESH_AXIS_DP, ...)
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                if tgt.id.startswith("MESH_AXIS"):
                    for s in _literal_strs(node.value):
                        add(s, tgt.id)
                elif "AXES" in tgt.id:
                    for s in _literal_strs(node.value):
                        add(s, tgt.id)
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        for e in node.value.elts:
                            if isinstance(e, ast.Name) and e.id in module.str_constants:
                                add(module.str_constants[e.id], tgt.id)
        elif isinstance(node, ast.Call):
            resolved = module.resolve(node.func) or ""
            leaf = resolved.rsplit(".", 1)[-1]
            # Mesh(devs, axis_names=(...)) / Mesh(devs, ("dp", ...))
            if leaf in ("Mesh", "AbstractMesh", "make_mesh"):
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        for s in _literal_strs(kw.value):
                            add(s, "axis_names=")
                if leaf in ("Mesh", "AbstractMesh") and len(node.args) >= 2:
                    for s in _literal_strs(node.args[1]):
                        add(s, "Mesh(...)")
                # make_mesh({"dp": 2, ...})
                if leaf == "make_mesh" and node.args and isinstance(node.args[0], ast.Dict):
                    for k in node.args[0].keys:
                        if isinstance(k, ast.Constant) and isinstance(k.value, str):
                            add(k.value, "make_mesh({...})")
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> set[str]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"] for e in data.get("findings", [])}


def write_baseline(findings: Sequence[Finding], path: str) -> None:
    data = {
        "comment": (
            "graftlint baseline: grandfathered findings (by line-free "
            "fingerprint). Regenerate with --write-baseline."
        ),
        "findings": [
            {
                "fingerprint": f.fingerprint(),
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "message": f.message,
            }
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# ---------------------------------------------------------------------------
# SARIF (CI annotation format; tools/sarif_check.py validates the shape)
# ---------------------------------------------------------------------------

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def sarif_report(result: "AnalysisResult", rules: Sequence[Rule]) -> dict:
    """Minimal SARIF 2.1.0 document for ``result.new_findings``: one run,
    the rule table (with each rule's fix hint as its help text), and one
    result per finding with rule id, level, message and a physical region.
    The line-free fingerprint rides along as a partialFingerprint so SARIF
    consumers dedupe across line drift exactly like the baseline does."""
    by_id = {r.id: r for r in rules}
    rules_meta = []
    listed: set[str] = set()

    def add_rule(rule_id: str, description: str, hint: str) -> None:
        if rule_id in listed:
            return
        listed.add(rule_id)
        meta = {
            "id": rule_id,
            "shortDescription": {"text": description},
            "defaultConfiguration": {"level": "error"},
        }
        if hint:
            meta["help"] = {"text": hint}
        rules_meta.append(meta)

    for r in rules:
        add_rule(r.id, r.description, r.fix_hint)
    results = []
    for f in result.new_findings:
        rule = by_id.get(f.rule)
        if rule is None:
            # syntax-error findings carry no Rule instance
            add_rule(f.rule, "file failed to parse", "fix the syntax error")
        message = f.message
        hint = rule.fix_hint if rule is not None else ""
        if hint:
            message = f"{message} — fix: {hint}"
        results.append(
            {
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": message},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": f.path.replace(os.sep, "/")
                            },
                            "region": {
                                "startLine": max(f.line, 1),
                                "startColumn": f.col + 1,
                            },
                        }
                    }
                ],
                "partialFingerprints": {"graftlint/v1": f.fingerprint()},
            }
        )
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "version": ANALYSIS_VERSION,
                        "informationUri": "docs/graftlint.md",
                        "rules": rules_meta,
                    }
                },
                "results": results,
            }
        ],
    }


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def load_ckpt_specs(path: str) -> dict[str, list]:
    """Recorded {tensor: PartitionSpec-as-JSON} from a sharded checkpoint.

    ``path`` may be one ``*.index.json`` file or a checkpoint directory, in
    which case every ``*.index.json`` inside contributes.  Tensors whose
    entry predates the spec record (older checkpoints) are skipped.
    """
    index_files = []
    if os.path.isdir(path):
        index_files = [
            os.path.join(path, f)
            for f in sorted(os.listdir(path))
            if f.endswith(".index.json")
        ]
        if not index_files:
            raise FileNotFoundError(f"no *.index.json files under {path}")
    else:
        index_files = [path]
    specs: dict[str, list] = {}
    for f in index_files:
        with open(f, encoding="utf-8") as fh:
            data = json.load(fh)
        for tensor, entry in data.get("tensors", {}).items():
            if isinstance(entry, dict) and "spec" in entry:
                specs[tensor] = entry["spec"]
    return specs


@dataclasses.dataclass
class _FileRecord:
    """One discovered file through the pipeline: parsed eagerly on a cache
    miss, replayed from its cached summary otherwise."""

    path: str
    rel_path: str
    content_hash: str
    source: str
    module: Optional[ModuleInfo]
    summary: object  # program.ModuleSummary
    cache_entry: Optional[dict]


def _module_env_hash(rel: str, rule_ids: Sequence[str], ctx: AnalysisContext, ckpt_hash: str) -> str:
    """Everything OUTSIDE a module's own text that its findings depend on.
    The findings cache is keyed on (content hash, this) — so editing file A
    re-analyzes A via the content hash, and re-analyzes B only when A's edit
    actually changed what B sees (its cross-module reached set, the axis
    universe, visible donors/escapers/blockers, the checkpoint specs)."""
    payload = {
        "version": ANALYSIS_VERSION,
        "rules": list(rule_ids),
        "cross": ctx.cross_module,
        "axes": sorted(ctx.axis_universe),
        "reached": sorted(ctx.cross_reached.get(rel, {}).items()),
        "donors": sorted(
            (k, list(v)) for k, v in ctx.donor_aliases.get(rel, {}).items()
        ),
        "escapes": sorted(
            (k, sorted(v["positions"]), v["where"])
            for k, v in ctx.escape_aliases.get(rel, {}).items()
        ),
        "blocking": sorted(ctx.blocking_aliases.get(rel, {}).items()),
        "divergent": sorted(ctx.divergent_aliases.get(rel, {}).items()),
        "collective": sorted(ctx.collective_aliases.get(rel, {}).items()),
        "ckpt": ckpt_hash,
    }
    blob = json.dumps(payload, sort_keys=True).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()[:24]


def run_analysis(
    paths: Sequence[str],
    rules: Optional[Sequence[Rule]] = None,
    baseline: Optional[set[str]] = None,
    ckpt_index: Optional[Union[str, dict]] = None,
    cross_module: bool = True,
    cache_dir: Optional[str] = None,
) -> AnalysisResult:
    from .cache import AnalysisCache
    from .program import ModuleSummary, ProgramGraph, extract_summary

    if rules is None:
        from .rules import ALL_RULES

        rules = [cls() for cls in ALL_RULES]
    rule_ids = sorted(r.id for r in rules)
    t0 = time.monotonic()
    files = discover_files(paths)
    cwd = os.getcwd()
    ctx = AnalysisContext(cross_module=cross_module)
    if ckpt_index:
        # a dict is an already-loaded {tensor: spec} mapping (the CLI
        # validates + loads once and hands it over); a str is a path
        ctx.ckpt_specs = (
            dict(ckpt_index)
            if isinstance(ckpt_index, dict)
            else load_ckpt_specs(ckpt_index)
        )
    # the branch namespace must come from the *analyzed* tree, which need
    # not be the process CWD (out-of-tree `graftlint /path/to/checkout`)
    analysis_root = os.path.dirname(files[0]) if files else cwd
    cache = AnalysisCache(cache_dir, root=analysis_root) if cache_dir else None

    # -- pass 1: summaries (cache-replayed or freshly extracted) ------------
    records: list[_FileRecord] = []
    for path in files:
        rel = os.path.relpath(path, cwd) if os.path.isabs(path) else path
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except UnicodeDecodeError as e:
            records.append(
                _FileRecord(
                    path, rel, "", "", None,
                    ModuleSummary(error=f"cannot parse: {e}"), None,
                )
            )
            continue
        content_hash = hashlib.sha256(source.encode("utf-8")).hexdigest()
        entry = cache.load(rel, content_hash) if cache else None
        if entry is not None:
            summary = ModuleSummary.from_dict(entry["summary"])
            records.append(
                _FileRecord(path, rel, content_hash, source, None, summary, entry)
            )
            continue
        try:
            module = ModuleInfo(path, rel, source)
        except SyntaxError as e:
            lineno = getattr(e, "lineno", 0) or 0
            summary = ModuleSummary(error=f"cannot parse: {e}", error_line=lineno)
            module = None
        else:
            summary = extract_summary(module)
        entry = {"summary": summary.to_dict(), "results": {}} if cache else None
        records.append(
            _FileRecord(path, rel, content_hash, source, module, summary, entry)
        )

    # -- pass 2: cross-file facts (axis universe + whole-program graph) -----
    for r in records:
        for axis, why in r.summary.axes:
            ctx.axis_universe.add(axis)
            ctx.axis_sources.setdefault(axis, f"{r.rel_path}: {why}")
    if not ctx.axis_universe:
        ctx.axis_universe = set(FALLBACK_AXIS_UNIVERSE)
        ctx.axis_sources = {
            a: "builtin default (no mesh declaration found)"
            for a in FALLBACK_AXIS_UNIVERSE
        }
    program = ProgramGraph(records, cross=cross_module)
    ctx.cross_reached = program.cross_reached
    ctx.donor_aliases = program.donor_aliases
    ctx.escape_aliases = program.escape_aliases
    ctx.blocking_aliases = program.blocking_aliases
    ctx.divergent_aliases = program.divergent_aliases
    ctx.collective_aliases = program.collective_aliases

    ckpt_hash = (
        hashlib.sha256(
            json.dumps(ctx.ckpt_specs, sort_keys=True).encode("utf-8")
        ).hexdigest()
        if ctx.ckpt_specs
        else ""
    )

    # -- pass 3: rules (per module, findings cache-keyed on content + env) --
    findings: list[Finding] = []
    suppressed = 0
    cache_hits = cache_misses = 0
    for r in records:
        if r.summary.error:
            findings.append(
                Finding("syntax-error", r.rel_path, r.summary.error_line, 0, r.summary.error)
            )
            continue
        env = _module_env_hash(r.rel_path, rule_ids, ctx, ckpt_hash)
        cached = r.cache_entry["results"].get(env) if r.cache_entry else None
        if cached is not None:
            for fd in cached["findings"]:
                findings.append(
                    Finding(
                        fd["rule"], fd["path"], fd["line"], fd["col"],
                        fd["message"], fd.get("symbol", ""),
                    )
                )
            suppressed += cached["suppressed"]
            cache_hits += 1
            results = r.cache_entry["results"]
            if next(reversed(results)) != env:
                # LRU refresh: move the env just used to most-recent, so the
                # eviction below drops stale variants, not the busiest one
                results[env] = results.pop(env)
                cache.store(r.rel_path, r.content_hash, r.cache_entry)
            continue
        module = r.module
        if module is None:  # cached summary but stale/absent findings: parse
            # the pass-1 source (NOT a re-read — the file may have changed
            # since, and findings are stored under the pass-1 content hash)
            module = ModuleInfo(r.path, r.rel_path, r.source)
        # inject the whole-program reachability before any rule looks at it
        module.callgraph.reached.update(ctx.cross_reached.get(r.rel_path, {}))
        mod_findings: list[Finding] = []
        mod_suppressed = 0
        for rule in rules:
            for f in rule.check(module, ctx):
                if module.is_suppressed(f):
                    mod_suppressed += 1
                else:
                    mod_findings.append(f)
        findings.extend(mod_findings)
        suppressed += mod_suppressed
        if cache is not None and r.cache_entry is not None:
            cache_misses += 1
            results = r.cache_entry["results"]
            results[env] = {
                "findings": [dataclasses.asdict(f) for f in mod_findings],
                "suppressed": mod_suppressed,
            }
            while len(results) > 8:  # drop the least-recently-used variants
                results.pop(next(iter(results)))
            cache.store(r.rel_path, r.content_hash, r.cache_entry)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    stale: list[str] = []
    if baseline:
        prints = {f.fingerprint() for f in findings}
        new = [f for f in findings if f.fingerprint() not in baseline]
        stale = sorted(baseline - prints)
    else:
        new = list(findings)
    return AnalysisResult(
        findings=findings,
        new_findings=new,
        files_analyzed=len(files),
        duration_s=time.monotonic() - t0,
        suppressed=suppressed,
        cross_module=cross_module,
        cache_hits=cache_hits,
        cache_misses=cache_misses,
        baseline_stale=stale,
    )
