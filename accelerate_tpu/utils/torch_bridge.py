"""Live torch.nn.Module → accelerate_tpu.nn conversion.

The reference's ``prepare_model`` accepts any ``torch.nn.Module`` (reference
accelerator.py:1421).  A JAX rebuild cannot run arbitrary torch forwards, but
the two cases that cover the reference's own test/bench surface convert
exactly:

1. **Known transformers architectures** (Bert* / GPT2* / Llama*/Mistral* / OPT* / GPT-J / GPT-NeoX / T5):
   rebuilt as the native ``models/`` classes with the torch state dict
   name-mapped in (``utils/hf.py``) — the native forward reproduces the HF
   forward (parity-tested in tests/test_torch_bridge.py, tests/test_llama.py,
   tests/test_opt.py).
2. **Structural containers** (``torch.nn.Sequential`` of standard layers —
   Linear/Embedding/LayerNorm/Dropout/activations): converted layer-by-layer;
   the container's semantics ARE its structure, so conversion is exact.
   This covers the reference's RegressionModel-style test models.

Anything else raises with guidance: write the model against
``accelerate_tpu.nn`` (same API shape as torch.nn) or load weights via
``utils/hf.py``.  ``Accelerator.prepare`` calls ``maybe_convert`` so the
reference's "wrap an existing torch loop" flow works unchanged for these
cases.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np


def is_torch_module(obj: Any) -> bool:
    try:
        import torch

        return isinstance(obj, torch.nn.Module)
    except ImportError:
        return False


def _to_np(t) -> np.ndarray:
    return t.detach().cpu().numpy()


def _convert_leaf(tm):
    """Convert one standard torch layer; return None when unsupported."""
    import torch

    from .. import nn

    if isinstance(tm, torch.nn.Linear):
        ours = nn.Linear(tm.in_features, tm.out_features, bias=tm.bias is not None)
        ours.weight.data = jnp.asarray(_to_np(tm.weight))
        if tm.bias is not None:
            ours.bias.data = jnp.asarray(_to_np(tm.bias))
        return ours
    if isinstance(tm, torch.nn.Embedding):
        ours = nn.Embedding(tm.num_embeddings, tm.embedding_dim)
        ours.weight.data = jnp.asarray(_to_np(tm.weight))
        return ours
    if isinstance(tm, torch.nn.LayerNorm):
        ours = nn.LayerNorm(tuple(tm.normalized_shape), eps=tm.eps,
                            elementwise_affine=tm.elementwise_affine)
        if tm.elementwise_affine:
            ours.weight.data = jnp.asarray(_to_np(tm.weight))
            ours.bias.data = jnp.asarray(_to_np(tm.bias))
        return ours
    if isinstance(tm, torch.nn.Dropout):
        return nn.Dropout(tm.p)
    if isinstance(tm, torch.nn.ReLU):
        return nn.ReLU()
    if isinstance(tm, torch.nn.GELU):
        return nn.GELU()
    if isinstance(tm, torch.nn.Tanh):
        return nn.Tanh()
    if isinstance(tm, torch.nn.Sigmoid):
        return nn.Sigmoid()
    if isinstance(tm, torch.nn.Identity):
        return nn.Identity()
    if isinstance(tm, torch.nn.Sequential):
        return _convert_sequential(tm)
    return None


def _convert_sequential(tm):
    from .. import nn

    converted = []
    for i, child in enumerate(tm):
        ours = _convert_leaf(child)
        if ours is None:
            raise TypeError(
                f"cannot convert torch layer {type(child).__name__} at position "
                f"{i} of Sequential; supported: Linear, Embedding, LayerNorm, "
                "Dropout, ReLU, GELU, Tanh, Sigmoid, Identity, nested Sequential"
            )
        converted.append(ours)
    return nn.Sequential(*converted)


def _convert_transformers(tm):
    """Known HF architectures → native models with name-mapped weights."""
    from .hf import (
        bert_config_from_hf,
        gpt2_config_from_hf,
        gptj_config_from_hf,
        gptneox_config_from_hf,
        llama_config_from_hf,
        load_mapped_state_dict,
        map_bert_key,
        map_gpt2_key,
        map_gptj_key,
        map_gptneox_key,
        map_llama_key,
        map_opt_key,
        map_t5_key,
        opt_config_from_hf,
        t5_config_from_hf,
    )

    cls_name = type(tm).__name__
    config = getattr(tm, "config", None)
    if config is None:
        return None
    cfg = config.to_dict() if hasattr(config, "to_dict") else dict(config)
    state = {k: _to_np(v) for k, v in tm.state_dict().items()}

    if cls_name in ("BertForSequenceClassification", "BertModel"):
        from ..models.bert import BertForSequenceClassification

        num_labels = getattr(config, "num_labels", 2)
        model = BertForSequenceClassification(bert_config_from_hf(cfg, num_labels))
        load_mapped_state_dict(model, state, map_bert_key)
        return model
    if cls_name in ("GPT2LMHeadModel", "GPT2Model"):
        from ..models.gpt import GPTLMHeadModel

        gcfg = gpt2_config_from_hf(cfg)
        model = GPTLMHeadModel(gcfg)
        load_mapped_state_dict(model, state, map_gpt2_key, pad_vocab_to=gcfg.vocab_size)
        return model
    if cls_name in ("LlamaForCausalLM", "LlamaModel",
                    "MistralForCausalLM", "MistralModel"):
        # Mistral is the Llama architecture with GQA + sliding window; the
        # HF state-dict layout and key names are identical, and
        # llama_config_from_hf picks up cfg["sliding_window"]
        from ..models.llama import LlamaForCausalLM

        model = LlamaForCausalLM(llama_config_from_hf(cfg))
        missing, _ = load_mapped_state_dict(model, state, map_llama_key)
        if model.config.tie_word_embeddings:
            missing = [m for m in missing if "lm_head" not in m]
        if missing:
            # a bare LlamaModel has no (untied) lm_head: converting it would
            # silently leave a randomly-initialised head producing garbage
            raise ValueError(
                f"{cls_name} conversion left weights uninitialised: "
                f"{missing[:4]} — pass the ForCausalLM class (the bare "
                "backbone model carries no LM head)"
            )
        return model
    if cls_name in ("OPTForCausalLM", "OPTModel"):
        from ..models.opt import OPTForCausalLM

        model = OPTForCausalLM(opt_config_from_hf(cfg))
        missing, _ = load_mapped_state_dict(model, state, map_opt_key)
        missing = [m for m in missing if "lm_head" not in m]  # tied to wte
        if missing:
            raise ValueError(f"OPT conversion left weights uninitialised: {missing[:4]}")
        return model
    if cls_name == "GPTJForCausalLM":
        from ..models.gptj import GPTJForCausalLM

        model = GPTJForCausalLM(gptj_config_from_hf(cfg))
        missing, _ = load_mapped_state_dict(model, state, map_gptj_key)
        if missing:  # untied biased head must come from the checkpoint
            raise ValueError(
                f"GPT-J conversion left weights uninitialised: {missing[:4]}"
            )
        return model
    if cls_name == "GPTNeoXForCausalLM":
        from ..models.gptneox import GPTNeoXForCausalLM

        model = GPTNeoXForCausalLM(gptneox_config_from_hf(cfg))
        missing, _ = load_mapped_state_dict(model, state, map_gptneox_key)
        if missing:
            raise ValueError(
                f"GPT-NeoX conversion left weights uninitialised: {missing[:4]}"
            )
        return model
    if cls_name == "T5ForConditionalGeneration":
        from functools import partial as _partial

        from ..models.t5 import T5ForConditionalGeneration

        t5cfg = t5_config_from_hf(cfg)
        model = T5ForConditionalGeneration(t5cfg)
        missing, _ = load_mapped_state_dict(
            model, state, _partial(map_t5_key, tied=t5cfg.tie_word_embeddings)
        )
        if t5cfg.tie_word_embeddings:
            missing = [m for m in missing if "lm_head" not in m]
        if missing:
            raise ValueError(
                f"T5 conversion left weights uninitialised: {missing[:4]}"
            )
        return model
    return None


def convert_torch_module(tm):
    """torch.nn.Module → accelerate_tpu.nn.Module (weights copied)."""
    converted = _convert_transformers(tm)
    if converted is None:
        converted = _convert_leaf(tm)
    if converted is None:
        raise TypeError(
            f"cannot convert {type(tm).__name__}: arbitrary torch forwards "
            "don't translate to XLA. Either (a) use a supported architecture "
            "(transformers Bert*/GPT2*/Llama*/OPT*, or Sequential of standard "
            "layers), (b) rewrite the model against accelerate_tpu.nn "
            "(torch-shaped API), or (c) load its checkpoint via "
            "accelerate_tpu.utils.hf.from_pretrained."
        )
    if tm.training:
        converted.train()
    else:
        converted.eval()
    return converted


def maybe_convert(obj):
    """Convert when ``obj`` is a torch module, else return unchanged."""
    if is_torch_module(obj):
        return convert_torch_module(obj)
    return obj


def is_torch_lr_scheduler(obj: Any) -> bool:
    try:
        import torch

        return isinstance(obj, torch.optim.lr_scheduler.LRScheduler)
    except (ImportError, AttributeError):
        return False


def convert_torch_scheduler(tsched, optimizer_pairs):
    """torch LR scheduler → native scheduler over the converted optimizer.

    Without this, a torch scheduler passed through ``prepare`` would keep
    stepping the *discarded* torch optimizer while the converted native
    optimizer trains at a frozen LR — silent wrong training.
    ``optimizer_pairs``: [(torch_opt, native_opt)] recorded during conversion.
    """
    import torch

    from .. import optim

    native_opt = None
    for topt, nopt in optimizer_pairs:
        if topt is tsched.optimizer:
            native_opt = nopt
            break
    if native_opt is None:
        raise ValueError(
            "torch LR scheduler references an optimizer that was not converted "
            "in this prepare() call; pass model, optimizer and scheduler to one "
            "prepare(...) together (reference flow), or build an "
            "accelerate_tpu.optim scheduler directly."
        )
    inner = native_opt.optimizer if hasattr(native_opt, "optimizer") else native_opt
    sched = tsched
    if isinstance(sched, torch.optim.lr_scheduler.LambdaLR):
        if len(sched.lr_lambdas) != 1:
            raise NotImplementedError("multi-group LambdaLR cannot be auto-converted")
        return optim.LambdaLR(inner, sched.lr_lambdas[0], last_epoch=sched.last_epoch - 1)
    if isinstance(sched, torch.optim.lr_scheduler.StepLR):
        return optim.StepLR(
            inner, sched.step_size, gamma=sched.gamma, last_epoch=sched.last_epoch - 1
        )
    if isinstance(sched, torch.optim.lr_scheduler.CosineAnnealingLR):
        return optim.CosineAnnealingLR(
            inner, sched.T_max, eta_min=sched.eta_min, last_epoch=sched.last_epoch - 1
        )
    raise TypeError(
        f"cannot convert {type(tsched).__name__}; supported: LambdaLR (incl. "
        "transformers get_*_schedule_with_warmup), StepLR, CosineAnnealingLR "
        "(or build an accelerate_tpu.optim scheduler directly)."
    )


def is_torch_optimizer(obj: Any) -> bool:
    try:
        import torch

        return isinstance(obj, torch.optim.Optimizer)
    except ImportError:
        return False


def convert_torch_optimizer(topt, converted_models):
    """torch.optim.{AdamW,Adam,SGD} → native optimizer over converted params.

    The reference re-points optimizer param groups at the prepared params
    (reference accelerator.py:1376-1410, the XLA param-identity remap); across
    the torch→JAX boundary param identity cannot survive, so the optimizer is
    rebuilt over the converted model's parameters with the torch
    hyperparameters.  Requires the standard flow — one optimizer over the
    converted model(s)' full parameter list, a single param group.
    """
    import torch

    from .. import optim

    if len(topt.param_groups) != 1:
        raise NotImplementedError(
            "torch optimizers with multiple param groups cannot be auto-"
            "converted; build an accelerate_tpu.optim optimizer directly."
        )
    group = topt.param_groups[0]
    n_torch = len(group["params"])
    params = [p for m in converted_models for p in m.parameters()]
    # tied weights appear once in parameters(); torch's dedup matches
    if n_torch != len(params):
        raise ValueError(
            f"torch optimizer has {n_torch} params but the converted model(s) "
            f"have {len(params)}; prepare() the model in the same call, before "
            "the optimizer."
        )
    if isinstance(topt, torch.optim.AdamW):
        return optim.AdamW(
            params,
            lr=group["lr"],
            betas=tuple(group["betas"]),
            eps=group["eps"],
            weight_decay=group["weight_decay"],
        )
    if isinstance(topt, torch.optim.Adam):
        return optim.Adam(
            params,
            lr=group["lr"],
            betas=tuple(group["betas"]),
            eps=group["eps"],
            weight_decay=group["weight_decay"],
        )
    if isinstance(topt, torch.optim.SGD):
        return optim.SGD(
            params,
            lr=group["lr"],
            momentum=group.get("momentum", 0.0),
            weight_decay=group.get("weight_decay", 0.0),
            nesterov=group.get("nesterov", False),
        )
    raise TypeError(
        f"cannot convert {type(topt).__name__}; supported: AdamW, Adam, SGD "
        "(or build an accelerate_tpu.optim optimizer directly)."
    )
