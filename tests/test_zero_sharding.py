"""ZeRO memory proof: optimizer state and fp32 masters must live on the
``fsdp`` axis after ``prepare()`` (reference FSDP shards optimizer state with
the params, accelerator.py:1555-1679; here it is a GSPMD layout decision).

Round-1 verdict flagged this as asserted-by-docstring-only: ``tx.init`` runs
before ``prepare()`` shards the params, so without an explicit re-layout the
Adam moments stay on the construction-time (replicated) layout and "ZeRO"
saves no optimizer memory.  These tests measure actual per-device bytes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.nn import F, Tensor


@pytest.fixture(autouse=True)
def _fresh():
    nn.manual_seed(0)
    yield
    Accelerator._reset_state()


def _per_device_opt_bytes(opt: optim.Optimizer) -> int:
    """Bytes of optimizer state (moments + fp32 masters) on ONE device."""
    total = 0
    leaves = jax.tree_util.tree_leaves(opt.opt_state)
    leaves += [m for m in opt.master_params if m is not None]
    for leaf in leaves:
        if isinstance(leaf, jax.Array) and leaf.ndim >= 1:
            total += leaf.addressable_shards[0].data.nbytes
    return total


def _build(fsdp_size: int):
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(fsdp_size=fsdp_size),
        mixed_precision="bf16",
    )
    model = nn.Sequential(nn.Linear(256, 256), nn.ReLU(), nn.Linear(256, 256))
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)
    return acc, model, opt


def test_opt_state_bytes_shrink_with_fsdp_size():
    _, _, opt_repl = _build(fsdp_size=1)
    repl_bytes = _per_device_opt_bytes(opt_repl.optimizer)

    _, _, opt_sharded = _build(fsdp_size=8)
    sharded_bytes = _per_device_opt_bytes(opt_sharded.optimizer)

    # every param axis here (256, 256) and bias (256) divides 8 exactly, so
    # per-device optimizer bytes must be total/8 (tiny scalar counts aside)
    assert sharded_bytes <= repl_bytes / 8 + 4096, (
        f"optimizer state not ZeRO-sharded: {sharded_bytes}B per device vs "
        f"{repl_bytes}B replicated (expected ~{repl_bytes // 8}B)"
    )


def test_masters_follow_param_sharding():
    acc, model, opt = _build(fsdp_size=8)
    inner = opt.optimizer
    for p, m in zip(inner.param_list, inner.master_params):
        assert m is not None  # bf16 params ⇒ fp32 masters exist
        assert m.sharding == p.data.sharding, (
            f"master copy sharding {m.sharding} != param {p.data.sharding}"
        )


def test_opt_state_sharded_after_steps():
    acc, model, opt = _build(fsdp_size=8)

    def step_fn(x, y):
        opt.zero_grad()
        pred = model(x)
        loss = F.mse_loss(pred, y)
        acc.backward(loss)
        opt.step()
        return loss

    step = acc.compile_step(step_fn)
    from accelerate_tpu.data_loader import batch_to_global_array

    rng = np.random.default_rng(0)
    x = batch_to_global_array(
        jnp.asarray(rng.normal(size=(8, 256)).astype(np.float32)), mesh=acc.mesh
    )
    y = batch_to_global_array(
        jnp.asarray(rng.normal(size=(8, 256)).astype(np.float32)), mesh=acc.mesh
    )
    before = _per_device_opt_bytes(opt.optimizer)
    step(x, y)
    step(x, y)
    after = _per_device_opt_bytes(opt.optimizer)
    assert after <= before, (
        f"optimizer state grew through the captured step: {before}B -> {after}B "
        "(jit outputs lost the fsdp sharding)"
    )
