"""GPT-2-family causal LM on accelerate_tpu.nn — the throughput flagship.

Decoder-only transformer with pre-norm blocks, learned positions, weight-tied
LM head, causal SDPA routed to the Pallas flash kernel.  Carries the TP plan
(qkv/ffn column-parallel, proj row-parallel) so pjit lays it out on any mesh.
"""

from __future__ import annotations

import dataclasses
import math
import warnings
from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import F, Tensor


def shift_labels_for_lm(labels) -> jnp.ndarray:
    """Next-token targets as a flat (B*S,) id array with the final position
    masked to ignore_index (-100) — shared by the dense and chunked loss
    paths so their masking cannot drift."""
    lab = jnp.asarray(labels.data if isinstance(labels, Tensor) else labels)
    return jnp.concatenate(
        [lab[:, 1:], jnp.full((lab.shape[0], 1), -100, lab.dtype)], axis=1
    ).reshape(-1)


def lm_head_loss(x, head, labels, vocab_size: int):
    """ONE dispatch for every unrolled causal family's loss tail: dense
    head+CE, or the fused chunked path when ``ACCELERATE_TPU_CE_CHUNK`` is
    set (nn.functional.chunked_lm_head_ce — logits never materialize).
    The pipelined trunk computes its loss inside the last pp stage and is
    NOT covered (it warns when the knob is set).

    ``head`` is the family's output ``nn.Linear`` (biased for GPT-J).
    Returns ``(loss, logits_or_None)`` — None under the fused path, which
    is the documented contract for label-bearing calls with the knob on.
    """
    chunk = F.ce_chunk_size()
    if chunk > 0:
        loss = F.chunked_lm_head_ce(
            x, head.weight, shift_labels_for_lm(labels), vocab_size, chunk,
            bias=getattr(head, "bias", None),
        )
        return loss, None
    logits = head(x)
    return lm_shift_loss(logits, labels, vocab_size), logits


def lm_shift_loss(logits, labels, vocab_size: int):
    """Next-token cross entropy without slicing logits to an odd length.

    Keeps the full seq-aligned logits and masks the final position with
    ignore_index (-100) instead of a ``[:, :-1]`` shift: slicing re-tiles the
    (B*S, vocab) tensor (8-sublane padding) — a measured ~4 ms, 786 MB
    physical copy per step on GPT-2-small/v5e, where the masked form is a
    free bitcast.
    """
    return F.cross_entropy(logits.reshape(-1, vocab_size), shift_labels_for_lm(labels))


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304  # padded to a 128 multiple for the MXU
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    # MoE (Switch-style): every `moe_every`-th block swaps its MLP for a
    # MixtureOfExperts over the `ep` mesh axis; 0 experts = dense model
    n_experts: int = 0
    moe_top_k: int = 1
    moe_every: int = 2
    moe_aux_weight: float = 0.01

    @classmethod
    def small(cls) -> "GPTConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "GPTConfig":
        return cls(vocab_size=1024, n_positions=256, n_embd=128, n_layer=2, n_head=4)

    @classmethod
    def tiny_moe(cls) -> "GPTConfig":
        return cls(
            vocab_size=1024, n_positions=256, n_embd=128, n_layer=2, n_head=4,
            n_experts=4, moe_every=2,
        )

    @classmethod
    def medium(cls) -> "GPTConfig":
        return cls(n_embd=1024, n_layer=24, n_head=16)

    @classmethod
    def large(cls) -> "GPTConfig":
        return cls(n_embd=1280, n_layer=36, n_head=20)


def _gpt2_init(model: nn.Module, config: GPTConfig) -> None:
    """GPT-2 init: N(0, 0.02) weights, zero biases, residual-proj scaling."""
    import jax

    from ..nn import random as nn_random

    scale = 0.02
    resid_scale = scale / math.sqrt(2 * config.n_layer)
    from ..nn.meta import is_meta

    for name, p in model.named_parameters():
        if is_meta(p.data):
            continue  # init_empty_weights: nothing to initialise
        if (
            name.endswith(".bias")
            or name.endswith(("b_in", "b_out"))  # MoE bias stacks are 2-D
            or ".ln" in name
            or "ln_" in name
        ):
            if p.ndim == 1 and name.endswith("weight"):
                continue  # LN weight stays ones
            if name.endswith("bias") or name.endswith(("b_in", "b_out")):
                p.data = jnp.zeros_like(p.data)
            continue
        if p.ndim >= 2:
            # MoE w_out plays the same residual-projection role as c_proj
            std = (
                resid_scale
                if ("c_proj" in name or name.endswith("w_out"))
                else scale
            )
            p.data = std * jax.random.normal(
                nn_random.next_key(), p.shape, dtype=p.dtype
            )


class CausalSelfAttention(nn.Module):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.n_head = config.n_head
        self.head_dim = config.n_embd // config.n_head
        self.c_attn = nn.Linear(config.n_embd, 3 * config.n_embd)
        self.c_proj = nn.Linear(config.n_embd, config.n_embd)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x):
        b, s, c = x.shape
        qkv = self.c_attn(x).reshape(b, s, 3, self.n_head, self.head_dim)
        qkv = qkv.transpose(2, 0, 3, 1, 4)  # (3, b, h, s, d)
        q, k, v = qkv[0], qkv[1], qkv[2]
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, c)
        return self.dropout(self.c_proj(out))


class MLP(nn.Module):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.c_fc = nn.Linear(config.n_embd, 4 * config.n_embd)
        self.c_proj = nn.Linear(4 * config.n_embd, config.n_embd)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x):
        return self.dropout(self.c_proj(F.gelu(self.c_fc(x))))


class Block(nn.Module):
    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.n_embd, eps=config.layer_norm_eps)
        self.attn = CausalSelfAttention(config)
        self.ln_2 = nn.LayerNorm(config.n_embd, eps=config.layer_norm_eps)
        # Switch convention: every moe_every-th block routes its FFN through
        # experts (sharded over the `ep` mesh axis); the rest stay dense
        if config.n_experts > 0 and layer_idx % config.moe_every == config.moe_every - 1:
            self.mlp = nn.MixtureOfExperts(
                config.n_embd, 4 * config.n_embd, config.n_experts,
                top_k=config.moe_top_k, dropout=config.dropout,
            )
        else:
            self.mlp = MLP(config)

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        return x + self.mlp(self.ln_2(x))


class GPTLMHeadModel(nn.Module):
    _no_split_modules = ["Block"]  # device_map units must keep residual adds intact
    tp_plan = {
        r".*\.c_attn\.weight": ("tp", None),
        r".*\.c_attn\.bias": ("tp",),
        r".*\.c_fc\.weight": ("tp", None),
        r".*\.c_fc\.bias": ("tp",),
        r".*\.c_proj\.weight": (None, "tp"),
        r"wte\.weight": ("tp", None),
        # MoE expert stacks: leading expert axis over ep (router replicated)
        r".*\.mlp\.w_in": ("ep", None, None),
        r".*\.mlp\.b_in": ("ep", None),
        r".*\.mlp\.w_out": ("ep", None, None),
        r".*\.mlp\.b_out": ("ep", None),
    }

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.n_embd)
        self.wpe = nn.Embedding(config.n_positions, config.n_embd)
        self.drop = nn.Dropout(config.dropout)
        self.h = nn.ModuleList(
            [Block(config, layer_idx=i) for i in range(config.n_layer)]
        )
        self.ln_f = nn.LayerNorm(config.n_embd, eps=config.layer_norm_eps)
        # LM head weight-tied to wte by Parameter-object sharing (reference
        # find_tied_parameters semantics, utils/modeling.py:559); a real
        # module (not an inline matmul) so device_map hooks cover it; built
        # under meta so the discarded weight never allocates or consumes RNG
        from ..nn.meta import meta_init

        with meta_init():
            self.lm_head = nn.Linear(config.n_embd, config.vocab_size, bias=False)
        self.lm_head.weight = self.wte.weight
        _gpt2_init(self, config)

    def forward(self, input_ids, labels=None):
        from ..parallel.sharding import constrain_activation

        ids = jnp.asarray(input_ids.data if isinstance(input_ids, Tensor) else input_ids)
        b, s = ids.shape
        pos = jnp.arange(s)[None, :]
        x = self.drop(self.wte(ids) + self.wpe(pos))
        # pin the activation layout at every layer boundary: batch stays on
        # (dp, fsdp) exactly as the loader placed it, so GSPMD never reshards
        # the residual stream (round-1 dryrun hit involuntary full remats)
        x = constrain_activation(x)
        for block in self.h:
            x = constrain_activation(block(x))
        x = self.ln_f(x)
        if labels is not None:
            loss, logits = lm_head_loss(
                x, self.lm_head, labels, self.config.vocab_size
            )
            if self.config.n_experts > 0:
                for block in self.h:
                    aux = getattr(block.mlp, "last_aux_loss", None)
                    if aux is not None:
                        loss = loss + self.config.moe_aux_weight * aux
            return {"loss": loss, "logits": logits}
        return {"logits": self.lm_head(x)}

    def generate(self, input_ids, max_new_tokens: int, temperature: float = 0.0,
                 rng=None, quantize_weights=None, **kwargs):
        """KV-cache greedy/sampled decode — see models/generation.py."""
        from .generation import generate

        return generate(self, input_ids, max_new_tokens, temperature, rng,
                        quantize_weights=quantize_weights, **kwargs)

    def _decoder_spec(self):
        """Hooks for the generic KV-cache engine (models/generation.py) —
        the math is gpt_attn_in/gpt_attn_out, the same functions the
        pipelined trunk trains with."""
        from .generation import DecoderSpec

        cfg = self.config
        if cfg.n_experts > 0:
            raise NotImplementedError(
                "generate() supports dense GPT trunks; MoE routing does not stack"
            )
        return DecoderSpec(
            family=GPT_DECODER,
            cfg=_GPTDecodeCfg(
                n_head=cfg.n_head,
                n_kv_head=cfg.n_head,
                head_dim=cfg.n_embd // cfg.n_head,
                eps=cfg.layer_norm_eps,
            ),
            max_len=cfg.n_positions,
            stack=self._stack_decoder_params,
        )

    def _stack_decoder_params(self) -> tuple[dict, dict]:
        """(globals, per-layer stacks) raw-array pytrees for cached decode,
        keyed like _StackedBlocks._ORDER so the pure block math reads both."""
        blocks = list(self.h)

        def stk(get):
            return jnp.stack([get(b).data for b in blocks])

        layers = {
            "ln1_w": stk(lambda b: b.ln_1.weight),
            "ln1_b": stk(lambda b: b.ln_1.bias),
            "qkv_w": stk(lambda b: b.attn.c_attn.weight),
            "qkv_b": stk(lambda b: b.attn.c_attn.bias),
            "proj_w": stk(lambda b: b.attn.c_proj.weight),
            "proj_b": stk(lambda b: b.attn.c_proj.bias),
            "ln2_w": stk(lambda b: b.ln_2.weight),
            "ln2_b": stk(lambda b: b.ln_2.bias),
            "fc_w": stk(lambda b: b.mlp.c_fc.weight),
            "fc_b": stk(lambda b: b.mlp.c_fc.bias),
            "fcproj_w": stk(lambda b: b.mlp.c_proj.weight),
            "fcproj_b": stk(lambda b: b.mlp.c_proj.bias),
        }
        g = {
            "wte": self.wte.weight.data,
            "wpe": self.wpe.weight.data,
            "ln_f_w": self.ln_f.weight.data,
            "ln_f_b": self.ln_f.bias.data,
        }
        return g, layers

    @property
    def num_flops_per_token(self) -> float:
        """Approximate training FLOPs/token (6N + attention term)."""
        n = self.num_parameters
        c = self.config
        attn = 12 * c.n_layer * c.n_embd * c.n_positions
        return 6 * n + attn


# ---------------------------------------------------------------------------
# Pure per-layer block math — the SINGLE source of truth shared by the
# pipelined trunk (shard_map training) and KV-cache decode (generation.py).
# Parameter keys follow _StackedBlocks._ORDER; weights are (out, in) like
# nn.Linear, applied as ``x @ w.T``.
# ---------------------------------------------------------------------------

def maybe_remat(fn):
    """Per-layer activation checkpointing (``ACCELERATE_TPU_REMAT=1`` or
    ``FullyShardedDataParallelPlugin(activation_checkpointing=True)`` /
    ``FSDP_ACTIVATION_CHECKPOINTING`` from the launcher protocol).

    Wraps a pure block function in ``jax.checkpoint``: the backward
    recomputes the layer forward instead of keeping its activations alive —
    ~33% more FLOPs for an O(layers) → O(1) activation footprint per layer,
    which buys a larger per-chip batch (usually a net MFU win on HBM-bound
    workloads; sweep with bench.py).  Used by every pure-fn decoder family
    (Llama/OPT/GPT-J/NeoX); numerics are exactly unchanged (tested).

    The knobs are read at TRACE time: captured steps bake the value at
    first compile, eager steps read it per layer call (a cheap dict get).
    """
    import os

    if os.environ.get("ACCELERATE_TPU_REMAT", "0").lower() in ("1", "true", "yes"):
        return jax.checkpoint(fn)
    from ..state import AcceleratorState

    # read the Borg dict directly: constructing AcceleratorState() here
    # could silently re-run a full default init if a prior Accelerator
    # construction failed partway, and this runs per layer call
    plugin = AcceleratorState._shared_state.get("fsdp_plugin")
    if plugin is not None and getattr(plugin, "activation_checkpointing", False):
        return jax.checkpoint(fn)
    return fn


def _pure_layernorm(x, w, b, eps):
    # fp32 statistics regardless of activation dtype (bf16-safe), output
    # cast back so the residual stream keeps its dtype
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return (((x32 - mu) * jax.lax.rsqrt(var + eps)) * w + b).astype(x.dtype)


def gpt_attn_in(p, x, *, n_head: int, eps: float):
    """LN1 + fused qkv projection, heads split: (b,s,c) → 3×(b,h,s,d)."""
    b, s, c = x.shape
    hd = c // n_head
    h = _pure_layernorm(x, p["ln1_w"], p["ln1_b"], eps)
    qkv = h @ p["qkv_w"].T + p["qkv_b"]
    qkv = qkv.reshape(b, s, 3, n_head, hd).transpose(2, 0, 3, 1, 4)
    return qkv[0], qkv[1], qkv[2]


def gpt_attn_out(p, x, att, *, eps: float):
    """Output projection + residual, then LN2 + gelu-MLP + residual.

    ``att`` arrives in (b, h, s, d) head layout straight from whichever
    attention engine ran (flash, ring, ulysses, or cached decode).
    """
    b, s, c = x.shape
    att = att.transpose(0, 2, 1, 3).reshape(b, s, c)
    h = x + att @ p["proj_w"].T + p["proj_b"]
    h2 = _pure_layernorm(h, p["ln2_w"], p["ln2_b"], eps)
    ff = jax.nn.gelu(h2 @ p["fc_w"].T + p["fc_b"], approximate=True)
    return h + ff @ p["fcproj_w"].T + p["fcproj_b"]


@dataclasses.dataclass(frozen=True)
class _GPTDecodeCfg:
    n_head: int
    n_kv_head: int
    head_dim: int
    eps: float


def _dec_embed(g, ids, positions, cfg):
    return g["wte"][ids] + g["wpe"][positions][None]


def _dec_attn_in(l, x, positions, cfg):
    return gpt_attn_in(l, x, n_head=cfg.n_head, eps=cfg.eps)


def _dec_attn_out(l, x, att, cfg):
    return gpt_attn_out(l, x, att, eps=cfg.eps)


def _dec_finalize(g, x, cfg):
    x = _pure_layernorm(x[:, -1], g["ln_f_w"], g["ln_f_b"], cfg.eps)
    return x @ g["wte"].T  # weight-tied head


def _make_gpt_decoder():
    from .generation import DecoderFamily

    return DecoderFamily(
        embed=_dec_embed,
        attn_in=_dec_attn_in,
        attn_out=_dec_attn_out,
        finalize=_dec_finalize,
    )


GPT_DECODER = _make_gpt_decoder()


def _pure_lm_head_loss(h, labels, extra, *, eps: float):
    """Final LN + (tied) head + shifted causal CE as pure jnp — the loss a
    1F1B pipeline computes INSIDE its last stage per microbatch.

    Returns ``(nll_sum, valid_count)`` — UN-normalised, so the pipeline can
    divide by the GLOBAL valid-token count after accumulating over
    microbatches and shards.  A per-microbatch mean would over-weight
    microbatches with more -100 padding; sum-and-count reproduces
    F.cross_entropy's global token mean exactly (the gpipe path's
    semantics).  -100 labels (HF padding convention) drop out of numerator
    AND denominator; gather on a clipped index so -100 never wraps into the
    vocab.  fp32 logsumexp.
    """
    ln_w, ln_b, head_w = extra
    h = _pure_layernorm(h, ln_w, ln_b, eps)
    logits = (h @ head_w.T).astype(jnp.float32)  # (b, s, V)
    lse = jax.nn.logsumexp(logits, axis=-1)  # (b, s)
    b, s = labels.shape
    shifted = jnp.concatenate(
        [labels[:, 1:], jnp.zeros((b, 1), labels.dtype)], axis=1
    )
    valid = shifted >= 0
    safe = jnp.where(valid, shifted, 0)
    picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    nll = lse - picked
    mask = valid.astype(jnp.float32) * jnp.concatenate(
        [jnp.ones((b, s - 1), jnp.float32), jnp.zeros((b, 1), jnp.float32)], axis=1
    )
    return jnp.sum(nll * mask), jnp.sum(mask)


def _pipelined_block(p, h, *, n_head: int, eps: float, seq_axis: str, sp_mode: str = "ring"):
    """One pre-norm GPT block as pure jnp, runnable inside shard_map.

    Attention goes through the selected sequence-parallel per-device body
    over ``seq_axis`` (``SequenceParallelPlugin.mode``: "ring" streams k/v
    chunks via ppermute, "all_to_all" re-partitions heads Ulysses-style) —
    with sp=1 the ring has one hop and reduces to plain causal SDPA, so
    pp-only and pp×sp use the same code.
    """
    from ..ops.ring_attention import _ring_attention_local, _ulysses_attention_local

    local_attn = (
        _ulysses_attention_local if sp_mode == "all_to_all" else _ring_attention_local
    )
    hd = h.shape[-1] // n_head
    q, k, v = gpt_attn_in(p, h, n_head=n_head, eps=eps)
    att = local_attn(q, k, v, axis_name=seq_axis, is_causal=True, scale=hd**-0.5)
    return gpt_attn_out(p, h, att, eps=eps)


class _StackedBlocks(nn.Module):
    """Per-layer GPT block weights stacked on a leading layer axis.

    The layer axis is sharded over ``pp`` (see tp_plan on the parent): each
    pipeline stage holds a contiguous span of layers, the TPU-native reading
    of the reference's PiPPy split-at-layer-boundaries
    (reference inference.py:124).
    """

    def __init__(self, config: GPTConfig):
        super().__init__()
        import jax as _jax

        from ..nn import random as nn_random

        L, E = config.n_layer, config.n_embd
        scale = 0.02
        resid = scale / math.sqrt(2 * L)

        def norm(shape, std):
            return nn.Parameter(
                std * _jax.random.normal(nn_random.next_key(), shape, jnp.float32)
            )

        self.ln1_w = nn.Parameter(jnp.ones((L, E)))
        self.ln1_b = nn.Parameter(jnp.zeros((L, E)))
        self.qkv_w = norm((L, 3 * E, E), scale)
        self.qkv_b = nn.Parameter(jnp.zeros((L, 3 * E)))
        self.proj_w = norm((L, E, E), resid)
        self.proj_b = nn.Parameter(jnp.zeros((L, E)))
        self.ln2_w = nn.Parameter(jnp.ones((L, E)))
        self.ln2_b = nn.Parameter(jnp.zeros((L, E)))
        self.fc_w = norm((L, 4 * E, E), scale)
        self.fc_b = nn.Parameter(jnp.zeros((L, 4 * E)))
        self.fcproj_w = norm((L, E, 4 * E), resid)
        self.fcproj_b = nn.Parameter(jnp.zeros((L, E)))

    _ORDER = (
        "ln1_w", "ln1_b", "qkv_w", "qkv_b", "proj_w", "proj_b",
        "ln2_w", "ln2_b", "fc_w", "fc_b", "fcproj_w", "fcproj_b",
    )

    def param_tensors(self):
        return [getattr(self, n) for n in self._ORDER]


class PipelinedGPTLMHeadModel(nn.Module):
    """GPT-2 whose trunk runs as a GPipe pipeline over the ``pp`` mesh axis
    with ring attention over ``sp`` — pp × sp × dp/fsdp in ONE shard_map.

    Embeddings and the (tied) head stay outside the pipeline (GPipe classic:
    every pipelined layer must be shape-preserving).  TP inside the pipeline
    body is intentionally out of scope — on-slice, GSPMD tp on the unrolled
    ``GPTLMHeadModel`` is the faster layout; pp/sp earn their keep across
    slices and long sequences (SURVEY.md §2.2 rows PP/SP).
    """

    tp_plan = {
        r"blocks\..*": ("pp",),  # leading layer axis → pipeline stages
        r"wte\.weight": ("tp", None),
    }

    def __init__(self, config: GPTConfig, num_microbatches: int = 2):
        super().__init__()
        self.config = config
        self.num_microbatches = num_microbatches
        self.wte = nn.Embedding(config.vocab_size, config.n_embd)
        self.wpe = nn.Embedding(config.n_positions, config.n_embd)
        self.blocks = _StackedBlocks(config)
        self.ln_f = nn.LayerNorm(config.n_embd, eps=config.layer_norm_eps)
        from ..nn.meta import is_meta, meta_init

        with meta_init():
            self.lm_head = nn.Linear(config.n_embd, config.vocab_size, bias=False)
        self.lm_head.weight = self.wte.weight
        # GPT-2 embedding init (the stacked blocks init themselves)
        for emb in (self.wte, self.wpe):
            if not is_meta(emb.weight.data):
                emb.weight.data = emb.weight.data * 0.02

    def forward(self, input_ids, labels=None):
        from ..parallel.pipeline import gpipe
        from ..parallel.plan import current_plan
        from ..parallel.sharding import constrain_activation
        from ..state import AcceleratorState

        mesh = AcceleratorState().mesh if AcceleratorState._shared_state else None
        # the resolved ParallelPlan owns schedule / stage layout / sp mode
        # (docs/parallel_plan.md) — this model never pokes plugins or the
        # mesh dict for axis sizes (graftlint stage-boundary-vs-plan)
        plan = current_plan()

        ids = jnp.asarray(input_ids.data if isinstance(input_ids, Tensor) else input_ids)
        b, s = ids.shape
        pos = jnp.arange(s)[None, :]
        x = self.wte(ids) + self.wpe(pos)
        x = constrain_activation(x)

        cfg = self.config
        names = _StackedBlocks._ORDER
        # the plan's sp mode selects the attention engine; the ulysses body
        # needs heads divisible across the sp axis, else ring
        sp_mode = "ring"
        sp_size = plan.sp if plan is not None else 1
        if plan is not None and plan.sp_mode == "all_to_all" and sp_size > 1:
            if cfg.n_head % sp_size == 0:
                sp_mode = "all_to_all"
            else:
                # captured steps keep whatever mode the first trace chose, so
                # a silent fallback would be invisible for the whole run
                warnings.warn(
                    f"SequenceParallelPlugin(mode='all_to_all') ignored: "
                    f"n_head={cfg.n_head} is not divisible by the sp axis "
                    f"size {sp_size}; falling back to ring "
                    "attention for this (and, under capture, every) step.",
                    stacklevel=2,
                )

        def stage_fn(layer_params, h):
            return _pipelined_block(
                layer_params, h,
                n_head=cfg.n_head, eps=cfg.layer_norm_eps, seq_axis="sp",
                sp_mode=sp_mode,
            )

        # -- fused/interleaved 1F1B training path (plan.stage.schedule) ------
        stage = plan.stage if plan is not None else None
        schedule = stage.schedule if stage is not None else "gpipe"
        pp_size = plan.pp if plan is not None else 1
        # Layer layout of record (docs/parallel_plan.md §layout contract):
        # the prepare-time commit stamps the stacked params, so the RUNTIME
        # source of truth is the marker, not the plan alone — an unprepared
        # model (plain stack) under a committed plan still runs correctly
        # through the in-program-gather fallback.
        committed = bool(
            getattr(self.blocks.qkv_w, "_layer_layout_committed", False)
        )
        trunk_virtual = stage.virtual if stage is not None else 1
        if labels is not None and schedule in ("1f1b", "interleaved") and pp_size > 1:
            if sp_size > 1:
                raise NotImplementedError(
                    f"schedule={schedule!r} computes the loss inside the "
                    "pipeline and does not yet compose with sequence "
                    "parallelism (the shifted CE crosses seq-chunk "
                    "boundaries); use schedule='gpipe' with sp>1"
                )
            from ..parallel.pipeline import pipeline_loss_1f1b

            lbl = jnp.asarray(labels.data if isinstance(labels, Tensor) else labels)
            n_names = len(names)
            virtual = stage.virtual

            def fused(xv, *flat):
                stacked = dict(zip(names, flat[:n_names]))
                extra = tuple(flat[n_names:])  # (ln_f w, ln_f b, head w)

                def loss_fn(out, lbl_mb, ep):
                    return _pure_lm_head_loss(out, lbl_mb, ep, eps=cfg.layer_norm_eps)

                f = pipeline_loss_1f1b(
                    stage_fn, loss_fn, lbl, self.num_microbatches, mesh=mesh,
                    virtual=virtual,
                    layout="committed" if committed else None,
                )
                return f(stacked, xv, extra)

            loss = nn.tape_op(
                fused, x, *self.blocks.param_tensors(),
                self.ln_f.weight, self.ln_f.bias, self.lm_head.weight,
            )
            # logits never materialise in the fused schedule — that is the
            # memory point; callers needing logits use schedule='gpipe'
            return {"loss": loss, "logits": None}

        def trunk(xv, *flat_params):
            stacked = dict(zip(names, flat_params))
            if committed:
                # cold/inference path only: view the committed stack in
                # plain model order for the sequential gpipe trunk (the
                # captured 1F1B training step above never runs this)
                from ..parallel.pipeline import uncommit_layer_layout

                stacked = uncommit_layer_layout(stacked, trunk_virtual, mesh=mesh)
            return gpipe(
                stage_fn,
                stacked,
                xv,
                num_microbatches=self.num_microbatches,
                mesh=mesh,
                seq_axis="sp",
            )

        x = nn.tape_op(trunk, x, *self.blocks.param_tensors())
        x = self.ln_f(x)
        logits = self.lm_head(x)
        if labels is not None:
            if F.ce_chunk_size() > 0 and not getattr(self, "_ce_chunk_warned", False):
                self._ce_chunk_warned = True
                warnings.warn(
                    "ACCELERATE_TPU_CE_CHUNK has no effect on "
                    "PipelinedGPTLMHeadModel: the pipelined loss runs inside "
                    "the last pp stage (1F1B computes it per microbatch) and "
                    "materializes dense logits; the fused chunked head+CE "
                    "covers the unrolled families only.",
                    stacklevel=2,
                )
            loss = lm_shift_loss(logits, labels, cfg.vocab_size)
            return {"loss": loss, "logits": logits}
        return {"logits": logits}
