from .constants import (
    ALL_MESH_AXES,
    CUSTOM_STATES_NAME,
    MODEL_NAME,
    OPTIMIZER_NAME,
    RNG_STATE_NAME,
    SAMPLER_NAME,
    SCHEDULER_NAME,
    TPU_PAD_MULTIPLE,
    WEIGHTS_NAME,
)
from .dataclasses import (
    AutocastKwargs,
    BaseEnum,
    ComputeBackend,
    DataLoaderConfiguration,
    DistributedDataParallelKwargs,
    DistributedType,
    ExpertParallelPlugin,
    FP8RecipeKwargs,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    GradScalerKwargs,
    InitProcessGroupKwargs,
    KwargsHandler,
    LoggerType,
    ParallelismConfig,
    PipelineParallelPlugin,
    PrecisionType,
    ProfileKwargs,
    ProjectConfiguration,
    RNGType,
    SaveFormat,
    SequenceParallelPlugin,
    TensorParallelPlugin,
)
from .fp8 import FP8Linear, convert_to_float8_training
from .quantization import (
    QuantizationConfig,
    QuantizedLinear,
    load_and_quantize_model,
    replace_with_quantized_layers,
)
from .fsdp_utils import (
    load_sharded_model_state,
    merge_sharded_weights,
    save_sharded_model_state,
)
from .environment import (
    are_libraries_initialized,
    get_int_from_env,
    parse_choice_from_env,
    parse_flag_from_env,
    patch_environment,
    str_to_bool,
)
from .imports import (
    is_aim_available,
    is_clearml_available,
    is_comet_ml_available,
    is_datasets_available,
    is_dvclive_available,
    is_flax_available,
    is_jax_available,
    is_mlflow_available,
    is_optax_available,
    is_orbax_available,
    is_pallas_available,
    is_rich_available,
    is_safetensors_available,
    is_tensorboard_available,
    is_torch_available,
    is_tpu_available,
    is_tqdm_available,
    is_transformers_available,
    is_wandb_available,
)
from .memory import (
    clear_device_cache,
    find_executable_batch_size,
    get_device_memory_stats,
    release_memory,
    should_reduce_batch_size,
)
from .other import (
    clean_state_dict_for_safetensors,
    convert_bytes,
    extract_model_from_parallel,
    load,
    save,
    wait_for_everyone,
)
from .random import set_seed, synchronize_rng_state, synchronize_rng_states
from .tqdm import tqdm
from .versions import compare_versions, is_jax_version
