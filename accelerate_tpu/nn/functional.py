"""Functional ops (``F.*``) over tape Tensors.

Every op is a thin ``tape_op`` around a pure jnp/lax function, so gradients
come from ``jax.vjp`` and the whole thing fuses under jit.  Attention routes
to the Pallas flash kernel on TPU when shapes allow (ops/flash_attention.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from . import random as nn_random
from .amp import region_cast
from .tape import Tensor, tape_op, _unwrap, is_grad_enabled


# -- activations ------------------------------------------------------------
def relu(x):
    return tape_op(jax.nn.relu, x)


def gelu(x, approximate: bool = True):
    return tape_op(lambda v: jax.nn.gelu(v, approximate=approximate), x)


def silu(x):
    return tape_op(jax.nn.silu, x)


def sigmoid(x):
    return tape_op(jax.nn.sigmoid, x)


def tanh(x):
    return tape_op(jnp.tanh, x)


def softmax(x, axis: int = -1):
    return tape_op(lambda v: jax.nn.softmax(region_cast(v), axis=axis), x)


def log_softmax(x, axis: int = -1):
    return tape_op(lambda v: jax.nn.log_softmax(region_cast(v), axis=axis), x)


# -- linear algebra ---------------------------------------------------------
def linear(x, weight, bias=None):
    """x @ W^T + b with torch weight layout (out, in).

    Honors an open ``autocast_region`` (nn/amp.py): inputs and params are
    cast to the region dtype before the matmul.
    """
    def _mm(v, w):
        v, w = region_cast(v, w)
        return v @ w.T

    def _mm_bias(v, w, b):
        v, w, b = region_cast(v, w, b)
        return v @ w.T + b

    if bias is None:
        return tape_op(_mm, x, weight)
    return tape_op(_mm_bias, x, weight, bias)


def embedding(ids, weight):
    ids = _unwrap(ids) if isinstance(ids, Tensor) else jnp.asarray(ids)
    return tape_op(lambda w: jnp.take(w, ids, axis=0), weight)


def one_hot(ids, num_classes: int):
    ids = _unwrap(ids)
    return Tensor(jax.nn.one_hot(ids, num_classes))


# -- normalization ----------------------------------------------------------
def layer_norm(x, normalized_shape, weight=None, bias=None, eps: float = 1e-5):
    def _ln(v, *wb):
        casted = region_cast(v, *wb)
        if wb:
            v, wb = casted[0], casted[1:]
        else:
            v = casted
        mean = v.mean(axis=-1, keepdims=True)
        var = ((v - mean) ** 2).mean(axis=-1, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + eps)
        if len(wb) >= 1:
            out = out * wb[0]
        if len(wb) == 2:
            out = out + wb[1]
        return out

    args = [a for a in (weight, bias) if a is not None]
    return tape_op(_ln, x, *args)


def rms_norm(x, weight=None, eps: float = 1e-6):
    def _rms(v, *w):
        # normalise in fp32 for stability, cast back (standard TPU practice)
        dtype = v.dtype
        v32 = v.astype(jnp.float32)
        out = v32 * jax.lax.rsqrt((v32**2).mean(axis=-1, keepdims=True) + eps)
        out = out.astype(dtype)
        if w:
            out = out * w[0]
        return out

    args = [weight] if weight is not None else []
    return tape_op(_rms, x, *args)


# -- losses -----------------------------------------------------------------
def _fused_ce(labels, ignore_index):
    """Mean NLL over logits with a hand-written VJP — no stored log-probs.

    ``log_softmax`` materializes a full (N, C) log-prob tensor as the
    backward residual; for an LM head that is another logits-sized HBM
    tensor (786 MB on GPT-2-small at 8×1024) read and written once each
    way — measured ~7.3 ms/step of pure bandwidth on v5e.  Here the
    forward keeps only the per-row logsumexp (O(N)) and the backward
    recomputes ``softmax = exp(logits - lse)`` from the logits XLA already
    holds as the lm_head matmul residual.  Reductions run in fp32.
    """

    @jax.custom_vjp
    def fused(lg):
        return _fwd(lg)[0]

    def _nll_parts(lg):
        lg32 = lg.astype(jnp.float32)
        lse = jax.nn.logsumexp(lg32, axis=-1, keepdims=True)  # (N, 1)
        if ignore_index is not None:
            mask = labels != ignore_index
            safe = jnp.where(mask, labels, 0)
        else:
            mask = jnp.ones(labels.shape, bool)
            safe = labels
        label_logit = jnp.take_along_axis(lg32, safe[..., None], axis=-1)
        nll = (lse - label_logit)[..., 0]
        denom = jnp.maximum(mask.sum().astype(jnp.float32), 1.0)
        return nll, mask, safe, lse, denom

    def _fwd(lg):
        nll, mask, safe, lse, denom = _nll_parts(lg)
        loss = jnp.where(mask, nll, 0.0).sum() / denom
        return loss, (lg, lse, denom)

    def _bwd(res, g):
        lg, lse, denom = res
        if ignore_index is not None:
            mask = labels != ignore_index
            safe = jnp.where(mask, labels, 0)
        else:
            mask = jnp.ones(labels.shape, bool)
            safe = labels
        p = jnp.exp(lg.astype(jnp.float32) - lse)
        classes = jax.lax.broadcasted_iota(jnp.int32, lg.shape, lg.ndim - 1)
        grad = p - (classes == safe[..., None].astype(jnp.int32))
        grad = jnp.where(mask[..., None], grad, 0.0) * (g / denom)
        return (grad.astype(lg.dtype),)

    fused.defvjp(_fwd, _bwd)
    return fused


def cross_entropy(logits, labels, ignore_index: Optional[int] = -100, label_smoothing: float = 0.0):
    """Mean token-level cross entropy; labels are int ids.

    Matches torch.nn.functional.cross_entropy semantics for (N, C) logits /
    (N,) labels and the flattened LM case, including ``ignore_index`` masking.
    The unsmoothed path runs through a fused logsumexp custom-VJP (see
    ``_fused_ce``); smoothing falls back to explicit log-probs.
    """
    labels = _unwrap(labels) if isinstance(labels, Tensor) else jnp.asarray(labels)

    if label_smoothing == 0.0:
        def _ce(lg):
            return _fused_ce(labels, ignore_index)(region_cast(lg))

        return tape_op(_ce, logits)

    def _ce(lg):
        lg = region_cast(lg)
        logp = jax.nn.log_softmax(lg, axis=-1)
        safe_labels = jnp.where(labels == ignore_index, 0, labels) if ignore_index is not None else labels
        nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
        smooth = -logp.mean(axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
        if ignore_index is not None:
            mask = (labels != ignore_index).astype(nll.dtype)
            return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        return nll.mean()

    return tape_op(_ce, logits)


def nll_loss(log_probs, labels):
    labels = _unwrap(labels) if isinstance(labels, Tensor) else jnp.asarray(labels)

    def _nll(lp):
        lp = region_cast(lp)
        return -jnp.take_along_axis(lp, labels[..., None], axis=-1)[..., 0].mean()

    return tape_op(_nll, log_probs)


def mse_loss(pred, target):
    def _mse(p, t):
        p, t = region_cast(p, t)
        return ((p - t) ** 2).mean()

    return tape_op(_mse, pred, target)


def binary_cross_entropy_with_logits(logits, targets):
    def _bce(lg, t):
        lg, t = region_cast(lg, t)
        return jnp.mean(jnp.maximum(lg, 0) - lg * t + jnp.log1p(jnp.exp(-jnp.abs(lg))))

    return tape_op(_bce, logits, targets)


# -- dropout ----------------------------------------------------------------
def dropout(x, p: float = 0.5, training: bool = True):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = nn_random.next_key()

    def _drop(v):
        keep = jax.random.bernoulli(key, 1.0 - p, shape=v.shape)
        return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)

    return tape_op(_drop, x)


# -- attention --------------------------------------------------------------
def scaled_dot_product_attention(
    q, k, v, attn_mask=None, is_causal: bool = False, scale: Optional[float] = None,
    dropout_p: float = 0.0,
):
    """SDPA with (batch, heads, seq, head_dim) layout (torch parity).

    Routes to the Pallas flash-attention kernel on TPU for supported shapes;
    falls back to the XLA-fused reference implementation elsewhere (CPU tests,
    tiny shapes, exotic masks).
    """
    mask_arr = _unwrap(attn_mask) if attn_mask is not None else None

    def _sdpa(q_, k_, v_):
        from ..ops.attention import sdpa_tpu

        q_, k_, v_ = region_cast(q_, k_, v_)
        return sdpa_tpu(q_, k_, v_, mask=mask_arr, is_causal=is_causal, scale=scale)

    out = tape_op(_sdpa, q, k, v)
    if dropout_p > 0.0:
        out = dropout(out, dropout_p)
    return out


# -- misc -------------------------------------------------------------------
def pad(x, pad_width, value=0.0):
    return tape_op(lambda v: jnp.pad(v, pad_width, constant_values=value), x)


def cat(tensors, dim: int = 0):
    return tape_op(lambda *ts: jnp.concatenate(ts, axis=dim), *tensors)


def stack(tensors, dim: int = 0):
    return tape_op(lambda *ts: jnp.stack(ts, axis=dim), *tensors)


def where(cond, a, b):
    cond = _unwrap(cond)
    return tape_op(lambda x, y: jnp.where(cond, x, y), a, b)
