"""Serialization + model-unwrap helpers.

Counterpart of ``/root/reference/src/accelerate/utils/other.py`` (352 LoC):
``save``/``load`` with safetensors-or-pickle (other.py:62-170),
``clean_state_dict_for_safetensors``, ``extract_model_from_parallel``
(other.py:62-266), ``wait_for_everyone``, ``write_basic_config`` lives in
commands/config.

TPU-native notes: there are no DDP/FSDP wrapper modules to peel off —
"parallel" is a sharding property of arrays, not a wrapper class — so
``extract_model_from_parallel`` only unwraps the fp32-output forward wrapper
and step-capture binding, mirroring the reference's `keep_fp32_wrapper`
handling (other.py:77-107).
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import jax
import numpy as np


def is_main_process_gate() -> bool:
    from ..state import PartialState

    return PartialState().is_main_process


def clean_state_dict_for_safetensors(state_dict: dict) -> dict:
    """Deduplicate aliased tensors and force contiguous numpy buffers —
    safetensors refuses shared/non-contiguous storage (reference
    other.py:137-154)."""
    seen: dict[int, str] = {}
    out: dict[str, np.ndarray] = {}
    for k, v in state_dict.items():
        arr = np.ascontiguousarray(np.asarray(jax.device_get(v)))
        ident = id(v)
        if ident in seen:
            arr = arr.copy()
        seen[ident] = k
        out[k] = arr
    return out


def save(obj: Any, f, save_on_each_node: bool = False, safe_serialization: bool = True) -> None:
    """Save ``obj`` to file ``f`` — safetensors for flat tensor dicts,
    pickle otherwise (reference other.py:62: `accelerator.save`). Gated to
    the main process unless ``save_on_each_node``."""
    if not save_on_each_node and not is_main_process_gate():
        return
    f = os.fspath(f)
    os.makedirs(os.path.dirname(f) or ".", exist_ok=True)
    tensor_dict = (
        isinstance(obj, dict)
        and len(obj) > 0
        and all(isinstance(v, (jax.Array, np.ndarray)) for v in obj.values())
    )
    if safe_serialization and tensor_dict:
        from ..native.st import pick_save_file

        pick_save_file()(clean_state_dict_for_safetensors(obj), f)
    else:
        obj = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x,
            obj,
        )
        with open(f, "wb") as fh:
            pickle.dump(obj, fh)


def load(f, map_location=None) -> Any:
    """Load a file written by :func:`save` (reference other.py:155)."""
    f = os.fspath(f)
    if f.endswith(".safetensors"):
        from ..native.st import pick_load_file

        return pick_load_file()(f)
    with open(f, "rb") as fh:
        head = fh.read(9)
    # safetensors layout: u64 LE header length, then the JSON header ("{...")
    if len(head) == 9 and head[8:9] == b"{":
        from ..native.st import pick_load_file

        try:
            return pick_load_file()(f)
        except Exception:
            pass
    with open(f, "rb") as fh:
        return pickle.load(fh)


def extract_model_from_parallel(model, keep_fp32_wrapper: bool = True, recursive: bool = False):
    """Return the underlying user model (reference other.py:62-107).

    On TPU parallelism never wraps the module — sharding lives on the
    arrays — so only the autocast fp32-output forward patch is removable.
    """
    if not keep_fp32_wrapper:
        forward = getattr(model, "__wrapped_forward__", None)
        if forward is not None:
            model.forward = forward
            try:
                delattr(model, "__wrapped_forward__")
            except AttributeError:
                pass
    return model


def wait_for_everyone() -> None:
    """Module-level barrier (reference other.py:58)."""
    from ..state import PartialState

    PartialState().wait_for_everyone()


def convert_bytes(size: float) -> str:
    """Human-readable byte size, e.g. 1253656678 → '1.17 GB'
    (reference utils/modeling.py:42)."""
    for unit in ["B", "KB", "MB", "GB", "TB", "PB"]:
        if abs(size) < 1024.0:
            return f"{size:.2f} {unit}"
        size /= 1024.0
    return f"{size:.2f} EB"


def check_os_kernel() -> None:
    """Warn on Linux kernels with known multiprocessing perf bugs
    (reference other.py:299 warns on <5.5)."""
    import platform
    import warnings

    if platform.system() != "Linux":
        return
    try:
        release = platform.release().split("-")[0]
        parts = release.split(".")
        version = (int(parts[0]), int(parts[1]))
    except (ValueError, IndexError):
        return
    if version < (5, 5):
        warnings.warn(
            f"Detected kernel version {release}, which is below the recommended "
            "minimum of 5.5.0; this can cause the process to hang.",
            stacklevel=2,
        )


def recursive_getattr(obj, attr: str):
    """`recursive_getattr(model, "h.0.attn")` (reference other.py:339)."""
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def get_pretty_name(obj) -> str:
    """Readable name for checkpoint registration logs (reference other.py:268)."""
    if not hasattr(obj, "__qualname__") and not hasattr(obj, "__name__"):
        obj = getattr(obj, "__class__", obj)
    if hasattr(obj, "__qualname__"):
        return obj.__qualname__
    if hasattr(obj, "__name__"):
        return obj.__name__
    return str(obj)


def merge_dicts(source: dict, destination: dict) -> dict:
    """Recursively merge ``source`` into ``destination`` (reference other.py:281)."""
    for key, value in source.items():
        if isinstance(value, dict):
            node = destination.setdefault(key, {})
            merge_dicts(value, node)
        else:
            destination[key] = value
    return destination
