#!/usr/bin/env python
"""pipeline_smoke — `make pipeline-smoke`: prove the resolved ParallelPlan
and the interleaved 1F1B pipeline end-to-end on CPU in seconds
(docs/parallel_plan.md, ISSUE 15 acceptance).

2-stage × dp=2 on the virtual 4-device mesh, interleaved schedule (V=2),
ZeRO-1 + int8 compression + gradient accumulation in ONE captured step.
Exit 0 requires:

* the plan resolves the acceptance geometry (pp=2, dp=2, zero1 armed,
  int8 compression, schedule=interleaved, V=2) and IS what consumers see
  (``current_plan()``);
* the composed run trains within 1e-3 loss parity of the dp-only run on
  the same data/seed, and both replay with zero steady-state recompiles
  (no builds after the two accumulation variants);
* the interleaved schedule's analytic bubble profile is strictly better
  than the fused one (bubble_ticks and bubble_fraction at V=2);
* interleaved-vs-fused training parity holds (same trajectory);
* (ISSUE 17) the committed layout's lowering contains NO stacked-layer
  gather while the legacy gather layout's does
  (``native.kernels.inspect.check_pipeline_layout``), and the per-stage
  captured programs round-trip the AOT store across two FRESH
  subprocesses: the warm leg loads every ``(stage, chunk, role)``
  program off disk with ZERO trace/compile at a bitwise-equal loss.
"""

import json
import os
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _train(pp: int, schedule: str = "interleaved", micro_steps: int = 6):
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import Accelerator, CompressionKwargs, ParallelismConfig, TelemetryKwargs
    from accelerate_tpu.data_loader import batch_to_global_array
    from accelerate_tpu.models import GPTConfig, PipelinedGPTLMHeadModel
    from accelerate_tpu.utils.dataclasses import PipelineParallelPlugin

    Accelerator._reset_state()
    nn.manual_seed(0)
    kwargs = dict(
        mixed_precision="no",
        gradient_accumulation_steps=2,
        kwargs_handlers=[
            TelemetryKwargs(enabled=True),
            CompressionKwargs(policy="int8"),
        ],
    )
    if pp > 1:
        acc = Accelerator(
            parallelism_config=ParallelismConfig(pp_size=pp),
            pp_plugin=PipelineParallelPlugin(
                pp_size=pp, num_microbatches=8, schedule=schedule
            ),
            **kwargs,
        )
    else:
        acc = Accelerator(**kwargs)
    cfg = dataclasses.replace(GPTConfig.tiny(), n_layer=4)
    model = PipelinedGPTLMHeadModel(cfg, num_microbatches=8)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        with acc.accumulate(model):
            opt.zero_grad()
            out = model(ids, labels=ids)
            acc.backward(out["loss"])
            opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(micro_steps):
        ids = batch_to_global_array(
            jnp.asarray(rng.integers(0, 1024, (64, 32)), jnp.int32),
            mesh=acc.mesh,
        )
        losses.append(float(step(ids)))
    return acc, step, losses


def _stagewise_leg(cache_dir: str, out_path: str) -> None:
    """One stagewise process against the AOT store — runs in a FRESH
    subprocess both cold (compile + store every per-stage program) and
    warm (load every program off disk; XLA:CPU only serializes reliably
    from a process that hasn't accumulated unrelated JIT state, which is
    exactly the restart shape this leg proves anyway)."""
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.native.aot_cache import AOTCompilationCache
    from accelerate_tpu.parallel.pipeline import apply_layer_order
    from accelerate_tpu.parallel.plan import StagePlan
    from accelerate_tpu.parallel.stagewise import (
        StagewisePrograms,
        stagewise_train_1f1b,
    )
    from accelerate_tpu.utils.dataclasses import CompilationCacheKwargs

    S, V, L, M, dim = 2, 2, 4, 4, 8
    stage = StagePlan(num_stages=S, virtual=V, num_microbatches=M,
                      schedule="interleaved")
    plan_desc = {"schedule": "interleaved", "virtual": V, "microbatches": M,
                 "layer_layout": stage.layout}
    ks = jax.random.split(jax.random.key(0), L)
    plain = {
        "w": jnp.stack([jax.random.normal(k, (dim, dim)) * 0.5 for k in ks]),
        "b": jnp.zeros((L, dim)),
    }
    committed = apply_layer_order(plain, stage.layer_order(L))
    x = jax.random.normal(jax.random.key(1), (M, dim))
    labels = jax.random.normal(jax.random.key(2), (M, dim))
    extra = {"head": jnp.eye(dim) + 0.1}

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    def loss_fn(out, lbl, e):
        err = (out @ e["head"] - lbl) ** 2
        return err.sum(), jnp.float32(err.size)

    cache = AOTCompilationCache(CompilationCacheKwargs(cache_dir=cache_dir))
    cache.set_context(plan=plan_desc)
    programs = StagewisePrograms(
        stage_fn, loss_fn, num_stages=S, virtual=V,
        cache=cache, plan_desc=plan_desc,
    )
    loss, *_ = stagewise_train_1f1b(
        stage_fn, committed, x, labels, extra, loss_fn, M,
        num_stages=S, virtual=V, programs=programs,
    )
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump({
            "loss": repr(float(loss)),  # bitwise contract
            "compiled": programs.compiled,
            "loaded": programs.loaded,
            "stores": cache.stores,
            "hits": cache.hits,
            "programs": 2 * S * V,
        }, f)


def _run_stagewise_leg(cache_dir: str, label: str) -> dict:
    out_path = os.path.join(cache_dir, f"{label}.result.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # single device: no virtual mesh needed
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--stagewise-leg",
         cache_dir, out_path],
        env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    if proc.returncode != 0:
        print(f"pipeline_smoke: stagewise {label} leg failed "
              f"rc={proc.returncode}", file=sys.stderr)
        print(proc.stderr[-4000:], file=sys.stderr)
        sys.exit(1)
    with open(out_path, encoding="utf-8") as f:
        return json.load(f)


def main() -> int:
    from accelerate_tpu.parallel.pipeline import bubble_fraction, bubble_ticks
    from accelerate_tpu.parallel.plan import current_plan

    failures = []

    acc_pp, step_pp, losses_pp = _train(pp=2)
    plan = acc_pp.plan
    if plan is not current_plan():
        failures.append("accelerator.plan is not the published current_plan()")
    geometry = (plan.pp, plan.dp, plan.zero1, plan.compression,
                plan.stage.schedule, plan.stage.virtual)
    expected = (2, 2, True, "int8", "interleaved", 2)
    if geometry != expected:
        failures.append(f"plan resolved {geometry}, expected {expected}")

    acc_dp, step_dp, losses_dp = _train(pp=1)
    diffs = [abs(a - b) for a, b in zip(losses_pp, losses_dp)]
    if max(diffs) > 1e-3:
        failures.append(f"loss parity vs dp-only broken: {diffs}")

    for name, acc, step in (("pp2", acc_pp, step_pp), ("dp", acc_dp, step_dp)):
        records = acc.telemetry.timeline.records()
        late_builds = [r.step for r in records[2:] if r.built]
        if late_builds:
            failures.append(f"{name}: steady-state recompiles at {late_builds}")
        if len(step._cache) != 2:
            failures.append(
                f"{name}: {len(step._cache)} compiled variants (want the 2 "
                "accumulation variants)"
            )

    fused_b = bubble_ticks(8, 2, 1, granularity=2)
    inter_b = bubble_ticks(8, 2, 2, granularity=2)
    if not inter_b < fused_b:
        failures.append(f"bubble ticks not reduced: {inter_b} vs {fused_b}")
    if not bubble_fraction(8, 2, 2) < bubble_fraction(8, 2, 1):
        failures.append("bubble fraction not reduced at V=2")

    _, _, losses_f = _train(pp=2, schedule="1f1b")
    fdiffs = [abs(a - b) for a, b in zip(losses_pp, losses_f)]
    if max(fdiffs) > 1e-4:
        failures.append(f"interleaved vs fused trajectory diverged: {fdiffs}")

    # the committed layout resolved as the layout of record (default V>1)
    if plan.layer_layout != "committed":
        failures.append(
            f"interleaved plan resolved layer_layout={plan.layer_layout!r}, "
            "expected the committed default"
        )

    # zero permutation bytes, proven structurally: no gather op / no layer-
    # order index vector in the committed lowering, both in the gather arm's
    ir_facts = {}
    try:
        from accelerate_tpu.native.kernels.inspect import check_pipeline_layout

        ir_facts = check_pipeline_layout()
    except AssertionError as exc:
        failures.append(f"layout IR inspection: {exc}")

    # per-stage captured programs round-trip the AOT store across fresh
    # processes: cold compiles+stores all 2·S·V programs, warm loads every
    # one with zero compiles at a bitwise-equal loss
    cache_dir = tempfile.mkdtemp(prefix="atpu_pipeline_smoke_")
    cold = _run_stagewise_leg(cache_dir, "cold")
    warm = _run_stagewise_leg(cache_dir, "warm")
    if cold["compiled"] != cold["programs"] or cold["loaded"] != 0:
        failures.append(f"stagewise cold leg: {cold}")
    if cold["stores"] != cold["programs"]:
        failures.append(
            f"stagewise cold leg stored {cold['stores']}/{cold['programs']} "
            "programs"
        )
    if warm["compiled"] != 0 or warm["loaded"] != warm["programs"]:
        failures.append(
            f"stagewise warm leg paid compiles: compiled={warm['compiled']} "
            f"loaded={warm['loaded']}/{warm['programs']}"
        )
    if warm["loss"] != cold["loss"]:
        failures.append(
            f"stagewise warm loss not bitwise-equal: cold={cold['loss']} "
            f"warm={warm['loss']}"
        )

    print(
        f"pipeline_smoke: plan {plan.describe()} | losses pp2={losses_pp[-1]:.4f} "
        f"dp={losses_dp[-1]:.4f} (max diff {max(diffs):.2e}) | bubble "
        f"{fused_b}->{inter_b} ticks | layout IR gather ops "
        f"{ir_facts.get('gather_gather_ops')}->"
        f"{ir_facts.get('committed_gather_ops')} | stagewise warm "
        f"{warm['loaded']}/{warm['programs']} programs from store, "
        f"{warm['compiled']} compiles"
    )
    for failure in failures:
        print(f"pipeline_smoke: FAIL: {failure}", file=sys.stderr)
    print(f"pipeline_smoke: {'FAILED' if failures else 'ok'}")
    return 1 if failures else 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--stagewise-leg":
        _stagewise_leg(sys.argv[2], sys.argv[3])
        sys.exit(0)
    sys.exit(main())
