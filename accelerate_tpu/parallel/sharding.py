"""Parameter sharding rules — FSDP/ZeRO and TP as GSPMD layouts.

The reference implements FSDP by wrapping modules (accelerator.py:1555-1679)
and TP via torch device meshes (:1545); here both are *data layout* decisions:
each parameter gets a ``NamedSharding`` over the global mesh and XLA inserts
the all-gathers / reduce-scatters (ZeRO) or keeps the matmuls local (TP).

Rules:
* TP plan entries map parameter-path regexes to partition-spec templates, e.g.
  ``{".*q_proj.weight": ("tp", None)}`` (shard output features).  Models can
  carry a default plan in ``Module.tp_plan``.
* FSDP shards the largest remaining axis over the ``fsdp`` mesh axis when
  divisible (ZeRO-3 param sharding; optimizer state follows params because
  optax states mirror param shapes and jit propagates shardings).
* Everything else is replicated.
"""

from __future__ import annotations

import re
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.dataclasses import FullyShardedDataParallelPlugin, TensorParallelPlugin


def canonical_spec(spec: P, mesh: Mesh) -> P:
    """Drop size-1 mesh axes and trailing Nones from a PartitionSpec.

    ``P('tp')`` over a tp:1 mesh is semantically ``P()`` but compares unequal,
    and ``jax.jit`` caches on input shardings: GSPMD canonicalizes program
    *outputs* to the axis-free form, so a non-canonical spec on a parameter
    makes the next step's carried state arrive with a "new" sharding and
    silently recompiles the whole train step.
    """
    def _size(a):
        if a not in mesh.shape:
            raise ValueError(
                f"PartitionSpec axis {a!r} does not exist in mesh axes "
                f"{tuple(mesh.shape)} — typo in a tp_plan / sharding spec?"
            )
        return mesh.shape[a]

    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if _size(a) > 1)
            out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
        else:
            out.append(entry if _size(entry) > 1 else None)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def zero1_state_spec(shape: tuple, mesh: Mesh, param_spec: Optional[P] = None) -> P:
    """PartitionSpec for ZeRO-1 optimizer state (fp32 masters + moments).

    "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
    Training" (arXiv:2004.13336) as a layout decision: the state leaf keeps
    its parameter's spec and additionally shards the largest still-free axis
    over ``dp`` when divisible.  GSPMD then lowers the captured update to
    reduce-scatter → shard-local AdamW → all-gather.  Tiny/indivisible
    params fall back to the param layout (replicated under pure DP), and
    ``canonical_spec`` guarantees a dp:1 mesh yields the axis-free spec so
    the capture cache key cannot drift into a recompile.
    """
    spec = list(param_spec) if param_spec is not None else []
    spec += [None] * (len(shape) - len(spec))
    dp_size = mesh.shape.get("dp", 1)
    used: set = set()
    for entry in spec:
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        elif entry is not None:
            used.add(entry)
    if dp_size > 1 and "dp" not in used:
        order = sorted(range(len(shape)), key=lambda i: -shape[i])
        for axis in order:
            if spec[axis] is None and shape[axis] % dp_size == 0 and shape[axis] >= dp_size:
                spec[axis] = "dp"
                break
    return canonical_spec(P(*spec), mesh)


def spec_to_jsonable(spec: P) -> list:
    """PartitionSpec → JSON-ready list (str | [str, ...] | None per dim) —
    the form recorded in checkpoint index.json metadata and consumed by
    graftlint's sharding-spec-drift rule."""
    return [list(e) if isinstance(e, (tuple, list)) else e for e in spec]


def plan_param_spec(
    name: str,
    shape: tuple,
    mesh: Mesh,
    fsdp_plugin: Optional[FullyShardedDataParallelPlugin] = None,
    tp_plan: Optional[dict] = None,
    fsdp_exempt: bool = False,
) -> P:
    """Decide the PartitionSpec for one parameter."""
    fsdp_size = mesh.shape.get("fsdp", 1)
    spec = [None] * len(shape)

    if tp_plan:
        # templates name their own mesh axes (tp, pp, ...); size-1 axes are
        # no-ops, so apply unconditionally — a pp-sharded layer stack must be
        # laid out even when tp=1
        for pattern, template in tp_plan.items():
            if re.fullmatch(pattern, name) or re.search(pattern, name):
                template = list(template) + [None] * (len(shape) - len(template))
                spec = list(template[: len(shape)])
                break

    if not fsdp_exempt and fsdp_plugin is not None and fsdp_size > 1 and fsdp_plugin.sharding_strategy in (
        "FULL_SHARD",
        "HYBRID_SHARD",
    ):
        # shard the largest axis not already taken by tp and divisible by fsdp
        order = sorted(range(len(shape)), key=lambda i: -shape[i])
        for axis in order:
            if spec[axis] is None and shape[axis] % fsdp_size == 0 and shape[axis] >= fsdp_size:
                spec[axis] = "fsdp"
                break
    return canonical_spec(P(*spec), mesh)


def shard_module_params(
    model,
    mesh: Mesh,
    fsdp_plugin: Optional[FullyShardedDataParallelPlugin] = None,
    tp_plugin: Optional[TensorParallelPlugin] = None,
) -> dict[str, P]:
    """device_put every param/buffer with its planned sharding.

    Returns the {name: spec} plan (used by checkpointing and tests).
    """
    tp_plan = None
    if tp_plugin is not None and tp_plugin.tp_plan is not None:
        tp_plan = tp_plugin.tp_plan
    elif getattr(model, "tp_plan", None):
        tp_plan = model.tp_plan

    plan: dict[str, P] = {}
    for name, p in model.named_parameters():
        spec = plan_param_spec(
            name,
            tuple(p.shape),
            mesh,
            fsdp_plugin,
            tp_plan,
            fsdp_exempt=getattr(p, "fsdp_exempt", False),
        )
        plan[name] = spec
        p.data = jax.device_put(p.data, NamedSharding(mesh, spec))
    for name, b in model.named_buffers():
        b.data = jax.device_put(b.data, NamedSharding(mesh, P()))
    return plan


def replicate_module_params(model, mesh: Mesh) -> None:
    for t in list(model.parameters()) + list(model.buffers()):
        t.data = jax.device_put(t.data, NamedSharding(mesh, P()))


def activation_spec(ndim: int, mesh: Mesh) -> P:
    """Canonical activation layout: batch over (dp, fsdp), rest unsharded.

    Matches the data loader's batch placement (``data_axes``), so constraining
    intermediate activations to this spec pins XLA's layout search at layer
    boundaries and prevents the "involuntary full rematerialization" reshards
    the round-1 multichip dryrun hit (batch layout drifting between the
    loader's P(('dp','fsdp')) and per-op inferred layouts).
    """
    from .mesh import data_axes

    batch_axes = data_axes(mesh)
    return canonical_spec(P(batch_axes, *([None] * (ndim - 1))), mesh)


def constrain_activation(x, mesh: Optional[Mesh] = None):
    """``with_sharding_constraint`` to the canonical activation layout.

    Accepts tape Tensors or raw arrays; no-op without a multi-device mesh
    (single chip, or outside an Accelerator context).  Differentiable: the
    constraint is linear, JAX transposes it to itself.
    """
    if mesh is None:
        from ..state import AcceleratorState

        if not AcceleratorState._shared_state:
            return x
        mesh = AcceleratorState().mesh
    if mesh is None or np.prod(list(mesh.shape.values())) <= 1:
        return x

    from ..nn.tape import Tensor, tape_op

    def _constrain(v):
        return jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, activation_spec(v.ndim, mesh))
        )

    if isinstance(x, Tensor):
        return tape_op(_constrain, x)
    return _constrain(x)
