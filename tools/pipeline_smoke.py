#!/usr/bin/env python
"""pipeline_smoke — `make pipeline-smoke`: prove the resolved ParallelPlan
and the interleaved 1F1B pipeline end-to-end on CPU in seconds
(docs/parallel_plan.md, ISSUE 15 acceptance).

2-stage × dp=2 on the virtual 4-device mesh, interleaved schedule (V=2),
ZeRO-1 + int8 compression + gradient accumulation in ONE captured step.
Exit 0 requires:

* the plan resolves the acceptance geometry (pp=2, dp=2, zero1 armed,
  int8 compression, schedule=interleaved, V=2) and IS what consumers see
  (``current_plan()``);
* the composed run trains within 1e-3 loss parity of the dp-only run on
  the same data/seed, and both replay with zero steady-state recompiles
  (no builds after the two accumulation variants);
* the interleaved schedule's analytic bubble profile is strictly better
  than the fused one (bubble_ticks and bubble_fraction at V=2);
* interleaved-vs-fused training parity holds (same trajectory).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _train(pp: int, schedule: str = "interleaved", micro_steps: int = 6):
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import Accelerator, CompressionKwargs, ParallelismConfig, TelemetryKwargs
    from accelerate_tpu.data_loader import batch_to_global_array
    from accelerate_tpu.models import GPTConfig, PipelinedGPTLMHeadModel
    from accelerate_tpu.utils.dataclasses import PipelineParallelPlugin

    Accelerator._reset_state()
    nn.manual_seed(0)
    kwargs = dict(
        mixed_precision="no",
        gradient_accumulation_steps=2,
        kwargs_handlers=[
            TelemetryKwargs(enabled=True),
            CompressionKwargs(policy="int8"),
        ],
    )
    if pp > 1:
        acc = Accelerator(
            parallelism_config=ParallelismConfig(pp_size=pp),
            pp_plugin=PipelineParallelPlugin(
                pp_size=pp, num_microbatches=8, schedule=schedule
            ),
            **kwargs,
        )
    else:
        acc = Accelerator(**kwargs)
    cfg = dataclasses.replace(GPTConfig.tiny(), n_layer=4)
    model = PipelinedGPTLMHeadModel(cfg, num_microbatches=8)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        with acc.accumulate(model):
            opt.zero_grad()
            out = model(ids, labels=ids)
            acc.backward(out["loss"])
            opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(micro_steps):
        ids = batch_to_global_array(
            jnp.asarray(rng.integers(0, 1024, (64, 32)), jnp.int32),
            mesh=acc.mesh,
        )
        losses.append(float(step(ids)))
    return acc, step, losses


def main() -> int:
    from accelerate_tpu.parallel.pipeline import bubble_fraction, bubble_ticks
    from accelerate_tpu.parallel.plan import current_plan

    failures = []

    acc_pp, step_pp, losses_pp = _train(pp=2)
    plan = acc_pp.plan
    if plan is not current_plan():
        failures.append("accelerator.plan is not the published current_plan()")
    geometry = (plan.pp, plan.dp, plan.zero1, plan.compression,
                plan.stage.schedule, plan.stage.virtual)
    expected = (2, 2, True, "int8", "interleaved", 2)
    if geometry != expected:
        failures.append(f"plan resolved {geometry}, expected {expected}")

    acc_dp, step_dp, losses_dp = _train(pp=1)
    diffs = [abs(a - b) for a, b in zip(losses_pp, losses_dp)]
    if max(diffs) > 1e-3:
        failures.append(f"loss parity vs dp-only broken: {diffs}")

    for name, acc, step in (("pp2", acc_pp, step_pp), ("dp", acc_dp, step_dp)):
        records = acc.telemetry.timeline.records()
        late_builds = [r.step for r in records[2:] if r.built]
        if late_builds:
            failures.append(f"{name}: steady-state recompiles at {late_builds}")
        if len(step._cache) != 2:
            failures.append(
                f"{name}: {len(step._cache)} compiled variants (want the 2 "
                "accumulation variants)"
            )

    fused_b = bubble_ticks(8, 2, 1, granularity=2)
    inter_b = bubble_ticks(8, 2, 2, granularity=2)
    if not inter_b < fused_b:
        failures.append(f"bubble ticks not reduced: {inter_b} vs {fused_b}")
    if not bubble_fraction(8, 2, 2) < bubble_fraction(8, 2, 1):
        failures.append("bubble fraction not reduced at V=2")

    _, _, losses_f = _train(pp=2, schedule="1f1b")
    fdiffs = [abs(a - b) for a, b in zip(losses_pp, losses_f)]
    if max(fdiffs) > 1e-4:
        failures.append(f"interleaved vs fused trajectory diverged: {fdiffs}")

    print(
        f"pipeline_smoke: plan {plan.describe()} | losses pp2={losses_pp[-1]:.4f} "
        f"dp={losses_dp[-1]:.4f} (max diff {max(diffs):.2e}) | bubble "
        f"{fused_b}->{inter_b} ticks"
    )
    for failure in failures:
        print(f"pipeline_smoke: FAIL: {failure}", file=sys.stderr)
    print(f"pipeline_smoke: {'FAILED' if failures else 'ok'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
