from .bert import BertConfig, BertForSequenceClassification, BertModel
from .gpt import GPTConfig, GPTLMHeadModel, PipelinedGPTLMHeadModel
from .gptj import GPTJConfig, GPTJForCausalLM
from .gptneox import GPTNeoXConfig, GPTNeoXForCausalLM
from .llama import LlamaConfig, LlamaForCausalLM, RopeScaling
from .opt import OPTConfig, OPTForCausalLM
from .t5 import T5Config, T5ForConditionalGeneration

# name → zero-arg builder; used by `accelerate-tpu estimate-memory` and tests
MODEL_REGISTRY = {
    "bert-base": lambda: BertModel(BertConfig.base()),
    "bert-small": lambda: BertModel(BertConfig.small()),
    "bert-base-classifier": lambda: BertForSequenceClassification(BertConfig.base()),
    "gpt-tiny": lambda: GPTLMHeadModel(GPTConfig.tiny()),
    "gpt-small": lambda: GPTLMHeadModel(GPTConfig.small()),
    "gpt-medium": lambda: GPTLMHeadModel(GPTConfig.medium()),
    "llama-tiny": lambda: LlamaForCausalLM(LlamaConfig.tiny()),
    "llama-7b": lambda: LlamaForCausalLM(LlamaConfig.llama2_7b()),
    "opt-tiny": lambda: OPTForCausalLM(OPTConfig.tiny()),
    "opt-125m": lambda: OPTForCausalLM(OPTConfig.opt_125m()),
    "opt-6.7b": lambda: OPTForCausalLM(OPTConfig.opt_6_7b()),
    "gptj-tiny": lambda: GPTJForCausalLM(GPTJConfig.tiny()),
    "gptj-6b": lambda: GPTJForCausalLM(GPTJConfig.gptj_6b()),
    "gptneox-tiny": lambda: GPTNeoXForCausalLM(GPTNeoXConfig.tiny()),
    "gptneox-20b": lambda: GPTNeoXForCausalLM(GPTNeoXConfig.neox_20b()),
    "t5-tiny": lambda: T5ForConditionalGeneration(T5Config.tiny()),
    "t5-small": lambda: T5ForConditionalGeneration(T5Config.t5_small()),
    "t0pp-11b": lambda: T5ForConditionalGeneration(T5Config.t0pp_geometry()),
}
