"""BulletMenu: real keystroke handling through a pty + non-TTY fallback
(reference commands/menu/selection_menu.py parity)."""

import os
import pty
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MENU_SCRIPT = """
import sys
from accelerate_tpu.commands.menu import BulletMenu
idx = BulletMenu("Pick one:", ["alpha", "beta", "gamma"]).run(default=0)
print(f"RESULT={idx}")
"""


def _run_in_pty(keys: bytes, timeout: float = 120.0) -> str:
    """Run the menu under a pseudo-terminal, feed raw keys, return output.

    Expect-style: keys are written only after the menu has rendered its
    cursor marker — input sent while the child is still in canonical mode
    does not survive the switch to raw mode.
    """
    import select
    import time

    leader, follower = pty.openpty()
    env = dict(
        os.environ,
        PYTHONPATH=os.pathsep.join(
            p for p in (REPO, os.environ.get("PYTHONPATH", "")) if p
        ),
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", MENU_SCRIPT],
        stdin=follower,
        stdout=follower,
        stderr=subprocess.DEVNULL,
        env=env,
        close_fds=True,
    )
    os.close(follower)
    buf = b""
    deadline = time.monotonic() + timeout
    try:
        while "➤".encode() not in buf:
            remaining = deadline - time.monotonic()
            assert remaining > 0, f"menu never rendered: {buf[-300:]!r}"
            ready, _, _ = select.select([leader], [], [], remaining)
            assert ready, f"menu never rendered: {buf[-300:]!r}"
            buf += os.read(leader, 4096)
        time.sleep(0.3)  # let the renderer re-enter the raw-mode key read
        os.write(leader, keys)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            ready, _, _ = select.select([leader], [], [], min(remaining, 1.0))
            if not ready:
                if proc.poll() is not None:
                    break
                continue
            try:
                data = os.read(leader, 4096)
            except OSError:
                break
            if not data:
                break
            buf += data
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
        os.close(leader)
    return buf.decode(errors="replace")


@pytest.mark.parametrize(
    "keys,expected",
    [
        (b"\r", 0),  # Enter on the default
        (b"\x1b[B\r", 1),  # arrow down once
        (b"\x1b[B\x1b[B\r", 2),  # down twice
        (b"j\x1b[A\r", 0),  # vim down then arrow up
        (b"2\r", 2),  # digit jump
    ],
)
def test_keystrokes_select(keys, expected):
    out = _run_in_pty(keys)
    assert f"RESULT={expected}" in out, out[-400:]


def test_non_tty_fallback_accepts_number_and_name():
    env = dict(
        os.environ,
        PYTHONPATH=os.pathsep.join(
            p for p in (REPO, os.environ.get("PYTHONPATH", "")) if p
        ),
        JAX_PLATFORMS="cpu",
    )
    for stdin_text, expected in [("1\n", 1), ("gamma\n", 2), ("\n", 0)]:
        proc = subprocess.run(
            [sys.executable, "-c", MENU_SCRIPT],
            input=stdin_text,
            capture_output=True,
            text=True,
            timeout=60,
            env=env,
        )
        assert proc.returncode == 0, proc.stderr[-400:]
        assert f"RESULT={expected}" in proc.stdout
