"""GPT-J / GPT-NeoX decoder tests: HF parity, decode, conversion.

These are the reference's own headline benchmark families (GPT-J-6B and
GPT-Neo-X-20B, reference benchmarks/big_model_inference/README.md:31-34).
Parity is asserted numerically against transformers' CPU implementations.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp

from accelerate_tpu.models import (
    GPTJConfig,
    GPTJForCausalLM,
    GPTNeoXConfig,
    GPTNeoXForCausalLM,
)


@pytest.fixture(scope="module")
def gptj_pair():
    from transformers import GPTJConfig as HFConfig, GPTJForCausalLM as HFModel

    from accelerate_tpu.utils.torch_bridge import convert_torch_module

    torch.manual_seed(0)
    hf = HFModel(
        HFConfig(
            vocab_size=1024, n_positions=256, n_embd=128, n_layer=2, n_head=4,
            rotary_dim=16, n_inner=256,
            resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        )
    ).eval()
    return hf, convert_torch_module(hf)


@pytest.fixture(scope="module")
def neox_pair():
    from transformers import (
        GPTNeoXConfig as HFConfig,
        GPTNeoXForCausalLM as HFModel,
    )

    from accelerate_tpu.utils.torch_bridge import convert_torch_module

    torch.manual_seed(0)
    hf = HFModel(
        HFConfig(
            vocab_size=1024, hidden_size=128, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=256,
            max_position_embeddings=256, rotary_pct=0.25,
            hidden_dropout=0.0, attention_dropout=0.0,
        )
    ).eval()
    return hf, convert_torch_module(hf)


def _assert_logits_parity(hf, ours, seed=0):
    ids = np.random.default_rng(seed).integers(0, 1024, (2, 16), dtype=np.int64)
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids, jnp.int32))["logits"].data)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_gptj_forward_parity(gptj_pair):
    _assert_logits_parity(*gptj_pair)


def test_neox_forward_parity(neox_pair):
    _assert_logits_parity(*neox_pair)


def _assert_greedy_parity(ours, seed=1):
    ids = np.random.default_rng(seed).integers(0, 1024, (2, 7), dtype=np.int32)
    want = jnp.asarray(ids, jnp.int32)
    for _ in range(5):
        logits = ours(want)["logits"].data
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want = jnp.concatenate([want, nxt[:, None]], axis=1)
    got = ours.generate(ids, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_gptj_greedy_generate_matches_full_forward(gptj_pair):
    _assert_greedy_parity(gptj_pair[1])


def test_neox_greedy_generate_matches_full_forward(neox_pair):
    _assert_greedy_parity(neox_pair[1])


def test_gptj_from_pretrained_roundtrip(tmp_path, gptj_pair):
    hf, ours = gptj_pair
    hf.save_pretrained(tmp_path / "gptj")
    from accelerate_tpu.utils.hf import from_pretrained

    loaded = from_pretrained(str(tmp_path / "gptj"))
    ids = np.random.default_rng(2).integers(0, 1024, (1, 12), dtype=np.int32)
    a = np.asarray(ours(jnp.asarray(ids))["logits"].data)
    b = np.asarray(loaded(jnp.asarray(ids))["logits"].data)
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_neox_from_pretrained_roundtrip(tmp_path, neox_pair):
    hf, ours = neox_pair
    hf.save_pretrained(tmp_path / "neox")
    from accelerate_tpu.utils.hf import from_pretrained

    loaded = from_pretrained(str(tmp_path / "neox"))
    ids = np.random.default_rng(2).integers(0, 1024, (1, 12), dtype=np.int32)
    a = np.asarray(ours(jnp.asarray(ids))["logits"].data)
    b = np.asarray(loaded(jnp.asarray(ids))["logits"].data)
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_neox_sequential_residual_rejected():
    with pytest.raises(NotImplementedError, match="parallel"):
        GPTNeoXConfig(use_parallel_residual=False)


def test_gptj_train_step_smoke():
    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import batch_to_global_array

    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(mixed_precision="bf16")
    model = GPTJForCausalLM(GPTJConfig.tiny())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    ids = batch_to_global_array(
        jnp.asarray(np.random.default_rng(0).integers(0, 1024, (8, 32)), jnp.int32),
        mesh=acc.mesh,
    )
    losses = [float(step(ids)) for _ in range(3)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses
