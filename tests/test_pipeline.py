import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.parallel.pipeline import bubble_fraction, bubble_ticks, gpipe
from accelerate_tpu.state import AcceleratorState
from accelerate_tpu.utils.dataclasses import ParallelismConfig


def stage_fn(params, h):
    return jnp.tanh(h @ params["w"] + params["b"])


def make_stages(n_stages, dim, key=0):
    ks = jax.random.split(jax.random.key(key), n_stages)
    return {
        "w": jnp.stack([jax.random.normal(k, (dim, dim)) * 0.5 for k in ks]),
        "b": jnp.zeros((n_stages, dim)),
    }


def sequential(params, x):
    h = x
    for i in range(params["w"].shape[0]):
        h = stage_fn({"w": params["w"][i], "b": params["b"][i]}, h)
    return h


def test_gpipe_matches_sequential():
    state = AcceleratorState(parallelism_config=ParallelismConfig(pp_size=4, dp_size=2))
    params = make_stages(4, 16)
    x = jax.random.normal(jax.random.key(1), (8, 16))
    expected = sequential(params, x)
    out = jax.jit(
        lambda p, x_: gpipe(stage_fn, p, x_, num_microbatches=4, mesh=state.mesh)
    )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_gpipe_differentiable():
    state = AcceleratorState(parallelism_config=ParallelismConfig(pp_size=4, dp_size=2))
    params = make_stages(4, 8)
    x = jax.random.normal(jax.random.key(2), (4, 8))

    def loss_pp(p):
        return gpipe(stage_fn, p, x, num_microbatches=2, mesh=state.mesh).sum()

    def loss_seq(p):
        return sequential(p, x).sum()

    g_pp = jax.grad(loss_pp)(params)
    g_seq = jax.grad(loss_seq)(params)
    np.testing.assert_allclose(np.asarray(g_pp["w"]), np.asarray(g_seq["w"]), rtol=1e-4, atol=1e-6)


def test_gpipe_pp1_fallback():
    state = AcceleratorState()  # pp == 1
    params = make_stages(3, 8)
    x = jax.random.normal(jax.random.key(3), (4, 8))
    out = gpipe(stage_fn, params, x, num_microbatches=2, mesh=state.mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(sequential(params, x)), rtol=1e-5)


def test_gpipe_bad_microbatch():
    state = AcceleratorState(parallelism_config=ParallelismConfig(pp_size=4, dp_size=2))
    params = make_stages(4, 8)
    with pytest.raises(ValueError):
        gpipe(stage_fn, params, jnp.ones((6, 8)), num_microbatches=4, mesh=state.mesh)


def test_bubble_profile_common_granularity():
    """Pin the bench's A/B bubble accounting (bench.py _pipeline_block):
    BOTH arms must be quoted in the SAME chunk unit (granularity=V), where
    the fused profile is exactly V× the interleaved one.  At each
    schedule's OWN default granularity the two are numerically equal
    (2·(S−1) self-sized chunks each) — comparing defaults would silently
    erase the interleaving gain, which is the bug this test pins out."""
    # the bench geometry: M=8, S=2, V=2 quoted in 1/2-stage chunks
    assert bubble_ticks(8, 2, 1, granularity=2) == 4
    assert bubble_ticks(8, 2, 2, granularity=2) == 2
    for S in (2, 4):
        for V in (2, 3, 4):
            fused = bubble_ticks(8, S, 1, granularity=V)
            inter = bubble_ticks(8, S, V, granularity=V)
            assert fused == V * inter, (S, V, fused, inter)
            assert inter < fused, (S, V)
            # default granularity is the schedule's own chunk: both sides
            # collapse to 2*(S-1) and the comparison loses its meaning
            assert bubble_ticks(8, S, V) == bubble_ticks(8, S, 1) == 2 * (S - 1)
    # the analytic fraction carries the same monotone gain
    assert bubble_fraction(8, 2, 2) == bubble_fraction(8, 2, 1) / 2


# ---------------------------------------------------------------------------
# Pipelined GPT: real trunk through GPipe (pp) + ring attention (sp)
# ---------------------------------------------------------------------------
def test_pipelined_gpt_matches_plain_trunk():
    """The pp×sp pipelined trunk must equal a sequential per-layer apply."""
    import functools

    import accelerate_tpu.nn as nn
    from accelerate_tpu.models.gpt import (
        GPTConfig,
        _StackedBlocks,
        _pipelined_block,
    )

    nn.manual_seed(0)
    cfg = GPTConfig(vocab_size=256, n_positions=64, n_embd=32, n_layer=4, n_head=2)
    blocks = _StackedBlocks(cfg)
    stacked = {n: getattr(blocks, n).data for n in _StackedBlocks._ORDER}
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(4, 16, 32)).astype(np.float32)
    )
    body = functools.partial(
        _pipelined_block, n_head=2, eps=cfg.layer_norm_eps, seq_axis="sp"
    )

    from accelerate_tpu.parallel.mesh import shard_map_compat
    from jax.sharding import Mesh, PartitionSpec as P

    from accelerate_tpu.utils.constants import ALL_MESH_AXES

    mesh1 = Mesh(
        np.asarray(jax.devices()[:1]).reshape((1,) * len(ALL_MESH_AXES)),
        ALL_MESH_AXES,
    )

    def seq_apply(xv):
        h = xv
        for i in range(cfg.n_layer):
            h = body({k: v[i] for k, v in stacked.items()}, h)
        return h

    ref = np.asarray(
        shard_map_compat(seq_apply, mesh=mesh1, in_specs=(P(),), out_specs=P())(x)
    )

    # pp2 × sp2 × dp2: layers span stages (2 per stage), seq rides the ring
    mesh8 = Mesh(
        np.asarray(jax.devices()).reshape(2, 1, 1, 2, 1, 2),
        ("dp", "fsdp", "tp", "sp", "ep", "pp"),
    )
    got = np.asarray(
        gpipe(body, stacked, x, num_microbatches=2, mesh=mesh8, seq_axis="sp")
    )
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_pipelined_gpt_trains_on_pp_sp_mesh():
    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import Accelerator
    from accelerate_tpu.data_loader import batch_to_global_array
    from accelerate_tpu.models import GPTConfig, PipelinedGPTLMHeadModel

    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(parallelism_config=ParallelismConfig(sp_size=2, pp_size=2))
    cfg = GPTConfig(vocab_size=256, n_positions=64, n_embd=32, n_layer=4, n_head=2)
    model = PipelinedGPTLMHeadModel(cfg, num_microbatches=2)
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    # stacked block params must ride the pp axis
    spec = model.blocks.qkv_w.data.sharding.spec
    assert spec and spec[0] == "pp", f"layer stack not pp-sharded: {spec}"

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, size=(8, 32)), jnp.int32
    )
    gb = batch_to_global_array(ids, mesh=acc.mesh)
    losses = [float(step(gb)) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses
    Accelerator._reset_state()
