"""``accelerate-tpu estimate-memory`` — dtype-wise model memory table.

Counterpart of ``/root/reference/src/accelerate/commands/estimate.py:183-305``.
The reference pulls the model from the Hub onto the meta device; here the
size comes from zero-memory shape evaluation: built-in model families
(``gpt-small``, ``bert-base``, ...) are constructed under
``init_empty_weights`` (meta device, big_modeling.py), and any HuggingFace
model id/path with a local ``config.json`` is sized via ``transformers``'
meta-device init when the package is importable (no downloads — zero-egress
friendly).
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Optional

__all__ = ["estimate_command", "estimate_command_parser", "gather_data",
           "estimate_training_usage", "estimate_training_usage_offloaded"]

_DTYPE_BYTES = {"float32": 4, "float16": 2, "bfloat16": 2, "int8": 1, "int4": 0.5}


def _builtin_model(name: str):
    from ..big_modeling import init_empty_weights
    from ..models import MODEL_REGISTRY

    if name not in MODEL_REGISTRY:
        return None
    builder = MODEL_REGISTRY[name]
    with init_empty_weights(include_buffers=False):
        model = builder()
    return model


def _num_params_builtin(model) -> tuple[int, int]:
    total = 0
    largest_layer = 0
    for module in model.children():
        size = sum(p.numel() for p in module.parameters())
        largest_layer = max(largest_layer, size)
    total = sum(p.numel() for p in model.parameters())
    return total, largest_layer


def _num_params_hf(model_id: str) -> Optional[tuple[int, int, str]]:
    """Size a HF model from a local path / cached config via transformers."""
    try:
        import torch
        from transformers import AutoConfig, AutoModel
    except ImportError:
        return None
    try:
        config = AutoConfig.from_pretrained(model_id, local_files_only=True)
        with torch.device("meta"):
            model = AutoModel.from_config(config)
    except Exception as e:
        raise ValueError(
            f"{model_id!r} is not a built-in model "
            f"(see `accelerate-tpu estimate-memory --list`) and could not be "
            f"loaded through transformers offline: {e}"
        )
    largest = 0
    for child in model.children():
        largest = max(largest, sum(p.numel() for p in child.parameters()))
    return model.num_parameters(), largest, config.model_type


def estimate_training_usage(bytes_params: float) -> float:
    """Peak training memory ≈ params + grads + Adam m/v + fp32 master copy
    (reference estimate.py:239: 4× model size heuristic for Adam)."""
    return 4 * bytes_params


def estimate_training_usage_offloaded(bytes_params: float) -> float:
    """Device HBM with FullyShardedDataParallelPlugin(offload_optimizer=True):
    params + grads stay on device; Adam moments and fp32 masters live in
    pinned host memory (docs/sharding.md)."""
    return 2 * bytes_params


def estimate_training_usage_param_offloaded(bytes_params: float) -> float:
    """IDLE (between-step) device HBM with full ZeRO-Infinity-style offload
    (``cpu_offload=True`` params + ``offload_optimizer=True``): params,
    moments and masters are all pinned to host between steps, so steady
    inter-step HBM residency is ~0 — only grads retained across
    accumulation micro-steps remain.  Peak DURING a step is unchanged
    (params are staged for the whole forward/backward: ~2× params); the win
    is idle residency and fitting alongside other HBM tenants."""
    return bytes_params  # grads retained between micro-steps; 0 after sync


def _fmt(num_bytes: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(num_bytes) < 1024:
            return f"{num_bytes:.2f} {unit}"
        num_bytes /= 1024
    return f"{num_bytes:.2f} PB"


def gather_data(args) -> list[list]:
    """Rows: [dtype, largest_layer, total_size, training_size,
    training_hbm_with_optimizer_offload]."""
    model = _builtin_model(args.model_name)
    if model is not None:
        total, largest = _num_params_builtin(model)
    else:
        sized = _num_params_hf(args.model_name)
        if sized is None:
            raise ValueError(
                f"`{args.model_name}` is not a built-in model, and sizing it from "
                "the Hugging Face Hub requires `transformers` and `torch` to be "
                "importable. Install them or pass one of the built-in model names."
            )
        total, largest, _ = sized
    rows = []
    for dtype in args.dtypes:
        per_param = _DTYPE_BYTES[dtype]
        total_bytes = total * per_param
        rows.append(
            [
                dtype,
                largest * per_param,
                total_bytes,
                estimate_training_usage(total_bytes),
                estimate_training_usage_offloaded(total_bytes),
                estimate_training_usage_param_offloaded(total_bytes),
            ]
        )
    return rows


def estimate_command_parser(subparsers: Optional[argparse._SubParsersAction] = None):
    description = "Estimate model memory per dtype (load + Adam training)"
    if subparsers is not None:
        parser = subparsers.add_parser("estimate-memory", help=description)
    else:
        parser = argparse.ArgumentParser(
            "accelerate-tpu estimate-memory", description=description
        )
    parser.add_argument(
        "model_name",
        nargs="?",
        default=None,
        help="Built-in name (gpt-small, bert-base, ...) or a local HF model path",
    )
    parser.add_argument(
        "--dtypes",
        nargs="+",
        default=["float32", "bfloat16", "int8", "int4"],
        choices=list(_DTYPE_BYTES),
    )
    parser.add_argument("--list", action="store_true", help="List built-in models")
    parser.add_argument("--json", action="store_true", help="Machine-readable output")
    if subparsers is not None:
        parser.set_defaults(func=estimate_command)
    return parser


def estimate_command(args) -> None:
    if args.list or args.model_name is None:
        from ..models import MODEL_REGISTRY

        print("Built-in models:")
        for name in sorted(MODEL_REGISTRY):
            print(f"  {name}")
        return
    rows = gather_data(args)
    if args.json:
        print(
            json.dumps(
                [
                    {
                        "dtype": r[0],
                        "largest_layer_bytes": r[1],
                        "total_bytes": r[2],
                        "training_bytes": r[3],
                        "training_hbm_bytes_with_optimizer_offload": r[4],
                        "idle_hbm_bytes_with_param_and_optimizer_offload": r[5],
                    }
                    for r in rows
                ]
            )
        )
        return
    header = ["dtype", "Largest Layer", "Total Size", "Training (Adam)",
              "w/ opt. offload", "idle w/ full offload"]
    widths = [10, 16, 16, 18, 17, 20]
    line = "".join(h.ljust(w) for h, w in zip(header, widths))
    print(f"Memory usage for `{args.model_name}`:\n{line}\n{'-' * len(line)}")
    for dtype, largest, total, training, offloaded, idle_full in rows:
        print(
            f"{dtype.ljust(widths[0])}{_fmt(largest).ljust(widths[1])}"
            f"{_fmt(total).ljust(widths[2])}{_fmt(training).ljust(widths[3])}"
            f"{_fmt(offloaded).ljust(widths[4])}{_fmt(idle_full).ljust(widths[5])}"
        )
    print(
        "(idle w/ full offload = between-step HBM with cpu_offload=True + "
        "offload_optimizer=True; in-step peak stays ~'w/ opt. offload')"
    )


def main():
    args = estimate_command_parser().parse_args()
    estimate_command(args)


if __name__ == "__main__":
    main()
