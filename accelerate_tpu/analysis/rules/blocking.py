"""blocking-in-hot-loop: per-iteration host synchronization in step loops.

``x.block_until_ready()`` inside a training loop serializes host and device
— the async dispatch queue (the thing hiding all python overhead between
step launches) drains to depth 0 every iteration.  Legitimate uses are
profiling/benchmark timers, so calls under an ``if`` whose condition
mentions profiling/debug knobs, or inside functions whose name says
bench/profile/warmup, are exempt.

Direct calls are matched syntactically; *indirect* ones come from the
whole-program blocking closure (``program.ProgramGraph``): a loop body
calling ``utils.sync_all(x)`` where ``sync_all`` — in another module —
unconditionally hits ``block_until_ready`` is the same per-step sync, and
is flagged with the chain that proves it.

**Profiler-session extension**: ``jax.profiler.start_trace``/``stop_trace``
inside a step loop is *worse* than a bare sync — each iteration opens a
global trace session, blocks the pipeline, and writes a dump to disk.  A
plain profiling-knob guard (``if profiling:``) does NOT exempt it: the knob
turns every-step tracing on, which is exactly the hazard.  What exempts it
is **sampled-cadence evidence** in a guarding condition — a modulus test
(``step % profile_every_n == 0``) or a cadence-named predicate
(``should_sample``/``every_n``/...) — the pattern the telemetry profiler's
``profile_every_n`` knob implements (docs/telemetry.md).
"""

from __future__ import annotations

import ast
import re

from ..callgraph import dotted_name
from ..engine import Finding, GUARD_NAME_RE, Rule, is_guard_expr

_BLOCKING_LEAVES = {"block_until_ready", "effects_barrier"}

# per-iteration trace sessions: flagged in loops unless a guarding
# condition carries sampled-cadence evidence (a knob guard alone is not it)
_PROFILER_SESSION_LEAVES = {"start_trace", "stop_trace"}

_CADENCE_NAME_RE = re.compile(
    r"every_n|_every\b|every_|sampl|cadence|interval",
    re.IGNORECASE,
)


def is_cadence_expr(test: ast.AST) -> bool:
    """True when a guard condition shows sampled-cadence evidence: a
    modulus test (``i % n == 0``) or a cadence-named knob/predicate."""
    for node in ast.walk(test):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
            return True
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name and _CADENCE_NAME_RE.search(name):
            return True
    return False


class _LoopVisitor(ast.NodeVisitor):
    def __init__(self, rule, module, fn_qual, blocking_callables):
        self.rule = rule
        self.module = module
        self.fn_qual = fn_qual
        self.blocking_callables = blocking_callables  # visible name -> chain
        self.loop_depth = 0
        self.guard_depth = 0
        self.cadence_depth = 0
        self.findings: list[Finding] = []

    def visit_For(self, node):
        # the iterable expression evaluates once, outside the hot body
        self.visit(node.iter)
        self.loop_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        self.loop_depth -= 1
        for stmt in node.orelse:
            self.visit(stmt)

    def visit_While(self, node):
        # unlike For.iter, the While test re-evaluates EVERY iteration — a
        # blocking call in the condition is a per-step sync too
        self.loop_depth += 1
        self.visit(node.test)
        for stmt in node.body:
            self.visit(stmt)
        self.loop_depth -= 1
        for stmt in node.orelse:
            self.visit(stmt)

    def visit_If(self, node):
        self.visit(node.test)
        guarded = is_guard_expr(node.test)
        cadenced = is_cadence_expr(node.test)
        self.guard_depth += guarded
        self.cadence_depth += cadenced
        for stmt in node.body:
            self.visit(stmt)
        self.guard_depth -= guarded
        self.cadence_depth -= cadenced
        for stmt in node.orelse:
            self.visit(stmt)

    def visit_Call(self, node):
        if self.loop_depth > 0 and self.cadence_depth == 0:
            # profiler sessions first: a profiling-knob guard (which exempts
            # plain syncs below) deliberately does NOT exempt these — an
            # `if profiling:` knob is what turns the every-step session ON
            fn = node.func
            leaf_attr = fn.attr if isinstance(fn, ast.Attribute) else None
            resolved_name = self.module.resolve(fn) or ""
            resolved_leaf = resolved_name.rsplit(".", 1)[-1]
            if (
                leaf_attr in _PROFILER_SESSION_LEAVES
                or resolved_leaf in _PROFILER_SESSION_LEAVES
            ):
                self.findings.append(
                    Finding(
                        self.rule.id,
                        self.module.rel_path,
                        node.lineno,
                        node.col_offset,
                        f"{leaf_attr or resolved_leaf}() inside a loop opens a "
                        "profiler trace session every iteration — sample it "
                        "(e.g. `if step % profile_every_n == 0:`) so only the "
                        "sampled step pays the sync+dump",
                        symbol=self.fn_qual,
                    )
                )
        if self.loop_depth > 0 and self.guard_depth == 0:
            fn = node.func
            resolved = self.module.resolve(fn) or ""
            leaf = resolved.rsplit(".", 1)[-1]
            is_blocking = leaf in _BLOCKING_LEAVES or (
                isinstance(fn, ast.Attribute) and fn.attr in _BLOCKING_LEAVES
            )
            if is_blocking:
                self.findings.append(
                    Finding(
                        self.rule.id,
                        self.module.rel_path,
                        node.lineno,
                        node.col_offset,
                        f"{leaf}() inside a loop drains the async dispatch queue "
                        "every iteration — gate it behind a profiling flag or "
                        "sync once after the loop",
                        symbol=self.fn_qual,
                    )
                )
            else:
                callee = (
                    fn.id if isinstance(fn, ast.Name) else (dotted_name(fn) or "")
                )
                chain = self.blocking_callables.get(callee)
                if chain is not None:
                    self.findings.append(
                        Finding(
                            self.rule.id,
                            self.module.rel_path,
                            node.lineno,
                            node.col_offset,
                            f"'{callee}()' blocks every iteration of this loop "
                            f"({chain}) — gate it behind a profiling flag or "
                            "sync once after the loop",
                            symbol=self.fn_qual,
                        )
                    )
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested defs are scanned as their own functions

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef


class BlockingInHotLoop(Rule):
    id = "blocking-in-hot-loop"
    description = (
        "block_until_ready/effects_barrier inside a step loop outside a "
        "profiling guard (direct, or through a helper in any module); "
        "jax.profiler start/stop_trace inside a loop without sampled-"
        "cadence evidence"
    )
    kind = "reachability"
    fix_hint = (
        "sync once after the loop, or gate the barrier behind a sampled "
        "profiling cadence (step % PROFILE_EVERY == 0)"
    )

    def check(self, module, ctx):
        blocking_callables = ctx.blocking_aliases.get(module.rel_path, {})
        findings = []
        for info in module.callgraph.functions.values():
            if GUARD_NAME_RE.search(info.name):
                continue  # bench/profiling helpers sync on purpose
            v = _LoopVisitor(self, module, info.qualname, blocking_callables)
            for stmt in info.node.body:
                v.visit(stmt)
            findings.extend(v.findings)
        return findings
