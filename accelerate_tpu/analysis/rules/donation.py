"""donation-reuse: reading a buffer after handing it to ``donate_argnums``.

Donation aliases the input buffer to an output — after the call the python
reference points at freed/overwritten device memory.  JAX only *warns* (and
only sometimes), the read returns garbage or raises much later.  The rule
tracks, per function body and in execution order, names passed at donated
positions of a known donating callable; any later read before a rebind is
flagged.

Loop bodies get a second pass: a read that *precedes* the donation in source
order is fine on iteration 1 but reads a dead buffer on iteration 2 unless
the name was rebound in between — the scanner visits each loop body twice
(with the loop-carried donation state) and deduplicates against the linear
findings, so straight-line reuse is reported once and loop-carried reuse is
caught at all.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..engine import Finding, Rule

_JIT_LEAVES = {"jit", "pjit"}


def _donated_positions(call: ast.Call) -> Optional[list[int]]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            out = [
                e.value
                for e in elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)
            ]
            return out or None
    return None


def _donating_callables(module) -> dict[str, list[int]]:
    """name -> donated positions, for `g = jax.jit(f, donate_argnums=...)`
    assignments and `@partial(jax.jit, donate_argnums=...)` decorated defs."""
    out: dict[str, list[int]] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            resolved = module.resolve(node.value.func) or ""
            if resolved.rsplit(".", 1)[-1] in _JIT_LEAVES:
                pos = _donated_positions(node.value)
                if pos:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out[t.id] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                resolved = module.resolve(dec.func) or ""
                leaf = resolved.rsplit(".", 1)[-1]
                is_jit_factory = leaf in _JIT_LEAVES
                is_partial_jit = leaf == "partial" and any(
                    (module.resolve(a) or "").rsplit(".", 1)[-1] in _JIT_LEAVES
                    for a in dec.args
                )
                if is_jit_factory or is_partial_jit:
                    pos = _donated_positions(dec)
                    if pos:
                        out[node.name] = pos
    return out


class _LinearScanner(ast.NodeVisitor):
    """Emit (use/store/donate) events in approximate execution order; the
    default field order of Assign (targets before value) is the one place
    AST order disagrees with evaluation order, so it's special-cased."""

    def __init__(self, rule, module, fn_qual, donors):
        self.rule = rule
        self.module = module
        self.fn_qual = fn_qual
        self.donors = donors
        self.dead: dict[str, tuple[str, int]] = {}  # name -> (donor, lineno)
        self.findings: list[Finding] = []

    def visit_Assign(self, node):
        self.visit(node.value)
        for t in node.targets:
            self.visit(t)

    def visit_AugAssign(self, node):
        self.visit(node.value)
        # target is read-then-write: the read part sees the donated state
        if isinstance(node.target, ast.Name):
            self._use(node.target, node.target.id)
            self.dead.pop(node.target.id, None)
        else:
            self.visit(node.target)

    def visit_AnnAssign(self, node):
        if node.value:
            self.visit(node.value)
        self.visit(node.target)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self._use(node, node.id)
        else:  # Store/Del rebinds the name away from the dead buffer
            self.dead.pop(node.id, None)

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in self.donors:
            for arg in node.args:
                self.visit(arg)
            for kw in node.keywords:
                self.visit(kw.value)
            for pos in self.donors[fn.id]:
                if pos < len(node.args) and isinstance(node.args[pos], ast.Name):
                    self.dead[node.args[pos].id] = (fn.id, node.lineno)
        else:
            self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested defs: separate scope, scanned separately

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    # -- loop bodies: second pass ------------------------------------------
    # A read BEFORE the donation in source order is fine on iteration 1 but
    # reads freed memory on iteration 2 unless the name was rebound; walking
    # the body twice with the carried `dead` state is exactly iteration-2
    # semantics.  Duplicate straight-line findings (same line, re-reported by
    # the second pass) are dropped in DonationReuse.check.
    def visit_For(self, node):
        self.visit(node.iter)
        self.visit(node.target)
        for _ in range(2):
            for stmt in node.body:
                self.visit(stmt)
            self.visit(node.target)  # re-bound from the iterator each pass
        for stmt in node.orelse:
            self.visit(stmt)

    visit_AsyncFor = visit_For

    def visit_While(self, node):
        for _ in range(2):
            self.visit(node.test)
            for stmt in node.body:
                self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)

    def _use(self, node, name):
        if name in self.dead:
            donor, _line = self.dead.pop(name)  # report once per donation
            self.findings.append(
                Finding(
                    self.rule.id,
                    self.module.rel_path,
                    node.lineno,
                    node.col_offset,
                    # no line numbers in the message: it feeds the baseline
                    # fingerprint, which must survive unrelated line drift
                    f"'{name}' is read after being donated to '{donor}' "
                    "(donate_argnums aliases its buffer to an output; "
                    "rebind the result or drop the donation)",
                    symbol=self.fn_qual,
                )
            )


class DonationReuse(Rule):
    id = "donation-reuse"
    description = "buffer read after appearing at a donate_argnums position"

    def check(self, module, ctx):
        donors = _donating_callables(module)
        if not donors:
            return []
        findings = []
        for info in module.callgraph.functions.values():
            scanner = _LinearScanner(self, module, info.qualname, donors)
            for stmt in info.node.body:
                scanner.visit(stmt)
            findings.extend(scanner.findings)
        # module top level
        scanner = _LinearScanner(self, module, "<module>", donors)
        for stmt in module.tree.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                scanner.visit(stmt)
        findings.extend(scanner.findings)
        # the loop second pass re-reports straight-line reuse at the same
        # location; keep the first occurrence only
        seen: set = set()
        unique = []
        for f in findings:
            if f not in seen:
                seen.add(f)
                unique.append(f)
        return unique
