#!/usr/bin/env python
"""sarif_check — structural validator for graftlint's SARIF output.

    python tools/graftlint.py pkg/ --format sarif | python tools/sarif_check.py
    python tools/sarif_check.py report.sarif
    python tools/sarif_check.py --self-test

Checks the shape CI consumers (GitHub code scanning et al.) actually rely
on: schema/version headers, the tool.driver rule table, and for every
result a rule id that the driver declares, a level, a message and a
1-based region.  Pure stdlib — no jsonschema dependency, mirroring the
linter's own zero-dependency rule.

``--self-test`` is the end-to-end smoke: write a known-bad fixture to a
temp dir, run graftlint --format sarif on it via a subprocess, require
exit 1, validate the document, and require at least one result whose
message carries a fix hint.

Exit codes: 0 valid, 1 structural problem(s), 2 usage error.
"""

import json
import os
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SARIF_VERSION = "2.1.0"


def validate(doc) -> list:
    """Return a list of human-readable structural problems (empty = valid)."""
    errors = []
    if not isinstance(doc, dict):
        return ["top level is not a JSON object"]
    if doc.get("version") != SARIF_VERSION:
        errors.append(f"version is {doc.get('version')!r}, want {SARIF_VERSION!r}")
    if not str(doc.get("$schema", "")).startswith("http"):
        errors.append("$schema missing or not a URL")
    runs = doc.get("runs")
    if not isinstance(runs, list) or not runs:
        return errors + ["runs missing or empty"]
    for ri, run in enumerate(runs):
        where = f"runs[{ri}]"
        driver = (run.get("tool") or {}).get("driver")
        if not isinstance(driver, dict):
            errors.append(f"{where}: tool.driver missing")
            continue
        if not driver.get("name"):
            errors.append(f"{where}: tool.driver.name missing")
        declared = set()
        for di, rule in enumerate(driver.get("rules") or []):
            rwhere = f"{where}.tool.driver.rules[{di}]"
            rid = rule.get("id")
            if not rid:
                errors.append(f"{rwhere}: id missing")
                continue
            declared.add(rid)
            if not (rule.get("shortDescription") or {}).get("text"):
                errors.append(f"{rwhere}: shortDescription.text missing")
        for si, res in enumerate(run.get("results") or []):
            swhere = f"{where}.results[{si}]"
            rid = res.get("ruleId")
            if not rid:
                errors.append(f"{swhere}: ruleId missing")
            elif rid not in declared:
                errors.append(f"{swhere}: ruleId {rid!r} not declared by the driver")
            if res.get("level") not in ("error", "warning", "note"):
                errors.append(f"{swhere}: level {res.get('level')!r} invalid")
            if not (res.get("message") or {}).get("text"):
                errors.append(f"{swhere}: message.text missing")
            locs = res.get("locations") or []
            if not locs:
                errors.append(f"{swhere}: locations missing")
                continue
            phys = (locs[0] or {}).get("physicalLocation") or {}
            if not (phys.get("artifactLocation") or {}).get("uri"):
                errors.append(f"{swhere}: artifactLocation.uri missing")
            region = phys.get("region") or {}
            if not isinstance(region.get("startLine"), int) or region["startLine"] < 1:
                errors.append(f"{swhere}: region.startLine missing or < 1")
    return errors


_SELF_TEST_BAD = """\
from accelerate_tpu.utils import telemetry


def autoscale(fleet):
    record = telemetry.serving_signal()
    if record and record.get("queue_depth", 0) > 8:
        fleet.resize(2)
"""


def self_test() -> int:
    """End-to-end: graftlint --format sarif on a known-bad fixture must exit
    1, produce a valid document, and carry a fix hint in the message."""
    graftlint = os.path.join(_REPO, "tools", "graftlint.py")
    with tempfile.TemporaryDirectory(prefix="sarif_check_") as tmp:
        bad = os.path.join(tmp, "bad_resize.py")
        with open(bad, "w") as fh:
            fh.write(_SELF_TEST_BAD)
        proc = subprocess.run(
            [sys.executable, graftlint, tmp, "--format", "sarif"],
            capture_output=True,
            text=True,
        )
    if proc.returncode != 1:
        print(
            f"sarif_check: self-test expected graftlint exit 1, got "
            f"{proc.returncode}\n{proc.stderr}",
            file=sys.stderr,
        )
        return 1
    try:
        doc = json.loads(proc.stdout)
    except ValueError as e:
        print(f"sarif_check: self-test output is not JSON: {e}", file=sys.stderr)
        return 1
    errors = validate(doc)
    results = doc["runs"][0].get("results", []) if not errors else []
    if not errors and not results:
        errors.append("self-test fixture produced no results")
    if not errors and not any(
        "fix:" in r["message"]["text"] for r in results
    ):
        errors.append("no result message carries a fix hint")
    for e in errors:
        print(f"sarif_check: self-test: {e}", file=sys.stderr)
    if not errors:
        print(f"sarif_check: self-test ok ({len(results)} result(s))")
    return 1 if errors else 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv == ["--self-test"]:
        return self_test()
    if len(argv) > 1:
        print(__doc__, file=sys.stderr)
        return 2
    try:
        if argv:
            with open(argv[0]) as fh:
                doc = json.load(fh)
        else:
            doc = json.load(sys.stdin)
    except (OSError, ValueError) as e:
        print(f"sarif_check: cannot read document: {e}", file=sys.stderr)
        return 2
    errors = validate(doc)
    for e in errors:
        print(f"sarif_check: {e}", file=sys.stderr)
    if not errors:
        n = sum(len(run.get("results", [])) for run in doc["runs"])
        print(f"sarif_check: ok ({n} result(s))")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
