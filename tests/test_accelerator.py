import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator
from accelerate_tpu.nn import F, Tensor
from accelerate_tpu.optimizer import AcceleratedOptimizer
from accelerate_tpu.scheduler import AcceleratedScheduler
from accelerate_tpu.data_loader import DataLoaderShard


@pytest.fixture(autouse=True)
def _fresh():
    nn.manual_seed(0)
    yield
    Accelerator._reset_state()


def make_regression_data(n=64, in_dim=4, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(in_dim,))
    x = rng.normal(size=(n, in_dim)).astype(np.float32)
    y = (x @ w + 0.1 * rng.normal(size=n)).astype(np.float32)
    return [{"x": x[i], "y": y[i]} for i in range(n)]


def test_prepare_returns_wrapped_objects():
    acc = Accelerator()
    model = nn.Linear(4, 1)
    opt = optim.AdamW(model.parameters(), lr=1e-2)
    sched = optim.LambdaLR(opt, lambda s: 1.0)
    data = make_regression_data()
    model, opt, dl, sched = acc.prepare(model, opt, data and acc.prepare_data_loader(
        __import__("accelerate_tpu").prepare_data_loader(dataset=data, batch_size=2)
    ), sched)
    assert isinstance(opt, AcceleratedOptimizer)
    assert isinstance(sched, AcceleratedScheduler)
    assert isinstance(dl, DataLoaderShard)
    # params now replicated global arrays on the mesh
    assert isinstance(model.weight.data, jax.Array)
    assert len(model.weight.data.sharding.device_set) == 8


def test_end_to_end_training_eager_converges():
    acc = Accelerator()
    model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = optim.AdamW(model.parameters(), lr=1e-2)
    dl = acc.prepare_data_loader(
        __import__("accelerate_tpu").prepare_data_loader(
            dataset=make_regression_data(), batch_size=2, shuffle=True
        )
    )
    model, opt = acc.prepare(model, opt)
    losses = []
    for epoch in range(10):
        for batch in dl:
            opt.zero_grad()
            pred = model(Tensor(batch["x"])).squeeze(-1)
            loss = F.mse_loss(pred, Tensor(batch["y"]))
            acc.backward(loss)
            opt.step()
            losses.append(float(loss.item()))
    assert losses[-1] < losses[0] * 0.2


def test_gradient_accumulation_semantics():
    """Accumulated micro-steps must produce the same update as one big batch."""
    data_x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    data_y = np.random.default_rng(1).normal(size=(8,)).astype(np.float32)

    def run(accum_steps, micro):
        Accelerator._reset_state()
        nn.manual_seed(0)
        acc = Accelerator(gradient_accumulation_steps=accum_steps)
        model = nn.Linear(4, 1)
        opt = acc.prepare(optim.SGD(model.parameters(), lr=0.1))
        acc.prepare_model(model)
        n = len(data_x) // micro
        for i in range(n):
            with acc.accumulate(model):
                xb = data_x[i * micro : (i + 1) * micro]
                yb = data_y[i * micro : (i + 1) * micro]
                pred = model(Tensor(jnp.asarray(xb))).squeeze(-1)
                loss = F.mse_loss(pred, Tensor(jnp.asarray(yb)))
                acc.backward(loss)
                opt.step()
                opt.zero_grad()
        return np.asarray(model.weight.data)

    w_accum = run(4, 2)  # 4 micro-batches of 2
    w_big = run(1, 8)  # one batch of 8
    np.testing.assert_allclose(w_accum, w_big, rtol=1e-5, atol=1e-6)


def test_no_sync_context():
    acc = Accelerator()
    model = nn.Linear(2, 1)
    opt = acc.prepare(optim.SGD(model.parameters(), lr=0.1))
    before = np.asarray(model.weight.data).copy()
    with acc.no_sync(model):
        pred = model(Tensor(jnp.ones((2, 2))))
        acc.backward(pred.sum())
        opt.step()
    np.testing.assert_array_equal(model.weight.data, before)


def test_clip_grad_norm():
    acc = Accelerator()
    model = nn.Linear(2, 1)
    acc.prepare_model(model)
    model.weight.grad = jnp.full((1, 2), 30.0)
    model.bias.grad = jnp.full((1,), 40.0)
    norm = acc.clip_grad_norm_(model.parameters(), max_norm=1.0)
    assert float(norm) == pytest.approx(np.sqrt(30**2 * 2 + 40**2), rel=1e-4)
    new_norm = np.sqrt(
        (np.asarray(model.weight.grad) ** 2).sum() + (np.asarray(model.bias.grad) ** 2).sum()
    )
    assert new_norm == pytest.approx(1.0, rel=1e-3)


def test_mixed_precision_bf16_params_and_master():
    acc = Accelerator(mixed_precision="bf16")
    model = nn.Linear(4, 4)
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)
    assert model.weight.dtype == jnp.bfloat16
    pred = model(Tensor(jnp.ones((2, 4), dtype=jnp.bfloat16)))
    acc.backward(pred.sum())
    opt.step()
    # master weights stay fp32 inside the optimizer
    assert opt.optimizer.master_params[0].dtype == jnp.float32
    assert model.weight.dtype == jnp.bfloat16


def test_compile_step_matches_eager():
    data = make_regression_data(n=16)
    x = np.stack([d["x"] for d in data])
    y = np.stack([d["y"] for d in data])

    def run(use_capture):
        Accelerator._reset_state()
        nn.manual_seed(0)
        acc = Accelerator()
        model = nn.Linear(4, 1)
        opt = optim.SGD(model.parameters(), lr=0.05)
        model, opt = acc.prepare(model, opt)

        def step_fn(xb, yb):
            opt.zero_grad()
            pred = model(Tensor(xb)).squeeze(-1)
            loss = F.mse_loss(pred, Tensor(yb))
            acc.backward(loss)
            opt.step()
            return loss

        step = acc.compile_step(step_fn) if use_capture else step_fn
        losses = []
        for i in range(8):
            loss = step(jnp.asarray(x), jnp.asarray(y))
            losses.append(float(loss.item() if hasattr(loss, "item") else loss))
        return losses, np.asarray(model.weight.data)

    eager_losses, eager_w = run(False)
    cap_losses, cap_w = run(True)
    np.testing.assert_allclose(cap_losses, eager_losses, rtol=1e-4)
    np.testing.assert_allclose(cap_w, eager_w, rtol=1e-4)


def test_compile_step_with_scheduler():
    Accelerator._reset_state()
    acc = Accelerator()
    model = nn.Linear(2, 1)
    opt = optim.SGD(model.parameters(), lr=1.0)
    sched = optim.LambdaLR(opt, lambda s: 1.0 / (s + 1))
    model, opt, sched = acc.prepare(model, opt, sched)

    def step_fn(xb):
        opt.zero_grad()
        loss = model(Tensor(xb)).sum()
        acc.backward(loss)
        opt.step()
        sched.step()
        return loss

    step = acc.compile_step(step_fn)
    step(jnp.ones((2, 2)))
    lr_after_1 = float(opt.optimizer.lr)
    step(jnp.ones((2, 2)))
    lr_after_2 = float(opt.optimizer.lr)
    # scheduler stepped 8× per call (8 shards): lr = 1/(8k+1)
    assert lr_after_1 == pytest.approx(1.0 / 9)
    assert lr_after_2 == pytest.approx(1.0 / 17)


def _run_accum_loop(accum_steps, micro, n_samples, capture, with_scheduler=False):
    """Drive the reference's canonical accumulate loop, optionally captured."""
    data_x = np.random.default_rng(0).normal(size=(n_samples, 4)).astype(np.float32)
    data_y = np.random.default_rng(1).normal(size=(n_samples,)).astype(np.float32)
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(gradient_accumulation_steps=accum_steps)
    model = nn.Linear(4, 1)
    opt = optim.SGD(model.parameters(), lr=0.1)
    sched = optim.LambdaLR(opt, lambda s: 1.0 / (s + 1)) if with_scheduler else None
    if sched is not None:
        model, opt, sched = acc.prepare(model, opt, sched)
    else:
        model, opt = acc.prepare(model, opt)

    def step_fn(xb, yb):
        # the reference's UNMODIFIED canonical loop body (accelerator.py:1116)
        with acc.accumulate(model):
            pred = model(Tensor(xb)).squeeze(-1)
            loss = F.mse_loss(pred, Tensor(yb))
            acc.backward(loss)
            opt.step()
            if sched is not None:
                sched.step()
            opt.zero_grad()
        return loss

    step = acc.compile_step(step_fn) if capture else step_fn
    losses = []
    for i in range(n_samples // micro):
        xb = jnp.asarray(data_x[i * micro : (i + 1) * micro])
        yb = jnp.asarray(data_y[i * micro : (i + 1) * micro])
        losses.append(float(step(xb, yb)))
    return losses, np.asarray(model.weight.data), float(opt.optimizer.lr)


def test_accumulate_inside_compile_step_matches_eager():
    """`with accelerator.accumulate(model):` INSIDE the captured body must
    reproduce the eager loop exactly — including the trailing half-finished
    accumulation window (7 micro-steps, num_steps=3: two updates + one
    pending micro-grad)."""
    eager = _run_accum_loop(3, 2, 14, capture=False)
    captured = _run_accum_loop(3, 2, 14, capture=True)
    np.testing.assert_allclose(captured[0], eager[0], rtol=1e-4)
    np.testing.assert_allclose(captured[1], eager[1], rtol=1e-4)


def test_accumulate_inside_compile_step_scheduler_parity():
    """Scheduler inside the captured accumulate body steps only at sync
    boundaries, same as eager."""
    eager = _run_accum_loop(2, 2, 8, capture=False, with_scheduler=True)
    captured = _run_accum_loop(2, 2, 8, capture=True, with_scheduler=True)
    assert captured[2] == pytest.approx(eager[2])
    np.testing.assert_allclose(captured[1], eager[1], rtol=1e-4)


def test_accumulate_outside_captured_call_still_works():
    """The previously-documented pattern (accumulate wrapping the captured
    call) must behave identically to putting it inside."""
    data_x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    data_y = np.random.default_rng(1).normal(size=(8,)).astype(np.float32)
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(gradient_accumulation_steps=2)
    model = nn.Linear(4, 1)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(xb, yb):
        pred = model(Tensor(xb)).squeeze(-1)
        loss = F.mse_loss(pred, Tensor(yb))
        acc.backward(loss)
        opt.step()
        opt.zero_grad()
        return loss

    step = acc.compile_step(step_fn)
    for i in range(4):
        with acc.accumulate(model):
            step(jnp.asarray(data_x[i * 2 : (i + 1) * 2]), jnp.asarray(data_y[i * 2 : (i + 1) * 2]))
    w_outside = np.asarray(model.weight.data)
    inside = _run_accum_loop(2, 2, 8, capture=True)
    np.testing.assert_allclose(w_outside, inside[1], rtol=1e-4)


def test_accumulate_variant_disagreement_raises():
    """A body that accumulates only in SOME trace variants (e.g. behind a
    training-mode branch) must fail loudly, not silently corrupt the
    micro-step schedule (round-4 review finding)."""
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(gradient_accumulation_steps=2)
    model = nn.Linear(4, 1)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(xb):
        if model.training:
            with acc.accumulate(model):
                loss = model(Tensor(xb)).sum()
                acc.backward(loss)
                opt.step()
                opt.zero_grad()
            return loss
        return model(Tensor(xb)).sum()

    step = acc.compile_step(step_fn)
    model.eval()
    step(jnp.ones((2, 4)))  # first trace: no accumulate
    model.train()
    with pytest.raises(RuntimeError, match="accumulate"):
        step(jnp.ones((2, 4)))


def test_double_accumulate_in_captured_body_raises():
    """Two accumulate blocks in one captured body would bake a single
    sync_gradients value into a program eager advances twice — loud error
    (round-4 review finding)."""
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(gradient_accumulation_steps=2)
    model = nn.Linear(4, 1)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(xa, xb):
        for xv in (xa, xb):
            with acc.accumulate(model):
                loss = model(Tensor(xv)).sum()
                acc.backward(loss)
                opt.step()
                opt.zero_grad()
        return loss

    step = acc.compile_step(step_fn)
    with pytest.raises(RuntimeError, match="more than"):
        step(jnp.ones((2, 4)), jnp.zeros((2, 4)))


def test_gather_for_metrics_object_path_truncates_remainder():
    """The object-list path must slice the flattened list itself (reference
    accelerator.py:2659); per-leaf truncation is a no-op on strings."""
    Accelerator._reset_state()
    acc = Accelerator()

    class _TailDL:  # duck-typed loader at its uneven tail
        end_of_dataloader = True
        remainder = 2

    tail = _TailDL()
    acc.gradient_state._add_dataloader(tail)
    try:
        out = acc.gather_for_metrics(["a", "b", "c", "d"], use_gather_object=True)
        assert out == ["a", "b"]
    finally:
        acc.gradient_state._remove_dataloader(tail)


def test_fp16_clip_unscales_first():
    """clip_grad_norm_ under fp16 must divide the loss scale out BEFORE
    measuring the norm (reference clips after unscale_gradients,
    accelerator.py:2450/2485) — and step must not divide again."""
    from accelerate_tpu.utils.dataclasses import GradScalerKwargs

    Accelerator._reset_state()
    nn.manual_seed(0)
    # small init scale: grad x default 65536 would overflow fp16 itself
    acc = Accelerator(
        mixed_precision="fp16",
        kwargs_handlers=[GradScalerKwargs(init_scale=1024.0)],
    )
    model = nn.Linear(4, 1)
    opt = optim.SGD(model.parameters(), lr=1.0)
    model, opt = acc.prepare(model, opt)
    assert acc.scaler is not None and float(acc.scaler.scale) > 1.0

    before = np.asarray(model.weight.data, dtype=np.float32).copy()
    loss = model(Tensor(jnp.ones((2, 4), jnp.float16))).sum()
    acc.backward(loss)  # grads carry the loss scale here
    norm = float(acc.clip_grad_norm_(model.parameters(), max_norm=1e9))
    # the measured norm is the TRUE gradient norm, not scale x norm
    true_norm = np.sqrt(sum(
        (np.asarray(g, dtype=np.float32) ** 2).sum()
        for g in ([np.full((1, 4), 2.0), np.full((1,), 2.0)])
    ))
    assert norm == pytest.approx(true_norm, rel=1e-2), (norm, true_norm)
    # unscaled grads stay fp32: an fp16 round-trip would flush the small
    # gradients loss scaling exists to protect
    assert all(p.grad.dtype == jnp.float32 for p in model.parameters())
    opt.step()
    after = np.asarray(model.weight.data, dtype=np.float32)
    # SGD lr=1: delta == -grad (unscaled exactly once)
    np.testing.assert_allclose(before - after, 2.0, rtol=1e-2)


def test_fp16_unscale_is_noop_mid_accumulation():
    """clip_grad_norm_ every micro-step must not corrupt the accumulation:
    unscaling mid-window would mix scaled and unscaled grads and apply the
    later micro-steps' contributions scale-times too large (round-4 review
    finding)."""
    from accelerate_tpu.utils.dataclasses import GradScalerKwargs

    def run(clip_every_step):
        Accelerator._reset_state()
        nn.manual_seed(0)
        acc = Accelerator(
            mixed_precision="fp16",
            gradient_accumulation_steps=2,
            kwargs_handlers=[GradScalerKwargs(init_scale=1024.0)],
        )
        model = nn.Linear(4, 1)
        opt = optim.SGD(model.parameters(), lr=0.1)
        model, opt = acc.prepare(model, opt)
        for i in range(4):
            with acc.accumulate(model):
                loss = model(Tensor(jnp.ones((2, 4), jnp.float16) * (i + 1))).sum()
                acc.backward(loss)
                if clip_every_step:
                    acc.clip_grad_norm_(model.parameters(), max_norm=1e9)
                opt.step()
                opt.zero_grad()
        return np.asarray(model.weight.data, dtype=np.float32)

    # a huge max_norm never actually clips, so weights must match exactly
    np.testing.assert_allclose(run(True), run(False), rtol=1e-3)


def test_reference_parity_surface():
    """The remaining small reference Accelerator APIs all exist and behave
    (save_iteration, optimizer_step_was_skipped, deepspeed_plugin,
    dataloader passthroughs, on_local_process, trigger_sync_in_backward)."""
    Accelerator._reset_state()
    acc = Accelerator()
    assert acc.save_iteration == 0
    assert acc.deepspeed_plugin is None
    assert acc.optimizer_step_was_skipped is False
    assert acc.split_batches is False and acc.even_batches is True
    assert acc.non_blocking is False and acc.use_stateful_dataloader is False
    assert acc.use_seedable_sampler in (True, False)

    ran = []
    acc.on_local_process(lambda: ran.append(1), local_process_index=0)()
    acc.on_local_process(lambda: ran.append(2), local_process_index=3)()
    assert ran == [1]  # single local process: only index 0 fires

    with acc.no_sync():
        assert acc.sync_gradients is False
        acc.trigger_sync_in_backward()
        assert acc.sync_gradients is True


def test_gather_for_metrics_truncates_remainder():
    import accelerate_tpu

    acc = Accelerator()
    data = [{"x": np.array([float(i)])} for i in range(20)]
    dl = acc.prepare_data_loader(
        accelerate_tpu.prepare_data_loader(dataset=data, batch_size=2)
    )
    seen = []
    for batch in dl:
        gathered = acc.gather_for_metrics(batch["x"])
        seen.extend(np.asarray(gathered)[:, 0].tolist())
    assert sorted(seen) == [float(i) for i in range(20)]


def test_trigger_single_process():
    acc = Accelerator()
    assert not acc.check_trigger()
    acc.set_trigger()
    assert acc.check_trigger()
    assert not acc.check_trigger()


def test_jsonl_tracker(tmp_path):
    acc = Accelerator(log_with="jsonl", project_dir=str(tmp_path))
    acc.init_trackers("run1", config={"lr": 0.1})
    acc.log({"loss": 1.5}, step=0)
    acc.log({"loss": jnp.asarray(0.5)}, step=1)
    acc.end_training()
    import json

    lines = (tmp_path / "run1" / "metrics.jsonl").read_text().strip().split("\n")
    assert len(lines) == 2
    assert json.loads(lines[1])["loss"] == 0.5
    assert json.loads((tmp_path / "run1" / "config.json").read_text())["lr"] == 0.1


def test_save_and_load_state_roundtrip(tmp_path):
    acc = Accelerator()
    model = nn.Linear(4, 2)
    opt = optim.AdamW(model.parameters(), lr=1e-2)
    model, opt = acc.prepare(model, opt)
    # train a step so optimizer state is nontrivial
    loss = model(Tensor(jnp.ones((2, 4)))).sum()
    acc.backward(loss)
    opt.step()
    w_before = np.asarray(model.weight.data).copy()
    acc.save_state(str(tmp_path / "ckpt"))
    # perturb
    model.weight.data = jnp.zeros_like(model.weight.data)
    acc.load_state(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(model.weight.data), w_before, rtol=1e-6)
    # sharding preserved after load
    assert len(model.weight.data.sharding.device_set) == 8


def test_verify_device_map_and_lomo_parity():
    """Reference-API parity: verify_device_map flags dispatched models;
    lomo_backward explains why it has no traced-step counterpart."""
    import pytest

    import accelerate_tpu.nn as nn
    from accelerate_tpu import Accelerator

    Accelerator._reset_state()
    acc = Accelerator()
    model = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 4))
    assert acc.verify_device_map(model) is False
    model.atpu_device_map = {"0": "tpu:0", "1": "cpu"}
    assert acc.verify_device_map(model) is True
    with pytest.raises(NotImplementedError, match="captured step"):
        acc.lomo_backward(None, 1e-3)


def test_prepare_refuses_device_mapped_model():
    """Reference accelerator.py:1338: offload-dispatched models cannot be
    prepared for distributed training."""
    import pytest

    import accelerate_tpu.nn as nn
    from accelerate_tpu import Accelerator

    Accelerator._reset_state()
    acc = Accelerator()
    model = nn.Sequential(nn.Linear(4, 4))
    model.atpu_device_map = {"0": "tpu:0", "1": "cpu"}
    if acc.num_devices > 1:
        with pytest.raises(ValueError, match="device_map"):
            acc.prepare(model)
