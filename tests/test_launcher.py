"""Launcher integration tests (reference Pattern 2/3, SURVEY.md §4).

One true subprocess launch exercises the CLI + env protocol end-to-end; the
other in-package scripts run in-process on the warm 8-device mesh (this CI
box has a single CPU core — every cold subprocess pays full XLA recompiles,
so subprocess fan-out is kept minimal).
"""

import os

import pytest

import accelerate_tpu.test_utils.scripts.test_ops as test_ops_script
import accelerate_tpu.test_utils.scripts.test_script as test_script
import accelerate_tpu.test_utils.scripts.test_sync as test_sync_script
from accelerate_tpu.test_utils.testing import launch_test_script


def test_launch_test_script_via_cli():
    """Full round trip: accelerate-tpu launch → env protocol → child SPMD."""
    env = os.environ.copy()
    env.pop("ACCELERATE_MIXED_PRECISION", None)
    out = launch_test_script(
        test_script.__file__, num_virtual_devices=2, env=env
    )
    assert "All checks passed" in out


def test_ops_script_in_process():
    test_ops_script.main()


def test_sync_script_in_process():
    test_sync_script.main()


def test_script_in_process():
    test_script.main()


def test_debug_launcher_multiprocess():
    """Two real OS processes rendezvous through jax.distributed on CPU
    (reference debug_launcher, launchers.py:268)."""
    from accelerate_tpu.launchers import debug_launcher

    debug_launcher(_check_world, num_processes=2, timeout=240)


def test_debug_launcher_sharded_checkpoint_two_processes():
    """Sharded checkpointing under REAL multi-process: the fsdp axis spans
    two processes, each writes its own model+optimizer shard files, and
    load_state reassembles per-process local blocks (the multihost half of
    tests/test_sharded_checkpoint.py, which is single-process)."""
    import accelerate_tpu.test_utils.scripts.test_sharded_ckpt as script

    from accelerate_tpu.launchers import debug_launcher

    debug_launcher(script.main, num_processes=2, timeout=600)


def test_debug_launcher_full_script_two_processes():
    """The FULL correctness suite under real 2-process rendezvous: this is
    the round-2 verdict's Missing #5 — the multihost branches of
    operations.py (gather/broadcast), the per-process slice assembly in
    batch_to_global_array, multi-process checkpoint save/load, and the
    captured train step all execute with num_processes > 1 (reference
    Pattern 3, tests/test_grad_sync.py:36-40 runs test_script the same way).
    This exact exercise caught the double-batch bug where every process fed
    the full global batch as its local shard."""
    from accelerate_tpu.launchers import debug_launcher

    debug_launcher(test_script.main, num_processes=2, timeout=600)


def _check_world():
    # PartialState() performs the jax.distributed rendezvous from the env
    # protocol — it must come before any process_count() query
    from accelerate_tpu import PartialState

    state = PartialState()
    assert state.num_processes == 2, f"got {state.num_processes} processes"
    import jax

    assert jax.process_count() == 2
    state.wait_for_everyone()
