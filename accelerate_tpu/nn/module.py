"""Torch-like Module system over JAX arrays.

Gives reference-style imperative ergonomics (``model(x)``, ``state_dict()``,
``named_parameters()``) while staying purely functional underneath: parameters
are a flat ``{dotted.path: jax.Array}`` pytree that can be swapped wholesale
(`_functional_call`) — which is what lets ``Accelerator`` jit the user's whole
loop body and shard params on the mesh without the user noticing.

The reference manipulates torch ``nn.Module``s it does not own
(accelerator.py:1421 prepare_model); here the module system is ours, so
"prepare" is a re-binding of ``.data`` arrays (device_put with shardings)
rather than a wrapper-module dance.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .tape import Tensor, no_grad


class Parameter(Tensor):
    """A Tensor registered as a learnable leaf of a Module."""

    def __init__(self, data, requires_grad: bool = True):
        from .meta import MetaArray, is_meta, meta_mode_active

        super().__init__(data, requires_grad=requires_grad)
        # init_empty_weights(include_buffers=False): initializers ran for real
        # (buffers need true values); params still come out meta
        if meta_mode_active() and not is_meta(self.data):
            self.data = MetaArray(self.data.shape, self.data.dtype)

    def __repr__(self):
        return f"Parameter(shape={tuple(self.shape)}, dtype={self.dtype})"


class Buffer(Tensor):
    """Non-learnable state (e.g. rotary caches, BN running stats)."""

    def __init__(self, data):
        super().__init__(data, requires_grad=False)


class Module:
    """Base class. Subclasses define ``__init__`` (register params/submodules
    by attribute assignment) and ``forward``."""

    def __init__(self):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_modules", OrderedDict())
        object.__setattr__(self, "training", True)

    # -- registration -------------------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        if isinstance(value, Parameter):
            self._parameters[name] = value
            self._buffers.pop(name, None)
            self._modules.pop(name, None)
        elif isinstance(value, Buffer):
            self._buffers[name] = value
            self._parameters.pop(name, None)
        elif isinstance(value, Module):
            self._modules[name] = value
            self._parameters.pop(name, None)
        object.__setattr__(self, name, value)

    def register_buffer(self, name: str, tensor) -> None:
        buf = tensor if isinstance(tensor, Buffer) else Buffer(tensor)
        setattr(self, name, buf)

    def register_parameter(self, name: str, param: Optional[Parameter]) -> None:
        if param is None:
            self._parameters.pop(name, None)
            object.__setattr__(self, name, None)
        else:
            setattr(self, name, param)

    def add_module(self, name: str, module: "Module") -> None:
        setattr(self, name, module)

    # -- traversal ----------------------------------------------------------
    def named_modules(self, prefix: str = "") -> Iterator[tuple[str, "Module"]]:
        yield prefix, self
        for name, module in self._modules.items():
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from module.named_modules(sub_prefix)

    def modules(self) -> Iterator["Module"]:
        for _, m in self.named_modules():
            yield m

    def get_submodule(self, target: str) -> "Module":
        """Resolve a dotted path like ``encoder.layer.3.attn`` (torch parity)."""
        module = self
        if not target:
            return module
        for part in target.split("."):
            if part not in module._modules:
                raise AttributeError(f"{module!r} has no submodule {part!r}")
            module = module._modules[part]
        return module

    def children(self) -> Iterator["Module"]:
        yield from self._modules.values()

    def named_children(self) -> Iterator[tuple[str, "Module"]]:
        yield from self._modules.items()

    def named_parameters(
        self, prefix: str = "", remove_duplicate: bool = True
    ) -> Iterator[tuple[str, Parameter]]:
        """Tied parameters (one object, several paths) are yielded once by
        default (torch semantics) — critical under step capture: duplicate
        pytree entries would split the tied gradient across two leaves."""
        seen: set[int] = set()
        for mod_name, module in self.named_modules(prefix):
            for name, param in module._parameters.items():
                if remove_duplicate:
                    if id(param) in seen:
                        continue
                    seen.add(id(param))
                yield (f"{mod_name}.{name}" if mod_name else name), param

    def parameters(self) -> Iterator[Parameter]:
        for _, p in self.named_parameters():
            yield p

    def named_buffers(
        self, prefix: str = "", remove_duplicate: bool = True
    ) -> Iterator[tuple[str, Buffer]]:
        seen: set[int] = set()
        for mod_name, module in self.named_modules(prefix):
            for name, buf in module._buffers.items():
                if remove_duplicate:
                    if id(buf) in seen:
                        continue
                    seen.add(id(buf))
                yield (f"{mod_name}.{name}" if mod_name else name), buf

    def buffers(self) -> Iterator[Buffer]:
        for _, b in self.named_buffers():
            yield b

    # -- state dict ---------------------------------------------------------
    def state_dict(self) -> "OrderedDict[str, jax.Array]":
        # tied weights appear under every name (torch state_dict semantics)
        out: OrderedDict[str, jax.Array] = OrderedDict()
        for name, p in self.named_parameters(remove_duplicate=False):
            out[name] = p.data
        for name, b in self.named_buffers(remove_duplicate=False):
            out[name] = b.data
        return out

    def load_state_dict(self, state_dict, strict: bool = True):
        own = dict(self.named_parameters(remove_duplicate=False))
        own.update(dict(self.named_buffers(remove_duplicate=False)))
        missing = [k for k in own if k not in state_dict]
        unexpected = [k for k in state_dict if k not in own]
        if strict and (missing or unexpected):
            raise KeyError(
                f"load_state_dict mismatch: missing={missing}, unexpected={unexpected}"
            )
        for key, value in state_dict.items():
            if key in own:
                target = own[key]
                value = jnp.asarray(value)
                if tuple(value.shape) != tuple(target.shape):
                    raise ValueError(
                        f"shape mismatch for {key}: checkpoint {value.shape} vs "
                        f"model {target.shape}"
                    )
                target.data = value.astype(target.dtype)
        return missing, unexpected

    # -- mode / dtype / device ----------------------------------------------
    def train(self, mode: bool = True) -> "Module":
        for m in self.modules():
            object.__setattr__(m, "training", mode)
        return self

    def eval(self) -> "Module":
        return self.train(False)

    def zero_grad(self, set_to_none: bool = True) -> None:
        for p in self.parameters():
            p.grad = None

    def apply(self, fn: Callable[["Module"], None]) -> "Module":
        for m in self.modules():
            fn(m)
        return self

    def to(self, device_or_dtype=None) -> "Module":
        """Move/cast all params+buffers. Accepts a dtype, Device, or Sharding."""
        import numpy as _np

        from .meta import is_meta

        if device_or_dtype is None:
            return self
        if isinstance(device_or_dtype, (jnp.dtype, _np.dtype, type)) or (
            isinstance(device_or_dtype, str) and not device_or_dtype.startswith(("tpu", "cpu"))
        ):
            dtype = jnp.dtype(device_or_dtype)
            for t in list(self.parameters()) + list(self.buffers()):
                t.data = t.data.astype(dtype)
        else:
            for t in list(self.parameters()) + list(self.buffers()):
                if is_meta(t.data):
                    continue
                t.data = jax.device_put(t.data, device_or_dtype)
        return self

    def astype(self, dtype) -> "Module":
        return self.to(dtype)

    # -- functional bridge --------------------------------------------------
    def param_pytree(self) -> dict[str, jax.Array]:
        """Flat {path: array} of parameters — the functional view."""
        return {name: p.data for name, p in self.named_parameters()}

    def buffer_pytree(self) -> dict[str, jax.Array]:
        return {name: b.data for name, b in self.named_buffers()}

    def bind_params(self, pytree: dict[str, Any]) -> None:
        """Point ``.data`` of each named parameter at ``pytree[name]``.

        This is the re-binding trick behind step capture: bind tracers, run
        the Python forward, collect outputs — the jitted function is pure.
        """
        params = dict(self.named_parameters())
        for name, value in pytree.items():
            params[name].data = value

    def bind_buffers(self, pytree: dict[str, Any]) -> None:
        bufs = dict(self.named_buffers())
        for name, value in pytree.items():
            bufs[name].data = value

    def _functional_call(self, params: dict[str, Any], *args, **kwargs):
        """Pure-ish call: swap params in, run forward, restore."""
        old = self.param_pytree()
        try:
            self.bind_params(params)
            return self(*args, **kwargs)
        finally:
            self.bind_params(old)

    # -- call ---------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def __repr__(self):
        lines = [self.__class__.__name__ + "("]
        for name, module in self._modules.items():
            sub = repr(module).split("\n")
            lines.append(f"  ({name}): " + "\n  ".join(sub))
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else f"{self.__class__.__name__}()"

    @property
    def num_parameters(self) -> int:
        return sum(int(np.prod(p.shape)) for p in self.parameters())


class Sequential(Module):
    def __init__(self, *modules: Module):
        super().__init__()
        for i, m in enumerate(modules):
            self.add_module(str(i), m)

    def forward(self, x):
        for m in self._modules.values():
            x = m(x)
        return x

    def __getitem__(self, idx: int) -> Module:
        return list(self._modules.values())[idx]

    def __len__(self):
        return len(self._modules)


class ModuleList(Module):
    def __init__(self, modules=()):
        super().__init__()
        for i, m in enumerate(modules):
            self.add_module(str(i), m)

    def append(self, module: Module) -> "ModuleList":
        self.add_module(str(len(self._modules)), module)
        return self

    def __iter__(self) -> Iterator[Module]:
        return iter(self._modules.values())

    def __getitem__(self, idx: int) -> Module:
        return list(self._modules.values())[idx]

    def __len__(self):
        return len(self._modules)

    def forward(self, *args, **kwargs):
        raise NotImplementedError("ModuleList is a container; call its items")


class ModuleDict(Module):
    def __init__(self, modules: Optional[dict[str, Module]] = None):
        super().__init__()
        if modules:
            for k, v in modules.items():
                self.add_module(k, v)

    def __getitem__(self, key: str) -> Module:
        return self._modules[key]

    def __setitem__(self, key: str, module: Module) -> None:
        self.add_module(key, module)

    def keys(self):
        return self._modules.keys()

    def items(self):
        return self._modules.items()

    def forward(self, *args, **kwargs):
        raise NotImplementedError("ModuleDict is a container; call its items")
