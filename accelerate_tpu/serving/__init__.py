"""Production decode service: continuous batching + paged KV cache.

The serving half of the system (docs/serving.md).  The single-request
decode engine (models/generation.py) is a first-class captured TPU program
— but one request at a time, one compiled program per geometry.  This
package turns it into a serving path:

* :class:`~.scheduler.DecodeService` — request front end: admission queue,
  continuous batching (sequences join/leave the in-flight batch at step
  boundaries), per-request stop tokens and budgets, TTFT/TPOT accounting,
  ``kind="serving"`` telemetry.
* :mod:`~.kv_blocks` — the block/paged KV cache: one preallocated pool of
  fixed-size blocks + an int32 block table per slot, so wildly different
  sequence lengths share ONE pinned program.
* :mod:`~.engine` — the two captured programs (bucketed prefill, whole-
  batch ``decode_steps``-token decode with in-program token feedback)
  layered on the same ``DecoderFamily`` / ``cached_attention`` /
  ``stacked_params_for_mode`` contracts the one-shot engine uses —
  quantized int8/int4 weight modes and ``shard_for_inference`` layouts
  compose unchanged.
* :mod:`~.recovery` — fault tolerance (docs/serving.md §fault
  tolerance): the bounded request journal (WAL of admissions + emitted
  tokens), deterministic teacher-forced re-prefill recovery, bounded
  decode-dispatch retry, preemption drain, and deadline/queue-depth
  shedding.  Default off; armed by ``ServingConfig(journal_dir=...)`` /
  ``$ACCELERATE_SERVING_JOURNAL``.

Steady state is **zero recompiles** — asserted through the telemetry
recompile forensics (``CompileWatcher``), benched by bench.py's serving
block, and smoke-tested by ``make serve-smoke``.
"""

from .kv_blocks import BlockPool, blocks_for_request, bucket_length, make_pools
from .recovery import QueueFullError, RequestJournal, replay_journal
from .scheduler import DecodeService, Request, ServingConfig

__all__ = [
    "BlockPool",
    "DecodeService",
    "QueueFullError",
    "Request",
    "RequestJournal",
    "ServingConfig",
    "blocks_for_request",
    "bucket_length",
    "make_pools",
    "replay_journal",
]
