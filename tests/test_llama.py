"""Llama-family decoder tests: HF parity, GQA decode, FSDP training.

The family is BASELINE.json config 4 ("FSDP-wrapped Llama-2-7B"); reference
equivalents are the any-module ``prepare_model`` (reference
accelerator.py:1421) and tests/fsdp.  Parity is asserted numerically against
transformers' CPU implementation — same contract as tests/test_torch_bridge.py.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM


def _tiny_hf_pair(seed=0):
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM as HFLlama

    from accelerate_tpu.utils.torch_bridge import convert_torch_module

    torch.manual_seed(seed)
    hf = HFLlama(
        HFConfig(
            vocab_size=1024, hidden_size=128, intermediate_size=256,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=256, rms_norm_eps=1e-5,
            tie_word_embeddings=False,
        )
    ).eval()
    return hf, convert_torch_module(hf)


@pytest.fixture(scope="module")
def hf_pair():
    return _tiny_hf_pair()


def test_forward_parity_vs_transformers(hf_pair):
    hf, ours = hf_pair
    ids = np.random.default_rng(0).integers(0, 1024, (2, 16), dtype=np.int64)
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(ours(jnp.asarray(ids, jnp.int32))["logits"].data)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_gqa_cache_is_kv_head_sized(hf_pair):
    """The decode cache must stay at n_kv_head — the point of GQA at 7B."""
    _, ours = hf_pair
    spec = ours._decoder_spec()
    assert spec.cfg.n_kv_head == 2 and spec.cfg.n_head == 4
    g, layers = spec.stack()
    # k projection emits n_kv_head * head_dim rows, not n_head * head_dim
    assert layers["k_w"].shape[1] == 2 * spec.cfg.head_dim
    assert layers["q_w"].shape[1] == 4 * spec.cfg.head_dim


def test_greedy_generate_matches_full_forward(hf_pair):
    _, ours = hf_pair
    ids = np.random.default_rng(1).integers(0, 1024, (2, 7), dtype=np.int32)
    want = jnp.asarray(ids, jnp.int32)
    for _ in range(5):
        logits = ours(want)["logits"].data
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want = jnp.concatenate([want, nxt[:, None]], axis=1)
    got = ours.generate(ids, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fsdp_training_loss_decreases():
    """Captured train step on a dp×fsdp mesh — the config-4 shape."""
    Accelerator._reset_state()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(fsdp_size=2), mixed_precision="bf16"
    )
    model = LlamaForCausalLM(LlamaConfig.tiny())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    from accelerate_tpu.data_loader import batch_to_global_array

    ids = batch_to_global_array(
        jnp.asarray(
            np.random.default_rng(0).integers(0, 1024, (8, 32)), jnp.int32
        ),
        mesh=acc.mesh,
    )
    losses = [float(step(ids)) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_remat_numerics_identical(monkeypatch):
    """ACCELERATE_TPU_REMAT=1 must change memory, not math: one SGD step
    with and without per-layer checkpointing yields identical params."""
    import accelerate_tpu.optim as optim_mod

    def one_step(remat: bool):
        if remat:
            monkeypatch.setenv("ACCELERATE_TPU_REMAT", "1")
        else:
            monkeypatch.delenv("ACCELERATE_TPU_REMAT", raising=False)
        Accelerator._reset_state()
        nn.manual_seed(0)
        acc = Accelerator(mixed_precision="no")
        model = LlamaForCausalLM(LlamaConfig.tiny())
        opt = optim_mod.SGD(model.parameters(), lr=0.1)
        model, opt = acc.prepare(model, opt)
        ids = batch_to_global_array(
            jnp.asarray(
                np.random.default_rng(0).integers(0, 1024, (8, 32)), jnp.int32
            ),
            mesh=acc.mesh,
        )
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return {n: np.asarray(p.data) for n, p in model.named_parameters()}

    from accelerate_tpu.data_loader import batch_to_global_array

    base = one_step(False)
    remat = one_step(True)
    for name in base:
        np.testing.assert_allclose(remat[name], base[name], rtol=1e-6, atol=1e-7, err_msg=name)


def test_unsupported_config_fields_rejected():
    """Configs whose math we'd silently get wrong must refuse to load."""
    from accelerate_tpu.utils.hf import llama_config_from_hf

    base = {"hidden_size": 128, "num_attention_heads": 4, "vocab_size": 1024}
    # llama3/linear/yarn rope scaling are implemented
    # (tests/test_llama_rope_scaling.py); schemes whose math we don't carry
    # still refuse
    with pytest.raises(NotImplementedError, match="longrope"):
        llama_config_from_hf({**base, "rope_scaling": {"rope_type": "longrope"}})
    with pytest.raises(NotImplementedError, match="attention_bias"):
        llama_config_from_hf({**base, "attention_bias": True})
    with pytest.raises(NotImplementedError, match="mlp_bias"):
        llama_config_from_hf({**base, "mlp_bias": True})


def test_from_pretrained_roundtrip(tmp_path, hf_pair):
    """HF save_pretrained directory → utils/hf.from_pretrained parity."""
    hf, ours = hf_pair
    hf.save_pretrained(tmp_path / "llama")
    from accelerate_tpu.utils.hf import from_pretrained

    loaded = from_pretrained(str(tmp_path / "llama"))
    ids = np.random.default_rng(2).integers(0, 1024, (1, 12), dtype=np.int32)
    a = np.asarray(ours(jnp.asarray(ids))["logits"].data)
    b = np.asarray(loaded(jnp.asarray(ids))["logits"].data)
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_decoupled_head_dim_trains_and_decodes():
    """Mistral-Nemo geometry: explicit head_dim != hidden // heads must
    train, and cached decode must match the forward argmax (the pure math
    derives d from the q weight, not the model width)."""
    from accelerate_tpu.utils.hf import llama_config_from_hf

    cfg = llama_config_from_hf(
        {
            "vocab_size": 512, "hidden_size": 96, "intermediate_size": 192,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "max_position_embeddings": 128,
            "head_dim": 32,  # derived would be 24
        }
    )
    assert cfg.resolved_head_dim == 32
    nn.manual_seed(0)
    model = LlamaForCausalLM(cfg)
    assert model.layers[0].self_attn.q_proj.weight.shape == (4 * 32, 96)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 512, (2, 16)), jnp.int32)
    out = model(ids, labels=ids)
    out["loss"].backward()
    assert all(p.grad is not None for p in model.parameters())
    gen = model.generate(ids[:1], max_new_tokens=1)
    want = int(np.asarray(out["logits"])[0, -1].argmax())
    assert int(np.asarray(gen)[0, -1]) == want
