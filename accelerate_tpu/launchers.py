"""Programmatic launchers: ``notebook_launcher`` + ``debug_launcher``.

Counterpart of ``/root/reference/src/accelerate/launchers.py`` (:40 notebook,
:268 debug).  The reference forks N torch.multiprocessing workers per GPU; on
TPU one SPMD process already drives every local chip, so ``notebook_launcher``
is mostly a guard-railed direct call — multi-worker spawning only exists for
(a) multi-host pods (where each host runs its own notebook anyway) and (b)
the CPU-simulation debug mode, which spawns real OS processes rendezvousing
through ``jax.distributed`` so collective semantics are genuinely exercised
(reference Pattern 3, SURVEY.md §4).
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
import textwrap
from typing import Any, Callable, Optional

from .state import PartialState
from .utils.environment import patch_environment

__all__ = ["notebook_launcher", "debug_launcher"]


def notebook_launcher(
    function: Callable,
    args: tuple = (),
    num_processes: Optional[int] = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    node_rank: int = 0,
    num_nodes: int = 1,
) -> Any:
    """Launch ``function(*args)`` for (notebook) training.

    Reference: notebook_launcher launchers.py:40.  TPU inversion: no per-chip
    fan-out is needed — ``function`` runs once in this process and pjit drives
    all chips.  ``num_processes`` > 1 without TPU hardware falls back to the
    debug (CPU multi-process) path.
    """
    if PartialState._shared_state:
        raise ValueError(
            "An Accelerator/PartialState was already created in this notebook. "
            "Restart the kernel and create it only inside the launched function."
        )
    with patch_environment(ACCELERATE_MIXED_PRECISION=mixed_precision):
        try:
            import jax

            local = jax.local_devices()
            backend = local[0].platform
            n_chips = len(local)
        except Exception:
            backend, n_chips = "cpu", 0
        if backend == "cpu" and num_processes and num_processes > 1:
            return debug_launcher(function, args, num_processes, use_port=use_port)
        print(f"Launching training on {backend} ({n_chips} chips).")
        return function(*args)


_WORKER_TEMPLATE = """\
import os, pickle, sys
os.environ.update({env!r})
with open({payload!r}, "rb") as f:
    function, args = pickle.load(f)
function(*args)
"""


def _free_port() -> str:
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return str(s.getsockname()[1])


def debug_launcher(
    function: Callable,
    args: tuple = (),
    num_processes: int = 2,
    use_port: Optional[str] = None,
    timeout: int = 300,
) -> None:
    """Run ``function`` on N CPU processes with real collective rendezvous.

    Reference: debug_launcher launchers.py:268 (gloo CPU fork).  Spawns fresh
    interpreters (never forks — the JAX backend may already be initialised
    here) that join a jax.distributed coordinator on localhost.  ``function``
    and ``args`` must be picklable (module-level function, as in the
    reference).
    """
    if use_port is None:
        use_port = _free_port()  # fixed ports collide across test runs
    with tempfile.TemporaryDirectory() as td:
        payload = os.path.join(td, "fn.pkl")
        with open(payload, "wb") as f:
            pickle.dump((function, args), f)
        workers = []
        # the worker must be able to unpickle `function`, whose module may
        # only be importable through the parent's sys.path (e.g. a test file)
        pythonpath = os.pathsep.join(
            [p for p in sys.path if p] + [os.environ.get("PYTHONPATH", "")]
        ).strip(os.pathsep)
        for rank in range(num_processes):
            env = {
                "PYTHONPATH": pythonpath,
                "JAX_PLATFORMS": "cpu",
                "ACCELERATE_NUM_PROCESSES": str(num_processes),
                "ACCELERATE_PROCESS_INDEX": str(rank),
                "ACCELERATE_LOCAL_PROCESS_INDEX": str(rank),
                "ACCELERATE_COORDINATOR_ADDRESS": f"127.0.0.1:{use_port}",
            }
            code = _WORKER_TEMPLATE.format(env=env, payload=payload)
            full_env = os.environ.copy()
            full_env.update(env)
            # a TPU PJRT plugin grabbing the one real chip in every worker
            # would break the CPU rendezvous (and the chip is single-client)
            full_env.pop("PALLAS_AXON_POOL_IPS", None)
            workers.append(
                subprocess.Popen([sys.executable, "-c", code], env=full_env)
            )
        try:
            rcs = [w.wait(timeout=timeout) for w in workers]
        except subprocess.TimeoutExpired:
            for w in workers:
                w.kill()
            raise RuntimeError(
                f"debug_launcher workers did not finish within {timeout}s "
                "(rendezvous deadlock?)"
            )
        for rank, rc in enumerate(rcs):
            if rc != 0:
                raise RuntimeError(
                    f"debug_launcher worker {rank} exited with code {rc}"
                )
