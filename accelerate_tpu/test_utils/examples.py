"""Example-diff checker: every ``by_feature`` snippet must appear in the
``complete_*`` example.

Counterpart of the reference's AST/line-level example checker
(test_utils/examples.py:26-146): each feature script is the base example plus
a marked feature; the complete example must textually contain every line the
feature added.  Implemented as normalized line-set subtraction over the
``training_function``/``main`` bodies — comments and blanks are stripped, so
``# New Code #`` markers and doc drift don't produce false diffs.
"""

from __future__ import annotations

import os
from typing import Optional


def extract_function(lines: list[str], name: str) -> list[str]:
    """Return the source lines of top-level ``def name`` up to the next
    top-level statement."""
    out: list[str] = []
    in_fn = False
    for line in lines:
        if not in_fn:
            if line.startswith(f"def {name}"):
                in_fn = True
                out.append(line)
            continue
        # body lines are indented (or blank); a new top-level def/if ends it
        if line.strip() and not line.startswith((" ", "\t", ")")):
            break
        out.append(line)
    return out


def normalize(lines: list[str]) -> set[str]:
    """Strip comments/blanks and whitespace-normalize for set comparison."""
    cleaned = set()
    for line in lines:
        s = line.strip()
        if not s or s.startswith("#"):
            continue
        s = s.split("  # ")[0].strip()  # trailing inline comments
        cleaned.add(s)
    return cleaned


def feature_additions(
    feature_path: str, base_path: str, function: str = "training_function"
) -> set[str]:
    """Lines ``function`` in the feature script adds relative to the base."""
    with open(feature_path) as f:
        feature = f.readlines()
    with open(base_path) as f:
        base = f.readlines()
    return normalize(extract_function(feature, function)) - normalize(
        extract_function(base, function)
    )


def missing_from_complete(
    complete_path: str,
    feature_path: str,
    base_path: str,
    function: str = "training_function",
    ignore: Optional[set[str]] = None,
) -> set[str]:
    """Feature-added lines absent from the complete example (empty == pass)."""
    with open(complete_path) as f:
        complete = normalize(extract_function(f.readlines(), function))
    added = feature_additions(feature_path, base_path, function)
    if ignore:
        added = {line for line in added if line not in ignore}
    return added - complete


def examples_dir() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(here, "examples")
