"""AOT executable cache (docs/aot_cache.md): warm restarts must dispatch the
deserialized executable with ZERO trace/compile phase time and bitwise-equal
losses; any fingerprint/entry problem must fall through to a normal compile
with a loud miss — never a crash, never a wrong-program dispatch; the
cache-off path is pinned to the pre-cache code."""

import glob
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import (
    Accelerator,
    CompilationCacheKwargs,
    TelemetryKwargs,
)
from accelerate_tpu.native.aot_cache import (
    AOTCompilationCache,
    fingerprint_mismatch,
    topology_fingerprint,
)
from accelerate_tpu.nn.tape import Tensor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _reset_active_cache():
    """A DecodeService constructed without an accelerator resolves the
    process-active cache (current_aot_cache) — intended for real processes,
    but between tests it would leak this file's tmp-dir caches into serving
    tests that never opted in.  Clear the module slot after every test."""
    yield
    from accelerate_tpu.native.aot_cache import _set_active

    _set_active(None)


def _fresh_accelerator(cache_dir, telemetry=True, **acc_kwargs):
    """Process-simulated fresh start: reset the library singletons and drop
    every in-memory jit/pjit cache, so only the on-disk store can skip
    trace+compile."""
    Accelerator._reset_state()
    jax.clear_caches()
    nn.manual_seed(0)
    handlers = []
    if telemetry:
        handlers.append(TelemetryKwargs(enabled=True))
    if cache_dir is not None:
        handlers.append(CompilationCacheKwargs(cache_dir=str(cache_dir)))
    return Accelerator(kwargs_handlers=handlers, **acc_kwargs)


def _linear_step(acc):
    model = nn.Linear(4, 2)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(xb):
        opt.zero_grad()
        loss = model(Tensor(xb)).sum()
        acc.backward(loss)
        opt.step()
        return loss

    return acc.compile_step(step_fn)


def _run(cache_dir, n_steps=2, telemetry=True):
    acc = _fresh_accelerator(cache_dir, telemetry=telemetry)
    step = _linear_step(acc)
    xb = jnp.ones((8, 4))
    losses = [float(step(xb)) for _ in range(n_steps)]
    return acc, step, losses


# ---------------------------------------------------------------------------
# the zero-cold-start contract
# ---------------------------------------------------------------------------

def test_warm_reload_skips_trace_and_compile_bitwise_loss(tmp_path):
    cache_dir = tmp_path / "cache"
    acc1, step1, losses1 = _run(cache_dir)
    assert acc1.aot_cache.misses >= 1 and acc1.aot_cache.stores >= 1
    cold_first = acc1.telemetry.timeline.records()[0]
    assert cold_first.compile_ms > 0

    acc2, step2, losses2 = _run(cache_dir)
    warm_first = acc2.telemetry.timeline.records()[0]
    assert warm_first.built  # a build — just one that came off disk
    assert warm_first.trace_ms == 0.0 and warm_first.compile_ms == 0.0
    assert acc2.aot_cache.hits >= 1
    assert not any(
        e["event"] == "miss" and e.get("scope") == "train"
        for e in acc2.telemetry.aot_cache_events
    )
    assert losses2 == losses1  # bitwise: same program, same state
    # the loaded entry is an executable, not the plain-jit fallback
    entry = next(iter(step2._cache.values()))
    assert not hasattr(entry[0], "lower")


def test_cache_off_is_pinned(tmp_path):
    """No cache dir → the pre-cache path byte-for-byte: disabled hub handle,
    a None pin on the CapturedStep, no events, no files; with telemetry
    also off the entry is the plain jitted callable exactly as before."""
    acc, step, _ = _run(None)
    assert not acc.aot_cache.enabled
    assert step._aot_cache is None
    assert not list(acc.telemetry.aot_cache_events)
    entry = next(iter(step._cache.values()))
    assert not hasattr(entry[0], "lower")  # telemetry AOT build, as before

    acc2, step2, _ = _run(None, telemetry=False)
    assert step2._aot_cache is None
    entry2 = next(iter(step2._cache.values()))
    assert hasattr(entry2[0], "lower")  # plain jit, as before


def test_env_surface(tmp_path, monkeypatch):
    monkeypatch.setenv("ACCELERATE_AOT_CACHE", str(tmp_path / "envcache"))
    assert CompilationCacheKwargs().enabled
    monkeypatch.setenv("ACCELERATE_AOT_CACHE", "0")
    assert not CompilationCacheKwargs().enabled
    monkeypatch.delenv("ACCELERATE_AOT_CACHE")
    assert not CompilationCacheKwargs().enabled


# ---------------------------------------------------------------------------
# invalidation: stale fingerprints fall through LOUDLY, broken entries softly
# ---------------------------------------------------------------------------

def _tamper_fingerprints(cache_dir, **overrides):
    """Re-file every entry under a fake topology fingerprint (digest suffix
    AND metadata), simulating entries written by a different fleet shape."""
    for meta_path in glob.glob(os.path.join(str(cache_dir), "*-*.json")):
        if os.path.basename(meta_path).startswith("profile-"):
            continue
        with open(meta_path, encoding="utf-8") as f:
            meta = json.load(f)
        meta["fingerprint"].update(overrides)
        stem = meta_path[: -len(".json")]
        variant = os.path.basename(stem).split("-")[0]
        fake = os.path.join(str(cache_dir), f"{variant}-deadbeefdeadbeef")
        os.rename(stem + ".pkl", fake + ".pkl")
        os.remove(meta_path)
        with open(fake + ".json", "w", encoding="utf-8") as f:
            json.dump(meta, f)


def test_stale_fingerprint_falls_through_with_loud_miss(tmp_path):
    cache_dir = tmp_path / "cache"
    _, _, losses1 = _run(cache_dir)
    _tamper_fingerprints(cache_dir, device_count=999, jax="0.0.1")

    acc2, _, losses2 = _run(cache_dir)
    misses = [
        e for e in acc2.telemetry.aot_cache_events if e["event"] == "miss"
    ]
    assert misses, "stale entry produced no miss record"
    assert any(
        "device_count" in (e.get("cause") or "") and "jax" in (e.get("cause") or "")
        for e in misses
    ), misses
    # fell through to a NORMAL compile: same math, no crash
    warm_first = acc2.telemetry.timeline.records()[0]
    assert warm_first.compile_ms > 0
    assert losses2 == losses1


def test_corrupt_entry_is_fail_soft_miss(tmp_path):
    cache_dir = tmp_path / "cache"
    _, _, losses1 = _run(cache_dir)
    for pkl in glob.glob(os.path.join(str(cache_dir), "*-*.pkl")):
        with open(pkl, "wb") as f:
            f.write(b"\x00truncated")
    acc2, _, losses2 = _run(cache_dir)
    assert losses2 == losses1
    causes = [
        e.get("cause") or ""
        for e in acc2.telemetry.aot_cache_events
        if e["event"] == "miss"
    ]
    assert any("unpicklable" in c or "deserialize" in c for c in causes), causes


def test_fingerprint_mismatch_names_moved_fields():
    live = topology_fingerprint()
    stale = dict(live, device_count=3, jaxlib="9.9.9")
    cause = fingerprint_mismatch(stale, live)
    assert "device_count" in cause and "jaxlib" in cause
    assert fingerprint_mismatch(None, live) == "entry metadata carries no fingerprint"


def test_compiler_flags_in_fingerprint():
    """ROADMAP carried item: the store is keyed on compiler-mode flags too.
    The fingerprint carries them as flat ``flag:*`` fields so a stale-flag
    miss names the exact flag that moved."""
    from accelerate_tpu.native.aot_cache import FINGERPRINT_FLAGS

    live = topology_fingerprint()
    for flag in FINGERPRINT_FLAGS:
        assert f"flag:{flag}" in live, flag
    assert "flag:jax_default_matmul_precision" in live


def test_flag_flip_is_loud_miss_naming_the_flag(tmp_path):
    """A ``jax_default_matmul_precision`` flip between the storing and the
    loading process would deserialize a program compiled under the other
    numerics — it must be a fall-through miss whose cause NAMES the flag,
    never a silent wrong-precision dispatch."""
    cache_dir = tmp_path / "cache"
    prev = jax.config.jax_default_matmul_precision
    _, _, losses1 = _run(cache_dir)
    try:
        jax.config.update("jax_default_matmul_precision", "float32")
        acc2, _, _ = _run(cache_dir)
        misses = [
            e for e in acc2.telemetry.aot_cache_events if e["event"] == "miss"
        ]
        assert misses, "flag flip produced no miss record"
        assert any(
            "flag:jax_default_matmul_precision" in (e.get("cause") or "")
            for e in misses
        ), misses
        # fell through to a NORMAL compile under the new flag: no crash
        warm_first = acc2.telemetry.timeline.records()[0]
        assert warm_first.compile_ms > 0
    finally:
        jax.config.update("jax_default_matmul_precision", prev)


# ---------------------------------------------------------------------------
# size bound
# ---------------------------------------------------------------------------

def test_lru_eviction_bounds_size(tmp_path):
    from accelerate_tpu.utils.dataclasses import CompilationCacheKwargs as K

    cache = AOTCompilationCache(K(cache_dir=str(tmp_path / "lru"), max_bytes=1))
    fp = cache.fingerprint()

    def compiled_for(n):
        return jax.jit(lambda x: x * n).lower(jnp.ones((4,))).compile()

    assert cache.store("variant0", fp, compiled_for(1), None, "train", "k0")
    assert cache.store("variant1", fp, compiled_for(2), None, "train", "k1")
    # 1-byte budget: storing entry 1 evicted entry 0 (the just-written entry
    # itself is exempt, so exactly one survives)
    assert cache.evictions >= 1
    pkls = glob.glob(os.path.join(str(tmp_path / "lru"), "*-*.pkl"))
    assert len(pkls) == 1 and "variant1" in pkls[0]
    assert cache.lookup("variant0", fp, "train", "k0") is None
    assert cache.lookup("variant1", fp, "train", "k1") is not None


# ---------------------------------------------------------------------------
# trace-time side effects survive the skipped trace
# ---------------------------------------------------------------------------

def _scheduler_run(cache_dir, n_steps=3):
    acc = _fresh_accelerator(cache_dir)
    model = nn.Linear(2, 1)
    opt = optim.SGD(model.parameters(), lr=1.0)
    sched = optim.LambdaLR(opt, lambda s: 1.0 / (s + 1))
    model, opt, sched = acc.prepare(model, opt, sched)

    def step_fn(xb):
        opt.zero_grad()
        loss = model(Tensor(xb)).sum()
        acc.backward(loss)
        opt.step()
        sched.step()
        return loss

    step = acc.compile_step(step_fn)
    lrs = []
    for _ in range(n_steps):
        step(jnp.ones((2, 2)))
        lrs.append(float(opt.optimizer.lr))
    return acc, lrs


def test_scheduler_replay_survives_warm_restart(tmp_path):
    """Deferred scheduler steps are recorded at TRACE time — a warm restart
    never traces, so they ride the entry's side metadata (scheduler registry
    index) and must replay identically."""
    cache_dir = tmp_path / "cache"
    _, lrs_cold = _scheduler_run(cache_dir)
    acc2, lrs_warm = _scheduler_run(cache_dir)
    warm_first = acc2.telemetry.timeline.records()[0]
    assert warm_first.trace_ms == 0.0 and warm_first.compile_ms == 0.0
    assert acc2.aot_cache.hits >= 1
    assert lrs_warm == lrs_cold


def _accum_run(cache_dir, n_calls=4):
    acc = _fresh_accelerator(cache_dir, gradient_accumulation_steps=2)
    model = nn.Linear(4, 1)
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)

    def step_fn(xb):
        with acc.accumulate(model):
            loss = model(Tensor(xb)).sum()
            acc.backward(loss)
            opt.step()
            opt.zero_grad()
        return loss

    step = acc.compile_step(step_fn)
    data = np.random.default_rng(0).normal(size=(n_calls, 2, 4)).astype(np.float32)
    return acc, [float(step(jnp.asarray(data[i]))) for i in range(n_calls)]


def test_accumulate_step_warm_restart(tmp_path):
    """An accumulate-using body bakes sync_gradients into each variant and
    advances the schedule during its FIRST trace — the warm process (no
    trace) must advance it host-side via the profile sidecar, land on the
    stored keys, and reproduce the micro/sync step pattern bitwise."""
    cache_dir = tmp_path / "cache"
    acc1, losses_cold = _accum_run(cache_dir)
    assert acc1.aot_cache.stores >= 2  # one per sync variant
    acc2, losses_warm = _accum_run(cache_dir)
    warm_first = acc2.telemetry.timeline.records()[0]
    assert warm_first.trace_ms == 0.0 and warm_first.compile_ms == 0.0
    assert acc2.aot_cache.hits >= 2
    assert not any(
        e["event"] == "miss" and e.get("scope") == "train"
        for e in acc2.telemetry.aot_cache_events
    )
    assert losses_warm == losses_cold


def test_restore_prefetch_then_first_step_hits(tmp_path):
    """The preemption-resume flow: ``load_state`` runs its cache prefetch
    BEFORE the process's first captured build, so the prefetch must hash
    the same (mesh/compression-pinned) fingerprint the cold run stored
    under — a context-less fingerprint here would stage nothing and every
    later lookup would miss.  The restored step must then run off the
    deserialized executable, bitwise-continuing the interrupted run."""
    cache_dir = tmp_path / "cache"
    ckpt = tmp_path / "ckpt"
    acc1 = _fresh_accelerator(cache_dir)
    step1 = _linear_step(acc1)
    xb = jnp.ones((8, 4))
    for _ in range(2):
        float(step1(xb))
    acc1.save_state(str(ckpt))
    loss_ref = float(step1(xb))  # the step a resumed process runs next

    acc2 = _fresh_accelerator(cache_dir)
    step2 = _linear_step(acc2)
    acc2.load_state(str(ckpt))  # prefetch fires here, before any build
    assert acc2.aot_cache.last_prefetch_count >= 1
    loss2 = float(step2(xb))
    warm_first = acc2.telemetry.timeline.records()[0]
    assert warm_first.trace_ms == 0.0 and warm_first.compile_ms == 0.0
    assert acc2.aot_cache.hits >= 1
    assert loss2 == loss_ref


# ---------------------------------------------------------------------------
# serving: replica spin-up warms every bucket program from disk
# ---------------------------------------------------------------------------

def _serving_run(cache_dir):
    from accelerate_tpu import DecodeService, ServingConfig
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel

    acc = _fresh_accelerator(cache_dir)
    cfg = GPTConfig(vocab_size=128, n_positions=96, n_embd=32, n_layer=2, n_head=2)
    model = acc.prepare(GPTLMHeadModel(cfg))
    model.eval()
    service = DecodeService(
        model,
        ServingConfig(max_slots=2, block_size=16, prompt_bucket=16),
        telemetry=acc.telemetry,
    )
    rid = service.submit(
        np.random.default_rng(0).integers(0, 128, (9,), dtype=np.int32),
        max_new_tokens=4,
    )
    service.run()
    return service, service.results[rid].tokens


def test_serving_warm_from_disk(tmp_path):
    """Replica spin-up: every bucket program the first service STORED comes
    off disk in the second, and anything XLA:CPU's serializer refused (its
    executable export can drop function symbols once the process
    JIT-compiled other programs; verify-on-store catches that and records
    store_failed) recompiles soundly — warmed + compiles covers both
    programs, zero steady-state recompile events, identical greedy tokens.
    The cross-process zero-cold-start proof is `make cache-smoke`."""
    cache_dir = tmp_path / "cache"
    svc1, tokens1 = _serving_run(cache_dir)
    assert svc1.watcher.compiles_total == 2  # prefill bucket + decode
    assert svc1._aot is not None and svc1._aot.warmed == 0
    stored = len(
        [p for p in glob.glob(os.path.join(str(cache_dir), "*-*.pkl"))]
    )

    svc2, tokens2 = _serving_run(cache_dir)
    assert svc2._aot.warmed == stored  # everything stored must warm
    assert svc2._aot.warmed + svc2.watcher.compiles_total == 2
    assert svc2.recompile_events == 0
    assert tokens2 == tokens1
    if stored == 0:
        # both programs hit the XLA:CPU symbol-dedup store refusal in this
        # process — the fall-through path above is proven, but the warm
        # path ran empty; say so instead of silently passing
        pytest.skip("XLA:CPU refused to serialize both serving programs "
                    "in this process; warm path exercised with 0 entries")


# ---------------------------------------------------------------------------
# observability: metrics provider, record schema, report section
# ---------------------------------------------------------------------------

def test_metrics_provider_and_report_section(tmp_path):
    cache_dir = tmp_path / "cache"
    _run(cache_dir)
    acc, _, _ = _run(cache_dir)
    assert any(
        name == "aot_cache" for name, _ in acc.telemetry._metrics_providers
    )
    metrics = acc.aot_cache.metrics()
    assert metrics["hits_total"] >= 1 and metrics["entries"] >= 1
    assert {"misses_total", "stores_total", "bytes"} <= set(metrics)

    jsonl = str(tmp_path / "run.jsonl")
    acc.telemetry.write_jsonl(jsonl)
    from telemetry_report import load_records, render, validate

    records = load_records(jsonl)
    assert validate(records, min_steps=1) == []
    assert any(r.get("kind") == "aot_cache" for r in records)
    assert "aot executable cache" in render(records)


# ---------------------------------------------------------------------------
# scope-map persistence: warm processes keep the per-phase device split
# ---------------------------------------------------------------------------

_SCOPE_MAP_CHILD = '''
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# the suite's persistent XLA compilation cache (tests/conftest.py) strips
# HLO metadata from deserialized programs — the very failure mode this
# feature exists to survive, but here it would ALSO blank the cold child's
# store-side parse, so the children run without it
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
sys.path.insert(0, "@REPO@")
cache_dir, out_path = sys.argv[1], sys.argv[2]

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, CompilationCacheKwargs, TelemetryKwargs
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.nn import Tensor

nn.manual_seed(0)
acc = Accelerator(
    kwargs_handlers=[
        TelemetryKwargs(enabled=True, profile_every_n=1),
        CompilationCacheKwargs(cache_dir=cache_dir),
    ]
)
model = nn.Linear(16, 8)
opt = optim.AdamW(model.parameters(), lr=1e-2)
model, opt = acc.prepare(model, opt)


def step_fn(x):
    opt.zero_grad()
    loss = model(Tensor(x)).sum()
    acc.backward(loss)
    opt.step()
    return loss


step = acc.compile_step(step_fn)
rng = np.random.default_rng(0)
x = batch_to_global_array(
    np.asarray(rng.normal(size=(8, 16)), np.float32), mesh=acc.mesh
)
for _ in range(2):
    float(step(x))
first = acc.telemetry.timeline.records()[0]
result = {
    "first_trace_ms": first.trace_ms,
    "first_compile_ms": first.compile_ms,
    "hits": acc.aot_cache.hits,
    "stores": acc.aot_cache.stores,
    "phases_per_sample": [
        sorted(r.phases) for r in acc.telemetry.device_records
    ],
}
with open(out_path, "w") as f:
    json.dump(result, f)
'''


@pytest.mark.slow
def test_scope_map_persists_across_processes(tmp_path):
    """ROADMAP carried item: programs deserialized from the AOT store carry
    no HLO metadata, so a warm process used to sample EMPTY ``phases`` —
    the op→scope map is now persisted beside the executable and restored on
    load.  Two real subprocesses (like ``make cache-smoke``): the cold one
    compiles/stores with every step profiled, the warm one deserializes
    (zero trace/compile) and its samples must STILL split by atpu phase."""
    import subprocess

    child = tmp_path / "child.py"
    child.write_text(_SCOPE_MAP_CHILD.replace("@REPO@", REPO))
    cache_dir = str(tmp_path / "aot")

    def run(label):
        out = str(tmp_path / f"{label}.json")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, str(child), cache_dir, out],
            env=env, capture_output=True, text=True, timeout=420, cwd=REPO,
        )
        assert proc.returncode == 0, (
            f"{label} child failed\n{proc.stdout[-2000:]}\n{proc.stderr[-4000:]}"
        )
        with open(out, encoding="utf-8") as f:
            return json.load(f)

    cold = run("cold")
    assert cold["stores"] >= 1 and cold["first_compile_ms"] > 0
    # the cold process compiled in-process: its samples carry phases from
    # the live HLO parse — the baseline the warm process must match
    assert cold["phases_per_sample"], "cold run sampled nothing"
    assert any(
        any(p.startswith("atpu") for p in phases)
        for phases in cold["phases_per_sample"]
    ), cold["phases_per_sample"]

    warm = run("warm")
    assert warm["hits"] >= 1
    assert warm["first_trace_ms"] == 0.0 and warm["first_compile_ms"] == 0.0, (
        "warm child recompiled — the store did not serve the program"
    )
    # THE pin: a metadata-less deserialized program still splits by phase,
    # because the stored scope map was restored into the telemetry hub
    assert warm["phases_per_sample"], "warm run sampled nothing"
    assert any(
        any(p.startswith("atpu") for p in phases)
        for phases in warm["phases_per_sample"]
    ), f"warm samples lost the per-phase split: {warm['phases_per_sample']}"


def test_jax_cache_layer_disarmed_for_scope_dependent_runs(tmp_path):
    """ROADMAP carried item, second layer: executables served by jax's OWN
    XLA compilation cache (``jax_cache_dir``) carry no HLO metadata and no
    side payload to persist a scope map in — a device-time-sampling run
    would read empty ``phases`` from every cache-served program.  Attaching
    a profiler-armed telemetry hub must therefore DISARM that layer (with a
    kind="aot_cache" record saying why); a hub without device-time sampling
    keeps it, because nothing scope-dependent ever reads the maps.  The
    disarm is a PROCESS-WIDE latch: jax's config is global, so a cache
    constructed after the disarm must not silently re-arm the layer while
    the sampler is still live (review-pinned)."""
    from accelerate_tpu.native import aot_cache as aot_mod
    from accelerate_tpu.telemetry import Telemetry
    from accelerate_tpu.utils.dataclasses import TelemetryKwargs

    saved = jax.config.jax_compilation_cache_dir
    jax_dir = str(tmp_path / "jaxcache")
    try:
        # a hub WITHOUT device-time sampling: the layer stays armed
        cache = AOTCompilationCache(CompilationCacheKwargs(
            cache_dir=str(tmp_path / "aot1"), jax_cache_dir=jax_dir,
        ))
        hub_plain = Telemetry(TelemetryKwargs(enabled=True))
        assert hub_plain.profiler is None
        cache.attach_telemetry(hub_plain)
        assert jax.config.jax_compilation_cache_dir == jax_dir

        # a scope-dependent hub (profile_every_n): the layer is disarmed
        cache2 = AOTCompilationCache(CompilationCacheKwargs(
            cache_dir=str(tmp_path / "aot2"), jax_cache_dir=jax_dir,
        ))
        hub = Telemetry(TelemetryKwargs(enabled=True, profile_every_n=1))
        assert hub.profiler is not None
        cache2.attach_telemetry(hub)
        assert jax.config.jax_compilation_cache_dir is None
        events = [
            r for r in hub.all_records()
            if r.get("kind") == "aot_cache"
            and r.get("event") == "jax_cache_layer_disarmed"
        ]
        assert events and "metadata" in events[0]["cause"]

        # THE latch pin: a cache constructed AFTER the disarm (a second
        # Accelerator, a serving replica) must NOT re-arm the global layer
        # while the profiler-armed hub is still sampling
        AOTCompilationCache(CompilationCacheKwargs(
            cache_dir=str(tmp_path / "aot3"), jax_cache_dir=jax_dir,
        ))
        assert jax.config.jax_compilation_cache_dir is None
    finally:
        jax.config.update("jax_compilation_cache_dir", saved)
        aot_mod._set_jax_cache_layer_disarmed(False)
