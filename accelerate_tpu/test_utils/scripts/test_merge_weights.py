"""Sharded-checkpoint merge round-trip (analog of reference
test_utils/scripts/test_merge_weights.py).

Trains a ZeRO-sharded model on the mesh, writes the GSPMD slice-bounds
sharded checkpoint, merges it offline with the same code path as
``accelerate-tpu merge-weights``, and verifies every merged tensor is
bitwise-identical to the live (gathered) parameters — including the
fsdp-exempt (replicated) embedding tables and bf16 views.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np

import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, set_seed
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.state import PartialState
from accelerate_tpu.utils.dataclasses import ParallelismConfig
from accelerate_tpu.utils.fsdp_utils import (
    merge_sharded_weights,
    save_sharded_model_state,
    sharded_index_path,
)


def main():
    import jax

    n_dev = len(jax.devices())
    fsdp = 2 if n_dev >= 2 else 1

    set_seed(11)
    acc = Accelerator(parallelism_config=ParallelismConfig(fsdp_size=fsdp))
    cfg = GPTConfig(
        vocab_size=256, n_positions=32, n_embd=64, n_layer=2, n_head=2, dropout=0.0
    )
    model = GPTLMHeadModel(cfg)
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    # one step so the merged weights are not just the init
    ids = np.zeros((max(8, n_dev), 32), dtype=np.int32)
    out = model(ids, labels=ids)
    acc.backward(out["loss"])
    opt.step()

    live = {k: np.asarray(jax.device_get(p.data)) for k, p in model.named_parameters()}

    with tempfile.TemporaryDirectory() as tmp:
        save_sharded_model_state({k: p.data for k, p in model.named_parameters()}, tmp)
        assert os.path.exists(sharded_index_path(tmp)), os.listdir(tmp)
        merged_path = merge_sharded_weights(
            tmp, os.path.join(tmp, "merged.safetensors")
        )

        import json as _json

        from safetensors import safe_open
        from accelerate_tpu.utils.fsdp_utils import _maybe_bf16_from_view

        merged = {}
        with safe_open(merged_path, framework="numpy") as f:
            bf16_keys = set(_json.loads(f.metadata().get("bf16_keys", "[]")))
            for key in f.keys():
                arr = f.get_tensor(key)
                merged[key] = _maybe_bf16_from_view(
                    arr, "bfloat16" if key in bf16_keys else str(arr.dtype)
                )

    def _np_view(a: np.ndarray) -> np.ndarray:
        # safetensors stores bf16 natively; live side is numpy's view
        return a.astype(np.float32) if a.dtype != np.float32 else a

    missing = set(live) - set(merged)
    assert not missing, f"merged checkpoint missing params: {sorted(missing)[:5]}"
    for name, arr in live.items():
        np.testing.assert_array_equal(
            _np_view(np.asarray(merged[name])),
            _np_view(arr),
            err_msg=f"merged weight {name} != live",
        )

    PartialState._reset_state()
    print("All merge-weights checks passed")


if __name__ == "__main__":
    main()
