"""Global RNG for the imperative nn layer.

Torch-style code expects implicit randomness (dropout just works); JAX wants
explicit keys.  Bridge: a counter-based global RNG — each draw is
``fold_in(base_key, counter)``.  Eagerly the base key comes from ``manual_seed``;
under step capture the ``Accelerator`` swaps in a *traced* per-step key so
dropout masks differ across steps inside one compiled program, and checkpoint
resume restores determinism by saving (seed, counter).
"""

from __future__ import annotations

import jax


class GlobalRNG:
    def __init__(self, seed: int = 0):
        self._seed = seed
        self._base_key = None
        self._counter = 0

    def manual_seed(self, seed: int) -> None:
        self._seed = seed
        self._base_key = jax.random.key(seed)
        self._counter = 0

    def set_key(self, key) -> None:
        """Swap in an externally-managed (possibly traced) base key."""
        self._base_key = key
        self._counter = 0

    def next_key(self):
        if self._base_key is None:
            self.manual_seed(self._seed)
        k = jax.random.fold_in(self._base_key, self._counter)
        self._counter += 1
        return k

    def get_state(self) -> dict:
        return {"seed": self._seed, "counter": self._counter}

    def set_state(self, state: dict) -> None:
        # lazy: creating the key here would stage a tracer when called inside
        # a jit trace (e.g. restoring after step capture); next_key() rebuilds
        # it outside the trace instead
        self._seed = state["seed"]
        self._base_key = None
        self._counter = state["counter"]


default_rng = GlobalRNG()


def manual_seed(seed: int) -> None:
    default_rng.manual_seed(seed)


def next_key():
    return default_rng.next_key()
