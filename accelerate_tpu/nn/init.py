"""Parameter initialisers, meta-mode aware.

All layer/model weight creation routes through these helpers so that inside
``init_empty_weights`` (big_modeling) nothing is allocated and no RNG is
consumed: each call returns a :class:`~accelerate_tpu.nn.meta.MetaArray`
instead of running the initializer. Outside meta mode they are thin wrappers
over ``jax.random`` / ``jnp`` with torch-default semantics (kaiming-uniform
Linear bounds are computed by the callers).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import random as nn_random
from .meta import MetaArray, meta_mode_active


def _meta_or(fn, shape, dtype):
    # These helpers only ever create Parameter data (Buffers build their
    # true values directly — arange caches etc. — so include_buffers=False
    # never needs a real draw here): under meta mode, skip the initializer
    # entirely so no RNG is consumed and later materialisation stays
    # deterministic.
    if meta_mode_active():
        return MetaArray(shape, dtype)
    return fn()


def uniform(shape, bound: float, dtype=jnp.float32):
    """U(-bound, bound) — torch Linear/Conv default (kaiming-uniform)."""
    return _meta_or(
        lambda: jax.random.uniform(
            nn_random.next_key(), shape, minval=-bound, maxval=bound, dtype=dtype
        ),
        shape,
        dtype,
    )


def normal(shape, std: float = 1.0, mean: float = 0.0, dtype=jnp.float32):
    return _meta_or(
        lambda: mean + std * jax.random.normal(nn_random.next_key(), shape, dtype),
        shape,
        dtype,
    )


def zeros(shape, dtype=jnp.float32):
    return _meta_or(lambda: jnp.zeros(shape, dtype), shape, dtype)


def ones(shape, dtype=jnp.float32):
    return _meta_or(lambda: jnp.ones(shape, dtype), shape, dtype)


def full(shape, fill_value, dtype=jnp.float32):
    return _meta_or(lambda: jnp.full(shape, fill_value, dtype), shape, dtype)


def arange(n: int, dtype=jnp.int32):
    """Buffer-value helper: unlike the parameter initializers above, in
    ``init_empty_weights(include_buffers=False)`` mode the TRUE values are
    produced (position ids / caches must survive meta init)."""
    from .meta import meta_include_buffers

    if meta_mode_active() and meta_include_buffers():
        return MetaArray((n,), dtype)
    return jnp.arange(n, dtype=dtype)
