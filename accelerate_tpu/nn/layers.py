"""Standard layers on the tape Module system.

Initialisations follow torch defaults (kaiming-uniform Linear, N(0,1)
Embedding scaled) so models built here converge like their reference-world
counterparts; everything computes through ``F.*`` → jnp → XLA.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from . import functional as F
from . import init
from .module import Buffer, Module, Parameter
from .tape import Tensor


class Linear(Module):
    def __init__(self, in_features: int, out_features: int, bias: bool = True, dtype=jnp.float32):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        bound = 1.0 / math.sqrt(in_features)
        self.weight = Parameter(init.uniform((out_features, in_features), bound, dtype))
        if bias:
            self.bias = Parameter(init.uniform((out_features,), bound, dtype))
        else:
            self.register_parameter("bias", None)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def __repr__(self):
        return f"Linear(in={self.in_features}, out={self.out_features}, bias={self.bias is not None})"


class Embedding(Module):
    def __init__(self, num_embeddings: int, embedding_dim: int, dtype=jnp.float32):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = Parameter(init.normal((num_embeddings, embedding_dim), dtype=dtype))
        # gather tables must not be ZeRO-sharded on the feature axis: an
        # fsdp-sharded embedding makes every lookup emit its output sharded
        # on embd, which GSPMD then full-rematerializes back to the batch
        # layout (Megatron layout: vocab-over-tp only)
        self.weight.fsdp_exempt = True

    def forward(self, ids):
        return F.embedding(ids, self.weight)

    def __repr__(self):
        return f"Embedding({self.num_embeddings}, {self.embedding_dim})"


class LayerNorm(Module):
    def __init__(self, normalized_shape, eps: float = 1e-5, elementwise_affine: bool = True, dtype=jnp.float32):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        if elementwise_affine:
            self.weight = Parameter(init.ones(self.normalized_shape, dtype))
            self.bias = Parameter(init.zeros(self.normalized_shape, dtype))
        else:
            self.register_parameter("weight", None)
            self.register_parameter("bias", None)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.eps)


class RMSNorm(Module):
    def __init__(self, dim: int, eps: float = 1e-6, dtype=jnp.float32):
        super().__init__()
        self.eps = eps
        self.weight = Parameter(init.ones((dim,), dtype))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.eps)


class Dropout(Module):
    def __init__(self, p: float = 0.5):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.dropout(x, self.p, training=self.training)

    def __repr__(self):
        return f"Dropout(p={self.p})"


class Identity(Module):
    def forward(self, x):
        return x


class ReLU(Module):
    def forward(self, x):
        return F.relu(x)


class GELU(Module):
    def __init__(self, approximate: str | bool = "tanh"):
        super().__init__()
        self.approximate = approximate in ("tanh", True)

    def forward(self, x):
        return F.gelu(x, approximate=self.approximate)


class SiLU(Module):
    def forward(self, x):
        return F.silu(x)


class Tanh(Module):
    def forward(self, x):
        return F.tanh(x)


class Sigmoid(Module):
    def forward(self, x):
        return F.sigmoid(x)


class Softmax(Module):
    def __init__(self, dim: int = -1):
        super().__init__()
        self.dim = dim

    def forward(self, x):
        return F.softmax(x, axis=self.dim)


class Conv2d(Module):
    """NCHW conv (torch layout) lowered to lax.conv_general_dilated.

    XLA maps this straight onto the MXU; for image models prefer channel
    counts that are multiples of 128 on TPU.
    """

    def __init__(
        self,
        in_channels: int,
        out_channels: int,
        kernel_size,
        stride=1,
        padding=0,
        bias: bool = True,
        dtype=jnp.float32,
    ):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self.stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
        self.padding = (
            ((padding, padding), (padding, padding))
            if isinstance(padding, int)
            else tuple((p, p) if isinstance(p, int) else p for p in padding)
        )
        fan_in = in_channels * kernel_size[0] * kernel_size[1]
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = Parameter(
            init.uniform((out_channels, in_channels, *kernel_size), bound, dtype)
        )
        if bias:
            self.bias = Parameter(init.uniform((out_channels,), bound, dtype))
        else:
            self.register_parameter("bias", None)

    def forward(self, x):
        from .tape import tape_op

        def _conv(v, w, *b):
            # mixed precision: compute in the weight dtype (lax.conv requires
            # matching dtypes; down-casting the input is the bf16-policy move)
            v = v.astype(w.dtype)
            out = jax.lax.conv_general_dilated(
                v,
                w,
                window_strides=self.stride,
                padding=self.padding,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
            )
            if b:
                out = out + b[0][None, :, None, None]
            return out

        args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
        return tape_op(_conv, *args)


class MaxPool2d(Module):
    def __init__(self, kernel_size, stride=None):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else kernel_size
        s = stride or kernel_size
        self.k = k
        self.s = (s, s) if isinstance(s, int) else s

    def forward(self, x):
        from .tape import tape_op

        def _pool(v):
            return jax.lax.reduce_window(
                v,
                -jnp.inf,
                jax.lax.max,
                (1, 1, *self.k),
                (1, 1, *self.s),
                "VALID",
            )

        return tape_op(_pool, x)


class AvgPool2d(Module):
    def __init__(self, kernel_size, stride=None):
        super().__init__()
        k = (kernel_size, kernel_size) if isinstance(kernel_size, int) else kernel_size
        s = stride or kernel_size
        self.k = k
        self.s = (s, s) if isinstance(s, int) else s

    def forward(self, x):
        from .tape import tape_op

        def _pool(v):
            summed = jax.lax.reduce_window(
                v, 0.0, jax.lax.add, (1, 1, *self.k), (1, 1, *self.s), "VALID"
            )
            return summed / (self.k[0] * self.k[1])

        return tape_op(_pool, x)


class CrossEntropyLoss(Module):
    def __init__(self, ignore_index: Optional[int] = -100, label_smoothing: float = 0.0):
        super().__init__()
        self.ignore_index = ignore_index
        self.label_smoothing = label_smoothing

    def forward(self, logits, labels):
        return F.cross_entropy(
            logits, labels, ignore_index=self.ignore_index, label_smoothing=self.label_smoothing
        )


class MSELoss(Module):
    def forward(self, pred, target):
        return F.mse_loss(pred, target)
