"""Gradient-accumulation semantics under the launcher (reference
test_utils/scripts/test_sync.py): grads only apply on sync steps, and the
accumulated update equals one big-batch update."""

from __future__ import annotations

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, set_seed
from accelerate_tpu.nn import Tensor
from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel


def _train(accum_steps: int, micro_bs: int, n_batches: int, lr=0.1):
    set_seed(0)
    acc = Accelerator(gradient_accumulation_steps=accum_steps)
    model = RegressionModel()
    opt = optim.SGD(model.parameters(), lr=lr)
    model, opt = acc.prepare(model, opt)
    data = RegressionDataset(length=micro_bs * n_batches, seed=7)
    for i in range(n_batches):
        sl = slice(i * micro_bs, (i + 1) * micro_bs)
        with acc.accumulate(model):
            pred = model(Tensor(data.x[sl]))
            loss = nn.F.mse_loss(pred, Tensor(data.y[sl]))
            acc.backward(loss)
            opt.step()
            opt.zero_grad()  # canonical order: both are no-ops mid-window
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    a, b = float(np.asarray(model.a.data)), float(np.asarray(model.b.data))
    PartialState._reset_state()
    return a, b


def main():
    # 4 micro-batches at accumulation 4 == one batch 4× the size at accumulation 1
    a_accum, b_accum = _train(accum_steps=4, micro_bs=4, n_batches=4)
    a_big, b_big = _train(accum_steps=1, micro_bs=16, n_batches=1)
    assert abs(a_accum - a_big) < 1e-5, f"{a_accum} vs {a_big}"
    assert abs(b_accum - b_big) < 1e-5, f"{b_accum} vs {b_big}"
    print("All sync checks passed")


if __name__ == "__main__":
    main()
