"""Ring attention — sequence/context parallelism over the ``sp`` mesh axis.

NEW capability relative to the reference: HF Accelerate has no native
sequence parallelism at all (SURVEY.md §2.2 — grep-verified; only Megatron
pass-through flags).  Here it is first-class and TPU-native:

* the sequence dimension is sharded over the ``sp`` mesh axis;
* each device holds one q-chunk permanently and streams k/v chunks around the
  ring with ``lax.ppermute`` over ICI — communication overlaps the blockwise
  attention compute of the previous chunk (XLA schedules the permute
  concurrently with the einsums);
* softmax is computed online (running max/denominator, the flash-attention
  recurrence) so the full (S × S) score matrix never exists anywhere and the
  per-device memory is O(S/n · S/n) per block pair;
* causal masking: fully-masked hops are skipped by a per-device ``lax.cond``
  (a real branch — shard_map bodies are scalar programs, not vmapped lanes)
  and, on TPU, partially-masked hops run the Pallas hop kernel whose
  offset-aware tile predicate skips MXU work above the diagonal.  The saving
  is ~half the *FLOPs/energy*; ring *latency* is still n lockstep hops, so
  per-step wall-clock is bounded by the busiest device (a zigzag/striped
  layout would balance that and is future work).

Design follows the blockwise/ring attention literature (see PAPERS.md);
no reference code exists for this path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _block_update(q, k, v, m, l, acc, q_offset, k_offset, scale, is_causal,
                  window=0):
    """One online-softmax accumulation of q against a k/v chunk."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if is_causal:
        sq, sk = s.shape[-2], s.shape[-1]
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        keep = q_pos >= k_pos
        if window > 0:
            keep = jnp.logical_and(keep, q_pos - k_pos < window)
        s = jnp.where(keep, s, _NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32), preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new


# test hook: force the Pallas hop path off-TPU (kernels in interpret mode)
_FORCE_FLASH_HOPS = False


def _use_flash_hops(chunk: int, d: int) -> bool:
    from .attention import _MXU_HEAD_DIMS, _on_tpu

    if _FORCE_FLASH_HOPS:
        return True
    return _on_tpu(None) and chunk % 128 == 0 and d in _MXU_HEAD_DIMS


def _ring_hops(k, v, carry0, do_step, *, axis_name: str, is_causal: bool,
               chunk: int, window: int = 0):
    """Shared ring skeleton: rotate k/v with ``ppermute``, apply ``do_step``
    per hop, skip fully-masked hops under causal masking.

    The causal skip is a real branch: shard_map bodies are per-device scalar
    programs, not vmapped lanes, so ``lax.cond`` lowers to an HLO conditional.
    The final hop's rotation is NOT issued — XLA cannot DCE a collective
    inside a loop, so the loop runs n-1 hops-with-rotation and the last hop
    happens outside it.
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop(step, k_cur, v_cur, inner):
        # after `step` rotations this device holds the chunk that started at
        # ring position (my_idx - step) mod n
        k_idx = jax.lax.rem(my_idx - step + n, n)
        q_offset = my_idx * chunk
        k_offset = k_idx * chunk
        update = functools.partial(do_step, k_cur, v_cur, q_offset, k_offset)
        if is_causal:
            # whole chunk strictly in the future — or, with a sliding
            # window, entirely beyond the band in the past — contributes
            # nothing: skip the hop's compute (a real HLO branch)
            fully_masked = k_offset > q_offset + chunk - 1
            if window > 0:
                fully_masked = jnp.logical_or(
                    fully_masked, q_offset - (k_offset + chunk - 1) >= window
                )
            return jax.lax.cond(fully_masked, lambda args: args, update, inner)
        return update(inner)

    def body(step, carry):
        k_cur, v_cur, inner = carry
        inner = hop(step, k_cur, v_cur, inner)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_next, v_next, inner

    k_last, v_last, inner = jax.lax.fori_loop(0, n - 1, body, (k, v, carry0))
    return hop(n - 1, k_last, v_last, inner)


def _ring_attention_local(q, k, v, *, axis_name: str, is_causal: bool,
                          scale: float, window: int = 0):
    """Per-device body under shard_map: q stays, k/v ride the ring.

    Two inner-block engines on the shared ``_ring_hops`` skeleton:

    * **Pallas hop kernel** (TPU, MXU-tileable chunks): each hop calls
      ``flash_attention_hop`` — offset-aware causal masking with tile-level
      skipping inside the kernel — and hops merge by the logsumexp rule.
      Diagonal hops do triangle work only.  The causal saving is in
      FLOPs/energy, not ring latency — hops are lockstep (ppermute), so the
      wall-clock lower bound is the busiest device's diagonal+past hops.
    * **jnp online-softmax** (CPU tests, odd shapes): the m/l/acc recurrence,
      fused by XLA.
    """
    b, h, sq, d = q.shape
    chunk = sq  # local chunk length (== global_seq / n)

    if _use_flash_hops(chunk, d):
        from .flash_attention import flash_attention_hop

        def do_step(k_cur, v_cur, q_offset, k_offset, inner):
            out, lse = inner
            o_hop, lse_hop = flash_attention_hop(
                q, k_cur, v_cur, q_offset, k_offset, is_causal, scale, window
            )
            lse_new = jnp.logaddexp(lse, lse_hop)
            w_old = jnp.exp(lse - lse_new)[..., None]
            w_hop = jnp.exp(lse_hop - lse_new)[..., None]
            return out * w_old + o_hop.astype(jnp.float32) * w_hop, lse_new

        carry0 = (
            jnp.zeros((b, h, sq, d), dtype=jnp.float32),
            jnp.full((b, h, sq), _NEG_INF, dtype=jnp.float32),
        )
        out, _ = _ring_hops(
            k, v, carry0, do_step, axis_name=axis_name, is_causal=is_causal,
            chunk=chunk, window=window,
        )
        return out.astype(q.dtype)

    q32 = q.astype(jnp.float32)

    def do_step(k_cur, v_cur, q_offset, k_offset, inner):
        m, l, acc = inner
        return _block_update(
            q32, k_cur.astype(jnp.float32), v_cur, m, l, acc,
            q_offset, k_offset, scale, is_causal, window,
        )

    carry0 = (
        jnp.full((b, h, sq, 1), _NEG_INF, dtype=jnp.float32),
        jnp.zeros((b, h, sq, 1), dtype=jnp.float32),
        jnp.zeros((b, h, sq, d), dtype=jnp.float32),
    )
    m, l, acc = _ring_hops(
        k, v, carry0, do_step, axis_name=axis_name, is_causal=is_causal,
        chunk=chunk, window=window,
    )
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l).astype(q.dtype)


def _ulysses_attention_local(
    q, k, v, *, axis_name: str, is_causal: bool, scale: float, window: int = 0
):
    """Per-device body of Ulysses-style (all-to-all) sequence parallelism.

    Instead of rotating k/v around a ring, an ``all_to_all`` re-partitions
    the problem: heads split across the ``sp`` devices, each device then
    holding h/n heads at FULL sequence length, runs ordinary causal
    attention locally (the Pallas flash kernel on TPU — no per-hop masking
    logic at all), and a second ``all_to_all`` restores the seq-sharded
    layout.  q/k/v are stacked so the inbound redistribution is ONE
    collective (two per attention call total, vs the ring's n-1 ppermute
    hops): better at moderate sequence lengths when h >= n; the ring wins
    when per-device memory must stay O(s/n) (Ulysses holds full-seq k/v
    for its head slice).
    """
    # heads -> devices, seq gathered: (3, b, h, s/n, d) -> (3, b, h/n, s, d)
    qkv = jnp.stack([q, k, v])
    qkv = jax.lax.all_to_all(qkv, axis_name, split_axis=2, concat_axis=3, tiled=True)
    from .attention import sdpa_tpu

    out = sdpa_tpu(qkv[0], qkv[1], qkv[2], is_causal=is_causal, scale=scale,
                   window=window)
    # seq -> devices, heads gathered back
    return jax.lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1, tiled=True)


def _shard_mapped_attention(
    local_fn, q, k, v, mesh, is_causal, scale, axis_name, batch_axes, window=0
):
    """Shared wrapper: resolve mesh/scale, sp=1 fast path, shard_map setup."""
    if window > 0 and not is_causal:
        # validate HERE so sp>1 meshes fail like sp=1 does (the per-device
        # bodies only band-mask under is_causal — silently ignoring the
        # window on one mesh shape and raising on another is worse)
        raise ValueError("sliding window requires is_causal=True")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if mesh is None:
        from ..state import AcceleratorState

        mesh = AcceleratorState().mesh
    if mesh.shape.get(axis_name, 1) == 1:
        return None, mesh, scale  # caller runs the single-device path
    batch_spec = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1) or None
    spec = P(batch_spec, None, axis_name, None)
    from ..parallel.mesh import shard_map_compat

    fn = shard_map_compat(
        functools.partial(
            local_fn, axis_name=axis_name, is_causal=is_causal, scale=scale,
            window=window,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn, mesh, scale


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    is_causal: bool = False,
    scale: Optional[float] = None,
    axis_name: str = "sp",
    batch_axes: tuple = ("dp", "fsdp"),
    window: int = 0,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses-style) sequence-parallel attention.

    Same contract as :func:`ring_attention` — (batch, heads, seq, head_dim)
    with seq sharded over ``axis_name`` — but the parallelism re-partitions
    heads across devices with an ``all_to_all`` pair instead of streaming
    k/v chunks.  Requires ``heads % sp_size == 0``; falls back to the ring
    otherwise.  Select per model via ``SequenceParallelPlugin(mode=...)``.
    """
    fn, mesh, scale = _shard_mapped_attention(
        _ulysses_attention_local, q, k, v, mesh, is_causal, scale, axis_name,
        batch_axes, window,
    )
    if fn is None:
        from .attention import sdpa_tpu

        return sdpa_tpu(q, k, v, is_causal=is_causal, scale=scale, window=window)
    if q.shape[1] % mesh.shape[axis_name] != 0:
        return ring_attention(
            q, k, v, mesh, is_causal, scale, axis_name, batch_axes, window
        )
    return fn(q, k, v)


_SP_MODES = ("ring", "all_to_all")


def sequence_parallel_attention(
    q,
    k,
    v,
    mesh: Optional[Mesh] = None,
    is_causal: bool = False,
    scale: Optional[float] = None,
    axis_name: str = "sp",
    batch_axes: tuple = ("dp", "fsdp"),
    mode: str = "ring",
    window: int = 0,
):
    """Dispatch on ``SequenceParallelPlugin.mode``: "ring" | "all_to_all"."""
    if mode not in _SP_MODES:
        raise ValueError(f"unknown sequence-parallel mode {mode!r}; use one of {_SP_MODES}")
    impl = ulysses_attention if mode == "all_to_all" else ring_attention
    return impl(q, k, v, mesh, is_causal, scale, axis_name, batch_axes, window)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Mesh] = None,
    is_causal: bool = False,
    scale: Optional[float] = None,
    axis_name: str = "sp",
    batch_axes: tuple = ("dp", "fsdp"),
    window: int = 0,
) -> jax.Array:
    """Sequence-parallel attention over (batch, heads, seq, head_dim) arrays
    whose seq dimension is sharded on the ``axis_name`` mesh axis.

    Differentiable (pure jnp + collectives inside shard_map — JAX transposes
    ppermute automatically), jit-compatible, composes with dp/fsdp batch
    sharding.  ``window`` > 0 (causal sliding band): ring hops whose chunk
    lies entirely beyond the band are skipped as whole branches — with
    window <= chunk each device runs at most TWO hops regardless of ring
    size, so windowed long-context cost stops growing with sp.
    """
    fn, mesh, scale = _shard_mapped_attention(
        _ring_attention_local, q, k, v, mesh, is_causal, scale, axis_name,
        batch_axes, window,
    )
    if fn is None:
        from .attention import sdpa_tpu

        return sdpa_tpu(q, k, v, is_causal=is_causal, scale=scale, window=window)
    return fn(q, k, v)
