"""Config template zoo: every shipped YAML loads, merges, and launches.

Reference ships copy-paste configs for each topology
(/root/reference/examples/config_yaml_templates/README.md, fsdp.yaml:1);
these tests pin that each TPU-native template (a) parses through the real
config loader, (b) merges into launch args the way `accelerate-tpu launch
--config_file` would, and (c) the CPU-simulation template drives run_me.py
through the actual launcher subprocess.
"""

import os
import subprocess
import sys

import pytest

from accelerate_tpu.commands.config.config_args import load_config_from_file
from accelerate_tpu.commands.launch import _merge_config_defaults, launch_command_parser

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TEMPLATES = os.path.join(REPO, "examples", "config_yaml_templates")
YAMLS = sorted(f for f in os.listdir(TEMPLATES) if f.endswith(".yaml"))


def test_zoo_is_complete():
    assert {
        "single_chip.yaml", "v5e_8.yaml", "multi_host.yaml",
        "fsdp.yaml", "fp8.yaml", "cpu_simulation.yaml",
    } <= set(YAMLS)


@pytest.mark.parametrize("name", YAMLS)
def test_template_loads_and_merges(name):
    path = os.path.join(TEMPLATES, name)
    config = load_config_from_file(path)  # validates keys + types
    parser = launch_command_parser()
    args = parser.parse_args(["--config_file", path, "run_me.py"])
    _merge_config_defaults(args)
    assert args.mixed_precision == config.mixed_precision
    if name == "fsdp.yaml":
        assert args.fsdp_size == 8 and args.use_fsdp
        assert args.fsdp_sharding_strategy == "FULL_SHARD"
    if name == "multi_host.yaml":
        assert args.num_processes == 2
        assert args.main_process_ip == "10.0.0.2"
    if name == "cpu_simulation.yaml":
        assert args.num_virtual_devices == 8
        assert args.fsdp_size == 2 and args.tp_size == 2


def test_cpu_simulation_template_launches_run_me():
    """`accelerate-tpu launch --config_file cpu_simulation.yaml run_me.py`
    end-to-end: the child resolves an 8-virtual-device fsdp×tp mesh."""
    env = dict(
        os.environ,
        PYTHONPATH=os.pathsep.join(
            p for p in (REPO, os.environ.get("PYTHONPATH", "")) if p
        ),
    )
    env.pop("ACCELERATE_MIXED_PRECISION", None)
    result = subprocess.run(
        [
            sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
            "launch",
            "--config_file", os.path.join(TEMPLATES, "cpu_simulation.yaml"),
            os.path.join(TEMPLATES, "run_me.py"),
        ],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "Accelerator state" in result.stdout
    assert "fsdp" in result.stdout.lower()
