"""FP8 + quantization tests (reference: SURVEY.md §2.4 precision backends;
fp8 benchmark scripts assert convergence parity vs bf16)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator
from accelerate_tpu.nn import Tensor
from accelerate_tpu.test_utils.testing import slow
from accelerate_tpu.utils.dataclasses import FP8RecipeKwargs
from accelerate_tpu.utils.fp8 import FP8Linear, convert_to_float8_training
from accelerate_tpu.utils.quantization import (
    QuantizationConfig,
    QuantizedLinear,
    dequantize_weight,
    load_and_quantize_model,
    quantize_weight,
    replace_with_quantized_layers,
)


class TinyMLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc_in = nn.Linear(8, 16)
        self.mid = nn.Linear(16, 16)
        self.fc_out = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc_out(nn.F.gelu(self.mid(nn.F.gelu(self.fc_in(x)))))


# --------------------------------------------------------------------- fp8
def test_fp8_linear_matches_fp32_within_tolerance():
    nn.manual_seed(0)
    lin = nn.Linear(16, 8)
    fp8 = FP8Linear.from_linear(lin)
    x = Tensor(np.random.default_rng(0).normal(size=(4, 16)).astype(np.float32))
    with nn.no_grad():
        ref = lin(x).data
        out = fp8(x).data
    # e4m3 has ~2 decimal digits; relative error on a dot of 16 terms
    assert np.allclose(np.asarray(out), np.asarray(ref), rtol=0.1, atol=0.1)


def test_fp8_linear_backward_flows():
    nn.manual_seed(1)
    fp8 = FP8Linear(8, 4)
    x = Tensor(np.random.default_rng(1).normal(size=(2, 8)).astype(np.float32))
    loss = fp8(x).sum()
    loss.backward()
    assert fp8.weight.grad is not None
    assert np.isfinite(np.asarray(fp8.weight.grad)).all()


def test_convert_to_float8_skips_first_and_last():
    nn.manual_seed(0)
    model = TinyMLP()
    convert_to_float8_training(model)
    assert type(model.fc_in).__name__ == "Linear"  # first kept
    assert isinstance(model.mid, FP8Linear)
    assert type(model.fc_out).__name__ == "Linear"  # last kept


def test_fp8_conversion_preserves_weights_and_state_dict_keys():
    nn.manual_seed(0)
    model = TinyMLP()
    before = {k: np.asarray(v) for k, v in model.state_dict().items()}
    convert_to_float8_training(model)
    after = model.state_dict()
    for key, value in before.items():
        assert key in after
        np.testing.assert_array_equal(value, np.asarray(after[key]))


def test_accelerator_fp8_prepare_and_train_step():
    nn.manual_seed(0)
    acc = Accelerator(mixed_precision="fp8")
    model = TinyMLP()
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)
    assert isinstance(model.mid, FP8Linear)
    assert model.mid.weight.dtype == jnp.bfloat16

    x = Tensor(np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32))
    y = Tensor(np.zeros((4, 4), dtype=np.float32))
    losses = []
    for _ in range(5):
        out = model(x)
        loss = nn.F.mse_loss(out, y)
        acc.backward(loss)
        opt.step()
        opt.zero_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0]  # training must make progress in fp8


@slow
def test_fp8_convergence_parity_vs_bf16():
    """fp8 training must track the bf16 loss curve within tolerance over
    200+ steps — the reference asserts exactly this for its fp8 backends
    (/root/reference/benchmarks/fp8/torchao/non_distributed.py:1); VERDICT
    r3 item 2 asks for the same evidence here before fp8 can be a
    recommended mode."""
    rng = np.random.default_rng(0)
    x_all = rng.normal(size=(512, 16)).astype(np.float32)
    w_true = rng.normal(size=(16, 4)).astype(np.float32)
    y_all = (np.tanh(x_all @ w_true) + 0.05 * rng.normal(size=(512, 4))).astype(
        np.float32
    )

    def run(precision):
        Accelerator._reset_state()
        nn.manual_seed(0)
        acc = Accelerator(mixed_precision=precision)
        model = nn.Sequential(
            nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 32), nn.ReLU(),
            nn.Linear(32, 4),
        )
        opt = optim.AdamW(model.parameters(), lr=1e-2)
        model, opt = acc.prepare(model, opt)

        def step_fn(xb, yb):
            opt.zero_grad()
            loss = nn.F.mse_loss(model(Tensor(xb)), Tensor(yb))
            acc.backward(loss)
            opt.step()
            return loss

        step = acc.compile_step(step_fn)
        losses = []
        for i in range(220):
            lo = (i * 32) % 512
            losses.append(
                float(step(jnp.asarray(x_all[lo : lo + 32]), jnp.asarray(y_all[lo : lo + 32])))
            )
        return losses

    bf16 = run("bf16")
    fp8 = run("fp8")
    # both converge, and the final fp8 loss is within 20% of bf16 (e4m3
    # matmuls on a 32-wide MLP; the reference's torchao suite uses the same
    # order of tolerance for end-loss comparison)
    assert bf16[-1] < bf16[0] * 0.5 and fp8[-1] < fp8[0] * 0.5
    tail_bf16 = float(np.mean(bf16[-20:]))
    tail_fp8 = float(np.mean(fp8[-20:]))
    assert abs(tail_fp8 - tail_bf16) <= 0.2 * tail_bf16 + 1e-3, (
        f"fp8 tail loss {tail_fp8:.4f} vs bf16 {tail_bf16:.4f}"
    )


def test_fp8_delayed_scaling_mode():
    nn.manual_seed(0)
    fp8 = FP8Linear(8, 8, recipe=FP8RecipeKwargs(amax_history_len=4))
    fp8.set_delayed(True)
    x = Tensor(np.random.default_rng(2).normal(size=(2, 8)).astype(np.float32))
    with nn.no_grad():
        fp8(x)
        fp8(x)
    hist = np.asarray(fp8.amax_history.data)
    assert (hist[-2:] > 0).all()  # history rolled twice


# ----------------------------------------------------------- quantization
@pytest.mark.parametrize("bits", [8, 4])
def test_quantize_dequantize_roundtrip(bits):
    w = np.random.default_rng(0).normal(size=(16, 32)).astype(np.float32)
    q, scale = quantize_weight(w, bits)
    back = np.asarray(dequantize_weight(jnp.asarray(q), jnp.asarray(scale), bits))
    qmax = 127 if bits == 8 else 7
    # max error is half a quantisation step per channel
    step = np.abs(w).max(axis=1, keepdims=True) / qmax
    assert (np.abs(back - w) <= step * 0.5 + 1e-6).all()


@pytest.mark.parametrize("bits", [8, 4])
def test_quantized_linear_forward(bits):
    nn.manual_seed(0)
    lin = nn.Linear(32, 8)
    qlin = QuantizedLinear.from_weight(lin.weight, lin.bias, bits=bits)
    x = Tensor(np.random.default_rng(1).normal(size=(4, 32)).astype(np.float32))
    with nn.no_grad():
        ref = np.asarray(lin(x).data)
        out = np.asarray(qlin(x).data)
    tol = 0.05 if bits == 8 else 0.3
    assert np.abs(out - ref).max() < tol


def test_quantized_linear_int8_compute():
    """W8A8 mode: int8xint8->int32 dot with dynamic activation scales stays
    close to the fp32 reference and handles 3-D activations."""
    nn.manual_seed(0)
    lin = nn.Linear(64, 16)
    qlin = QuantizedLinear.from_weight(lin.weight, lin.bias, compute="int8")
    rng = np.random.default_rng(2)
    for shape in [(4, 64), (2, 5, 64)]:
        x = Tensor(rng.normal(size=shape).astype(np.float32))
        with nn.no_grad():
            ref = np.asarray(lin(x).data)
            out = np.asarray(qlin(x).data)
        assert out.shape == ref.shape
        # two quantisation sources (weight + activation) → looser tolerance
        assert np.abs(out - ref).max() < 0.1, np.abs(out - ref).max()
    # int4 cannot ride the int8 path
    with pytest.raises(ValueError, match="int8"):
        QuantizedLinear.from_weight(lin.weight, None, bits=4, compute="int8")


def test_quantization_config_int8_compute_validation():
    cfg = QuantizationConfig(load_in_8bit=True, compute="int8")
    assert cfg.compute == "int8"
    with pytest.raises(ValueError, match="compute"):
        QuantizationConfig(load_in_8bit=True, compute="fp4")
    with pytest.raises(ValueError, match="int8"):
        QuantizationConfig(load_in_4bit=True, compute="int8")


def test_int8_backward_bf16_upstream():
    """STE cotangent returns in the primal dtype: a bf16 upstream node must
    not crash the vjp (review finding: hardcoded fp32 did)."""
    import jax.numpy as jnp

    nn.manual_seed(0)
    lin = nn.Linear(16, 8)
    qlin = QuantizedLinear.from_weight(lin.weight, lin.bias, compute="int8")
    x = Tensor(jnp.ones((2, 16), jnp.bfloat16), requires_grad=True)
    h = x * 2.0  # upstream bf16 tape node
    (qlin(h) ** 2).sum().backward()
    assert x.grad is not None and np.isfinite(np.asarray(x.grad, np.float32)).all()


def test_quantize_root_fused_module_guarded():
    """A fused block passed AS the model root still triggers the guard
    (review finding: startswith(p + '.') never matched the root '')."""
    from accelerate_tpu.models.opt import OPTConfig, OPTDecoderLayer
    from accelerate_tpu.utils.quantization import replace_with_quantized_layers

    nn.manual_seed(0)
    layer = OPTDecoderLayer(OPTConfig.tiny())
    with pytest.raises(NotImplementedError, match="param_tensors"):
        replace_with_quantized_layers(layer, QuantizationConfig(load_in_8bit=True))


def test_jnp_left_operand_keeps_tape():
    """raw jnp array on the LEFT of a Tensor still defers to the reflected
    op and stays gradient-tracked (regression: __jax_array__ broke this)."""
    import jax.numpy as jnp

    x = Tensor(jnp.ones((3,)), requires_grad=True)
    y = jnp.ones((3,)) + x
    assert isinstance(y, Tensor)
    y.sum().backward()
    np.testing.assert_array_equal(np.asarray(x.grad), np.ones(3))


def test_int8_compute_backward_not_dead():
    """STE backward: gradients flow through the int8 dot to upstream layers
    and match the dequant-path gradients closely (review finding: the naive
    round/clip vjp was silently zero)."""
    nn.manual_seed(0)
    lin = nn.Linear(32, 8)
    q_int8 = QuantizedLinear.from_weight(lin.weight, lin.bias, compute="int8")
    q_deq = QuantizedLinear.from_weight(lin.weight, lin.bias)
    x_np = np.random.default_rng(4).normal(size=(4, 32)).astype(np.float32)

    def grad_through(layer):
        x = Tensor(jnp.asarray(x_np))
        x.requires_grad = True
        (layer(x) ** 2).sum().backward()
        return np.asarray(x.grad)

    g8 = grad_through(q_int8)
    gd = grad_through(q_deq)
    assert np.abs(g8).max() > 0.1  # not dead
    # same weight linearization up to activation-quant noise in the cotangent
    assert np.abs(g8 - gd).max() / (np.abs(gd).max() + 1e-9) < 0.15


def test_quantize_fused_family_exemption_and_atomic_failure():
    """keep_in_fp32_modules exempting the fused trunk lets non-fused linears
    quantize; a conflicting call fails BEFORE mutating anything."""
    from accelerate_tpu.models import OPTConfig, OPTForCausalLM
    from accelerate_tpu.nn.layers import Linear
    from accelerate_tpu.utils.quantization import replace_with_quantized_layers

    nn.manual_seed(0)
    model = OPTForCausalLM(OPTConfig.tiny())
    with pytest.raises(NotImplementedError, match="param_tensors"):
        replace_with_quantized_layers(model, QuantizationConfig(load_in_8bit=True))
    # atomic: nothing was swapped by the failed call
    assert not any(isinstance(m, QuantizedLinear) for m in model.modules())
    # exempting the fused trunk succeeds and quantizes only NON-fused
    # linears (OPT-tiny's lm_head-adjacent projections)
    replace_with_quantized_layers(
        model,
        QuantizationConfig(load_in_8bit=True, keep_in_fp32_modules=["layers"]),
    )
    quantized = [
        n for n, m in model.named_modules() if isinstance(m, QuantizedLinear)
    ]
    assert quantized, "non-fused linears should quantize under the exemption"
    assert not any(".layers." in n or n.startswith("layers") for n in quantized)


def test_replace_layers_int8_compute_mode():
    """int8-compute model ≈ dequant-compute model: the int8 dot adds only
    activation-quantization noise on top of the shared weight quantization."""
    from accelerate_tpu.utils.quantization import replace_with_quantized_layers

    def build(compute):
        nn.manual_seed(0)
        model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(), nn.Linear(16, 8))
        replace_with_quantized_layers(
            model, QuantizationConfig(load_in_8bit=True, compute=compute)
        )
        return model

    m8, md = build("int8"), build("dequant")
    quant = [m for m in m8.modules() if isinstance(m, QuantizedLinear)]
    assert quant and all(m.compute == "int8" for m in quant)
    x = Tensor(np.random.default_rng(3).normal(size=(2, 16)).astype(np.float32))
    with nn.no_grad():
        out8 = np.asarray(m8(x).data)
        outd = np.asarray(md(x).data)
    assert np.isfinite(out8).all()
    assert np.abs(out8 - outd).max() < 0.05


def test_int4_memory_is_halved():
    lin_w = np.zeros((16, 32), dtype=np.float32)
    q8, _ = quantize_weight(lin_w, 8)
    q4, _ = quantize_weight(lin_w, 4)
    assert q4.nbytes == q8.nbytes // 2


def test_replace_with_quantized_layers_respects_skip():
    nn.manual_seed(0)
    model = TinyMLP()
    config = QuantizationConfig(load_in_8bit=True, skip_modules=["fc_out"])
    replace_with_quantized_layers(model, config)
    assert isinstance(model.fc_in, QuantizedLinear)
    assert isinstance(model.mid, QuantizedLinear)
    assert type(model.fc_out).__name__ == "Linear"


def test_load_and_quantize_model_from_meta(tmp_path):
    """bnb-style path: meta init → quantize straight from the checkpoint."""
    from accelerate_tpu.big_modeling import init_empty_weights
    from accelerate_tpu.checkpointing import save_model_weights

    nn.manual_seed(0)
    source = TinyMLP()
    save_model_weights(source.state_dict(), str(tmp_path))

    with init_empty_weights():
        empty = TinyMLP()
    config = QuantizationConfig(load_in_8bit=True)
    load_and_quantize_model(empty, config, weights_location=str(tmp_path))

    x = Tensor(np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32))
    with nn.no_grad():
        ref = np.asarray(source(x).data)
        out = np.asarray(empty(x).data)
    assert np.abs(out - ref).max() < 0.1


def test_quantization_config_validation():
    with pytest.raises(ValueError):
        QuantizationConfig(load_in_8bit=True, load_in_4bit=True)
    with pytest.raises(ValueError):
        QuantizationConfig()
