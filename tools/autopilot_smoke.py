#!/usr/bin/env python
"""autopilot_smoke — `make autopilot-smoke`: prove the CLOSED elastic loop
end-to-end on 4 virtual CPU devices in seconds (docs/elastic.md §autopilot).

Tiny GPT at dp=4 with the fleet armed AND the autopilot driving — the
training loop below does NO polling: no ``should_resize`` read, no
``resize()`` call, it just steps batches.  The fault plan injects a
``host_lost`` before step 2's dispatch and a ``host_gained`` before step
4's; the autopilot alone drives dp 4→2→4 from the captured-step dispatch
path (drain → re-mesh → reshard → AOT prewarm each way), with every
decision landing as a ``kind="autopilot"`` record.  The scenario runs
TWICE against one AOT store: the warm pass's post-resize first step in
EACH direction must deserialize a stored program (zero trace/compile phase
time on every build).  A third leg injects a ``signal_storm`` flapping the
skew signal across the threshold: the debounce/hysteresis window must
suppress it — decision records present, exactly zero resizes.

Exit 0 = autopilot shrank and grew back unattended, losses within the
documented rtol of an uninterrupted dp=4 run both passes, zero
trace/compile on the warm pass's builds, and the storm suppressed.
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STEPS = 6
HOST_LOST_AT = 2
HOST_GAINED_AT = 4
LOSS_RTOL = 1e-3  # documented resize tolerance: the dp reduce order moves


def main() -> int:
    import jax
    import numpy as np

    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import (
        Accelerator,
        CompilationCacheKwargs,
        FleetKwargs,
        TelemetryKwargs,
    )
    from accelerate_tpu.data_loader import batch_to_global_array
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel

    errors: list[str] = []
    tmp = tempfile.mkdtemp(prefix="atpu_autopilot_")
    cache_dir = os.path.join(tmp, "aot")

    def build(plan=None, autopilot=None):
        Accelerator._reset_state()
        jax.clear_caches()
        nn.manual_seed(0)
        handlers = [TelemetryKwargs(enabled=True)]
        if plan is not None or autopilot is not None:
            handlers += [
                FleetKwargs(
                    enabled=True,
                    autopilot=autopilot,
                    fault_plan=plan,
                    checkpoint_dir=os.path.join(tmp, "drain"),
                ),
                CompilationCacheKwargs(cache_dir=cache_dir),
            ]
        acc = Accelerator(kwargs_handlers=handlers)
        model = GPTLMHeadModel(
            GPTConfig(vocab_size=256, n_positions=64, n_embd=32, n_layer=1, n_head=2)
        )
        opt = optim.AdamW(model.parameters(), lr=1e-3)
        model, opt = acc.prepare(model, opt)

        def step_fn(ids):
            opt.zero_grad()
            out = model(ids, labels=ids)
            acc.backward(out["loss"])
            opt.step()
            return out["loss"]

        rng = np.random.default_rng(0)
        raw = [rng.integers(0, 256, (8, 32), dtype=np.int32) for _ in range(STEPS)]
        return acc, acc.compile_step(step_fn), raw

    def run_autopilot(tag):
        acc, step, raw = build(
            plan=f"host_lost:step={HOST_LOST_AT};host_gained:step={HOST_GAINED_AT}",
            autopilot=True,
        )
        dp0 = dict(acc.mesh.shape)["dp"]
        if dp0 != 4:
            errors.append(f"{tag}: expected dp=4 start, got {dict(acc.mesh.shape)}")
        # THE loop under test: no fleet polling, no resize call — the batch
        # is placed on the LIVE mesh each iteration and that is all the
        # caller contributes to elasticity
        losses = [
            float(step(batch_to_global_array(b, mesh=acc.mesh))) for b in raw
        ]
        if acc.fleet.resizes_total != 1 or acc.fleet.grows_total != 1:
            errors.append(
                f"{tag}: expected exactly 1 shrink + 1 grow, got "
                f"{acc.fleet.resizes_total} resizes / {acc.fleet.grows_total} grows"
            )
        if dict(acc.mesh.shape)["dp"] != dp0:
            errors.append(
                f"{tag}: fleet did not grow back to dp={dp0}: "
                f"{dict(acc.mesh.shape)}"
            )
        decisions = [e for e in acc.fleet.events if e.get("kind") == "autopilot"]
        fired = [(d["signal"], d["action"]) for d in decisions if d.get("fired")]
        if fired != [("host_lost", "shrink"), ("host_gained", "grow")]:
            errors.append(f"{tag}: unexpected fired decisions: {fired}")
        events = [e["event"] for e in acc.fleet.events]
        for expected in ("host_lost", "host_gained", "grow_rendezvous"):
            if expected not in events:
                errors.append(f"{tag}: missing fleet event {expected}: {events}")
        return losses, acc

    # uninterrupted dp=4 reference over the same batches
    acc_ref, step, raw = build()
    reference = [
        float(step(batch_to_global_array(b, mesh=acc_ref.mesh))) for b in raw
    ]

    # pass 1 (cold store): the shrink compiles+stores the dp=2 program; the
    # initial steps store the dp=4 one
    losses1, acc1 = run_autopilot("cold")
    if acc1.aot_cache.stores < 1:
        errors.append(f"cold: no AOT stores recorded ({acc1.aot_cache.stores})")

    # pass 2 (warm store): EVERY build — the first step, the post-shrink
    # step, the post-grow step — must deserialize (zero trace/compile)
    losses2, acc2 = run_autopilot("warm")
    built = [r for r in acc2.telemetry.timeline.records() if r.built]
    if len(built) < 3:
        errors.append(f"warm: expected >= 3 builds (start/shrink/grow), got {len(built)}")
    for record in built:
        if record.trace_ms != 0.0 or record.compile_ms != 0.0:
            errors.append(
                f"warm: build at step {record.step} recompiled "
                f"(trace={record.trace_ms}ms compile={record.compile_ms}ms) — "
                "a post-resize program was not served from the store"
            )
    hits = sum(1 for e in acc2.telemetry.aot_cache_events if e["event"] == "hit")
    if hits < 3:
        errors.append(f"warm: expected >= 3 aot_cache hits, got {hits}")

    for tag, losses in (("cold", losses1), ("warm", losses2)):
        if len(losses) == len(reference) and not np.allclose(
            losses, reference, rtol=LOSS_RTOL
        ):
            errors.append(
                f"{tag}: losses diverged beyond rtol={LOSS_RTOL}: "
                f"{losses} vs {reference}"
            )

    # storm leg: a flapping skew signal must be SUPPRESSED by the
    # debounce/hysteresis window — records written, zero resizes
    acc3, step, raw = build(plan="signal_storm:step=1,times=8", autopilot=True)
    for b in raw:
        float(step(batch_to_global_array(b, mesh=acc3.mesh)))
    if acc3.fleet.resizes_total != 0 or acc3.fleet.grows_total != 0:
        errors.append(
            f"storm: the flapping signal resized the fleet "
            f"({acc3.fleet.resizes_total} resizes / {acc3.fleet.grows_total} grows)"
        )
    suppressed = [
        e
        for e in acc3.fleet.events
        if e.get("kind") == "autopilot" and e.get("suppressed")
    ]
    if len(suppressed) < 2:
        errors.append(
            f"storm: expected suppressed decision records, got {len(suppressed)}"
        )

    for error in errors:
        print(f"autopilot-smoke: FAIL: {error}", file=sys.stderr)
    if errors:
        return 1
    print(
        "autopilot-smoke: ok — autopilot alone drove dp 4→2 (host_lost at "
        f"step {HOST_LOST_AT}) and 2→4 (host_gained at step {HOST_GAINED_AT}), "
        f"losses within rtol={LOSS_RTOL} of the uninterrupted run both "
        f"passes; warm pass served every build from the AOT store ({hits} "
        f"hits, zero trace/compile); signal storm suppressed "
        f"({len(suppressed)} records, zero resizes)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
