"""Lowering-IR inspection harness: prove the fusion actually happened
(docs/kernels.md §IR contract).

A kernel that silently de-fuses — an all-gather the compiler re-separated
from its consuming matmuls, a page walk that re-materialized the full span
— would still pass every numerics test, because the reference and the
kernel compute the same values by design.  The only place the fusion is
visible is the IR the program commits to, so each check here lowers the
kernel path (``jax.jit(...).lower().compiler_ir()``) and asserts the
structural fact that IS the optimization:

* ``check_collective_matmul`` — NO ``all_gather`` op anywhere in the
  kernel path's IR; the transport is chunked ``collective_permute`` hops
  with one partial dot per chunk (and the Pallas partial-dot kernel is in
  the jaxpr).  The reference contrast (a plain dot on the dp-committed
  weight) partitions to exactly the all-gather-then-dot the kernel exists
  to remove.
* ``check_quantize_rs`` — the narrow wire dtype (``i8`` / ``f8E4M3FN``)
  appears in the kernel path's IR (the payload crosses narrow) and the
  rounding op lives INSIDE the kernel region (the grid loop the
  interpreter lowers to), not as a free-floating top-level op between HBM
  round-trips.
* ``check_paged_attention`` — no tensor of the batched full-page-span
  gather shape ``(slots, blocks_per_slot, n_kv, block_size, d)`` exists in
  the kernel path's IR; the reference path's IR contains exactly that
  materialization.

Every check returns the dict of facts it asserted (the smoke target prints
them); ``main()`` runs all three on a small geometry.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp

__all__ = [
    "stablehlo_text",
    "jaxpr_text",
    "check_collective_matmul",
    "check_quantize_rs",
    "check_paged_attention",
    "check_pipeline_layout",
    "run_all",
]

_ALL_GATHER_RE = re.compile(r"all[_-]gather", re.IGNORECASE)


def stablehlo_text(fn, *args, in_shardings=None) -> str:
    """The IR the program commits to at trace level —
    ``lower().compiler_ir()`` per the harness contract."""
    jitted = jax.jit(fn) if in_shardings is None else jax.jit(
        fn, in_shardings=in_shardings
    )
    return str(jitted.lower(*args).compiler_ir(dialect="stablehlo"))


def compiled_text(fn, *args, in_shardings=None) -> str:
    """Post-partitioning HLO (``lower().compile().as_text()``): where
    GSPMD's inserted collectives become visible — used for the reference
    contrasts, whose all-gather only exists after partitioning."""
    jitted = jax.jit(fn) if in_shardings is None else jax.jit(
        fn, in_shardings=in_shardings
    )
    return jitted.lower(*args).compile().as_text()


def jaxpr_text(fn, *args) -> str:
    return str(jax.make_jaxpr(fn)(*args))


def check_collective_matmul(mesh=None, *, m: int = 8, k_chunk: int = 8,
                            n_out: int = 16, interpret: bool = True) -> dict:
    """No unfused all-gather-then-dot: the kernel path's IR carries zero
    ``all_gather`` ops, ``dp`` chunked ``collective_permute`` hops feeding
    per-chunk dots, and the Pallas partial-dot kernel."""
    from .collective_matmul import collective_matmul, reference_collective_matmul

    if mesh is None:
        mesh = jax.make_mesh((len(jax.devices()),), ("dp",))
    n = mesh.shape["dp"]
    P = jax.sharding.PartitionSpec
    x = jnp.ones((m, k_chunk * n), jnp.float32)
    w = jnp.ones((k_chunk * n, n_out), jnp.float32)

    def fused(x, w):
        return collective_matmul(x, w, mesh=mesh, interpret=interpret)

    text = stablehlo_text(fused, x, w)
    facts = {
        "dp": n,
        "fused_has_all_gather": bool(_ALL_GATHER_RE.search(text)),
        "fused_permute_hops": text.count("collective_permute"),
        "fused_partial_dots": text.count("stablehlo.dot_general"),
        "pallas_partial_dot_in_jaxpr": "pallas_call" in jaxpr_text(fused, x, w),
    }
    assert not facts["fused_has_all_gather"], (
        "collective-matmul lowering still contains an all-gather — the "
        "monolithic gather the kernel exists to remove"
    )
    if n > 1:
        assert facts["fused_permute_hops"] >= 1, "no chunked transport hops"
        assert facts["fused_partial_dots"] >= n, (
            f"expected >= {n} per-chunk partial dots, found "
            f"{facts['fused_partial_dots']}"
        )
    assert facts["pallas_partial_dot_in_jaxpr"]
    # contrast: the reference dot on a dp-committed weight partitions into
    # all-gather-then-dot (fail-soft: some backends refuse to partition)
    try:
        ref_text = compiled_text(
            reference_collective_matmul, x, w,
            in_shardings=(
                jax.sharding.NamedSharding(mesh, P()),
                jax.sharding.NamedSharding(mesh, P("dp", None)),
            ),
        )
        facts["reference_has_all_gather"] = bool(_ALL_GATHER_RE.search(ref_text))
    except Exception as exc:  # pragma: no cover - backend-dependent
        facts["reference_has_all_gather"] = f"unavailable: {type(exc).__name__}"
    return facts


def check_quantize_rs(*, shape=(32, 16), wire_dtype=jnp.int8,
                      interpret: bool = True) -> dict:
    """Scale+round fused into the kernel region, narrow payload in the IR:
    the wire dtype appears (the boundary is crossed narrow) and the
    rounding op sits inside the kernel's lowered region, not between
    top-level HBM round-trips."""
    from .quantize_rs import fused_quantize_dequantize

    x = jnp.ones(shape, jnp.float32)

    def fused(x):
        return fused_quantize_dequantize(x, 0, wire_dtype, interpret=interpret)

    text = stablehlo_text(fused, x)
    narrow = "i8" if jnp.dtype(wire_dtype) == jnp.int8 else "f8E4M3"
    region_at = text.find("stablehlo.while")  # the kernel region's lowering
    round_at = text.find("round_nearest")
    facts = {
        "narrow_payload_in_ir": f"x{narrow}>" in text or f"x{narrow} " in text,
        "kernel_region_present": region_at >= 0,
        "round_inside_kernel_region": round_at > region_at >= 0,
        "pallas_call_in_jaxpr": "pallas_call" in jaxpr_text(fused, x),
    }
    assert facts["narrow_payload_in_ir"], (
        "quantize-rs lowering shows no narrow payload — the wire widened "
        "before the boundary"
    )
    assert facts["kernel_region_present"] and facts["pallas_call_in_jaxpr"]
    assert facts["round_inside_kernel_region"], (
        "rounding lowered outside the kernel region — the scale/round "
        "fusion did not happen"
    )
    return facts


def check_paged_attention(*, slots: int = 3, bps: int = 4, n_kv: int = 2,
                          block_size: int = 8, d: int = 16, heads: int = 4,
                          num_blocks: int = 10, interpret: bool = True) -> dict:
    """No full-span page materialization: the batched gather shape
    ``(slots, bps, n_kv, block_size, d)`` must not exist in the kernel
    path's IR (and must exist in the reference's — proving the assertion
    bites)."""
    from ...models.generation import cached_attention  # noqa: F401 (doc link)
    from .paged_attention import paged_attention, reference_paged_attention

    class _Cfg:
        sliding_window = 0

    q = jnp.ones((slots, heads, 1, d), jnp.float32)
    kp = jnp.ones((num_blocks, n_kv, block_size, d), jnp.float32)
    vp = jnp.ones((num_blocks, n_kv, block_size, d), jnp.float32)
    tables = jnp.zeros((slots, bps), jnp.int32)
    positions = jnp.zeros((slots,), jnp.int32)
    span_shape = f"tensor<{slots}x{bps}x{n_kv}x{block_size}x{d}x"

    def fused(q, kp, vp, t, p):
        return paged_attention(q, kp, vp, t, p, cfg=_Cfg(), interpret=interpret)

    def ref(q, kp, vp, t, p):
        return reference_paged_attention(q, kp, vp, t, p, cfg=_Cfg())

    fused_text = stablehlo_text(fused, q, kp, vp, tables, positions)
    ref_text = stablehlo_text(ref, q, kp, vp, tables, positions)
    facts = {
        "span_shape": span_shape + "...>",
        "fused_materializes_span": span_shape in fused_text,
        "reference_materializes_span": span_shape in ref_text,
        "pallas_call_in_jaxpr": "pallas_call"
        in jaxpr_text(fused, q, kp, vp, tables, positions),
    }
    assert not facts["fused_materializes_span"], (
        "paged-attention lowering materializes the batched full page span — "
        "the gather the kernel exists to remove"
    )
    assert facts["reference_materializes_span"], (
        "reference path no longer materializes the span — the inspection "
        "contrast lost its meaning; update the harness"
    )
    assert facts["pallas_call_in_jaxpr"]
    return facts


def check_pipeline_layout(mesh=None, *, num_stages: int = 2, virtual: int = 3,
                          num_layers: int = 6, dim: int = 8,
                          microbatches: int = 4) -> dict:
    """Zero permutation bytes in the committed interleaved 1F1B step
    (ISSUE 17 acceptance): the committed-layout lowering contains NO
    gather op and NO ``num_layers``-long index vector anywhere, while the
    legacy ``gather`` layout's lowering carries both — the in-program
    ``jnp.take`` of the layer order (and its inverse on the gradients)
    that the prepare-time commit removed."""
    from ...parallel.pipeline import apply_layer_order, pipeline_train_1f1b
    from ...parallel.plan import _layer_orders

    if mesh is None:
        mesh = jax.make_mesh((num_stages,), ("pp",))
    S, V, L = num_stages, virtual, num_layers
    ks = jax.random.split(jax.random.key(0), L)
    plain = {
        "w": jnp.stack([jax.random.normal(k, (dim, dim)) * 0.5 for k in ks]),
        "b": jnp.zeros((L, dim)),
    }
    committed = apply_layer_order(plain, _layer_orders(S, V, L)[0])
    batch = microbatches * 2
    x = jax.random.normal(jax.random.key(1), (batch, dim))
    labels = jax.random.normal(jax.random.key(2), (batch, dim))
    extra = {"head": jnp.eye(dim)}

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    def loss_fn(out, lbl, e):
        err = (out @ e["head"] - lbl) ** 2
        return err.sum(), jnp.float32(err.size)

    def lowered(layout, params):
        def f(p, x_, l_, e_):
            return pipeline_train_1f1b(
                stage_fn, p, x_, l_, e_, loss_fn, microbatches,
                mesh=mesh, virtual=V, layout=layout,
            )

        return stablehlo_text(f, params, x, labels, extra)

    gather_op = re.compile(r"stablehlo\.(?:dynamic_)?gather")
    idx_vec = f"tensor<{L}xi32>"  # the traced layer-order index vector
    committed_text = lowered("committed", committed)
    gather_text = lowered("gather", plain)
    facts = {
        "geometry": {"num_stages": S, "virtual": V, "num_layers": L},
        "committed_gather_ops": len(gather_op.findall(committed_text)),
        "committed_order_vectors": committed_text.count(idx_vec),
        "gather_gather_ops": len(gather_op.findall(gather_text)),
        "gather_order_vectors": gather_text.count(idx_vec),
    }
    assert facts["committed_gather_ops"] == 0, (
        "committed-layout 1F1B lowering still contains a gather — the "
        "stacked-layer permutation the prepare-time commit exists to remove"
    )
    assert facts["committed_order_vectors"] == 0, (
        "committed-layout lowering carries a layer-order index vector"
    )
    assert facts["gather_gather_ops"] > 0 and facts["gather_order_vectors"] > 0, (
        "gather-layout reference no longer traces the in-program permutation "
        "— the inspection contrast lost its meaning; update the harness"
    )
    return facts


def run_all(interpret: bool = True) -> dict:
    """All three checks on a small geometry (the kernel-smoke entry)."""
    out = {"quantize_rs": check_quantize_rs(interpret=interpret)}
    out["paged_attention"] = check_paged_attention(interpret=interpret)
    if len(jax.devices()) > 1:
        out["collective_matmul"] = check_collective_matmul(interpret=interpret)
    else:
        out["collective_matmul"] = {"skipped": "single device: no dp ring"}
    return out


def main() -> int:  # pragma: no cover - exercised via tools/kernel_smoke.py
    import json

    print(json.dumps(run_all(), indent=1, default=str))
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
