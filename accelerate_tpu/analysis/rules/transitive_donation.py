"""transitive-donation: a buffer is stashed by a helper, then donated.

``donation-reuse`` catches the *local* reads of a donated name.  What it
cannot see is an alias that **escaped** before the donation: a helper —
typically in another module — that stores its argument (appends it to a
cache, assigns it to ``self.something`` or a global) keeps a reference to
the buffer that outlives the call.  Donating the buffer afterwards leaves
that stored alias pointing at freed/overwritten device memory, even though
the local name was correctly rebound:

```python
# utils/stash.py
_HISTORY = []
def remember(x):
    _HISTORY.append(x)          # alias escapes into module state

# ops/train.py
from ..utils.stash import remember
g = jax.jit(f, donate_argnums=(0,))
def train(x):
    remember(x)                 # x now aliased by utils._HISTORY
    x = g(x)                    # BAD: donation frees the stored alias
    return x
```

Which helpers store which parameters comes from the whole-program graph
(``program.escaping_params`` per function, resolved through imports), so
the helper can live anywhere in the analyzed tree.  Donors are the same
whole-program set ``donation-reuse`` uses.
"""

from __future__ import annotations

import ast

from ..callgraph import dotted_name
from ..engine import Finding, Rule
from .donation import visible_donors


class _EscapeScanner(ast.NodeVisitor):
    """Track, in execution order: names whose buffer escaped into a storing
    helper, and donation events.  A donation of an escaped name fires."""

    def __init__(self, rule, module, fn_qual, donors, escapers):
        self.rule = rule
        self.module = module
        self.fn_qual = fn_qual
        self.donors = donors
        self.escapers = escapers  # visible name -> {"positions", "where"}
        self.escaped: dict[str, tuple[str, str]] = {}  # name -> (helper, where)
        self.findings: list[Finding] = []

    def visit_Assign(self, node):
        self.visit(node.value)
        for t in node.targets:
            self.visit(t)

    # AnnAssign/AugAssign default field order is target-first; evaluation is
    # value-first — without these, `x: Array = g(x)` would clear the escaped
    # state before the donor check sees the donation
    def visit_AnnAssign(self, node):
        if node.value is not None:
            self.visit(node.value)
        self.visit(node.target)

    def visit_AugAssign(self, node):
        self.visit(node.value)
        self.visit(node.target)

    def visit_Name(self, node):
        # rebinding a name detaches it from the OLD buffer; the stored alias
        # still exists but donating the NEW buffer is unrelated to it
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.escaped.pop(node.id, None)

    def visit_FunctionDef(self, node):
        pass  # nested defs scan as their own functions

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def _callee_name(self, fn) -> str:
        if isinstance(fn, ast.Name):
            return fn.id
        d = dotted_name(fn)
        return d or ""

    def visit_Call(self, node):
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)
        name = self._callee_name(node.func)
        esc = self.escapers.get(name)
        if esc:
            for pos in esc["positions"]:
                if pos < len(node.args) and isinstance(node.args[pos], ast.Name):
                    self.escaped.setdefault(
                        node.args[pos].id, (name, esc["where"])
                    )
        donated = self.donors.get(name)
        if donated:
            for pos in donated:
                if pos < len(node.args) and isinstance(node.args[pos], ast.Name):
                    buf = node.args[pos].id
                    if buf in self.escaped:
                        helper, where = self.escaped.pop(buf)
                        self.findings.append(
                            Finding(
                                self.rule.id,
                                self.module.rel_path,
                                node.lineno,
                                node.col_offset,
                                f"'{buf}' was stored by '{helper}' ({where}) "
                                f"before being donated to '{name}' — the "
                                "stored alias dangles once donation frees the "
                                "buffer; copy before stashing or drop the "
                                "donation",
                                symbol=self.fn_qual,
                            )
                        )


class TransitiveDonation(Rule):
    id = "transitive-donation"
    description = (
        "buffer stored by a helper (possibly in another module), then donated "
        "— the stored alias outlives the donation"
    )
    kind = "reachability"
    fix_hint = (
        "hand the helper a copy (helper(x.copy())) so the stored alias owns "
        "its buffer, or drop the donation"
    )

    def check(self, module, ctx):
        donors = visible_donors(module, ctx)
        escapers = ctx.escape_aliases.get(module.rel_path, {})
        if not donors or not escapers:
            return []
        findings: list[Finding] = []
        for info in module.callgraph.functions.values():
            scanner = _EscapeScanner(self, module, info.qualname, donors, escapers)
            for stmt in info.node.body:
                scanner.visit(stmt)
            findings.extend(scanner.findings)
        scanner = _EscapeScanner(self, module, "<module>", donors, escapers)
        for stmt in module.tree.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                scanner.visit(stmt)
        findings.extend(scanner.findings)
        return findings
