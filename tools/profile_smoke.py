#!/usr/bin/env python
"""profile_smoke — `make profile-smoke`: prove device-time attribution and
the live metrics endpoint end-to-end on CPU in seconds.

Tiny model, 3 captured steps with `profile_every_n=1` (every call sampled),
then assert:

* every step produced a DeviceStepRecord joined 1:1 to its StepRecord, with
  a NONEMPTY device split (busy > 0, compute > 0, op events parsed) whose
  busy+idle accounts for >= 80% of the step's wall clock (net of the
  recorded profiler stop/parse overhead);
* the hub's metrics endpoint serves valid Prometheus text exposition with
  the live counters in it;
* profiling introduced ZERO recompiles (telemetry forensics stream).
"""

import os
import sys
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the renderer's own sample-line grammar (incl. histogram `le` labels):
# one source of truth, so the smoke can never validate a different format
# than the endpoint emits
from accelerate_tpu.telemetry.metrics import SAMPLE_LINE_RE as _SAMPLE_RE  # noqa: E402


def main() -> int:
    import numpy as np
    import jax.numpy as jnp

    import accelerate_tpu.nn as nn
    import accelerate_tpu.optim as optim
    from accelerate_tpu import Accelerator, TelemetryKwargs
    from accelerate_tpu.data_loader import batch_to_global_array
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel

    nn.manual_seed(0)
    acc = Accelerator(
        kwargs_handlers=[TelemetryKwargs(enabled=True, profile_every_n=1)]
    )
    model = GPTLMHeadModel(
        GPTConfig(vocab_size=256, n_positions=64, n_embd=32, n_layer=1, n_head=2)
    )
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    rng = np.random.default_rng(0)
    ids = batch_to_global_array(
        jnp.asarray(rng.integers(0, 256, (4, 32), dtype=np.int32)), mesh=acc.mesh
    )
    for _ in range(3):
        loss = step(ids)
    float(loss)

    errors = []
    device_records = list(acc.telemetry.device_records)
    host = {r.step: r for r in acc.telemetry.timeline.records()}
    if len(device_records) != 3:
        errors.append(f"expected 3 device records, got {len(device_records)}")
    for rec in device_records:
        joined = host.get(rec.step)
        if joined is None or joined.key != rec.key:
            errors.append(f"device record step {rec.step} failed the host join")
            continue
        if not (rec.busy_ms > 0 and rec.compute_ms > 0 and rec.op_events > 0):
            errors.append(f"empty device split at step {rec.step}: {rec}")
        if not joined.built:  # replays: the ISSUE 8 coverage acceptance
            covered = (rec.busy_ms + rec.idle_ms) / max(
                joined.total_ms - rec.overhead_ms, 1e-9
            )
            if covered < 0.8:
                errors.append(
                    f"step {rec.step}: busy+idle covers {covered:.0%} "
                    f"of wall clock (< 80%)"
                )
    if acc.telemetry.recompiles_total != 0:
        errors.append(
            f"profiling introduced {acc.telemetry.recompiles_total} recompile(s): "
            + "; ".join(e.cause for e in acc.telemetry.recompile_events)
        )

    server = acc.telemetry.serve_metrics(port=0)
    if server is None:
        errors.append("metrics endpoint failed to start")
    else:
        body = urllib.request.urlopen(server.url, timeout=10).read().decode()
        samples = [l for l in body.splitlines() if l and not l.startswith("#")]
        bad = [l for l in samples if not _SAMPLE_RE.match(l)]
        if bad:
            errors.append(f"invalid Prometheus exposition lines: {bad[:3]}")
        for needle in (
            "atpu_telemetry_steps_total 3",
            "atpu_telemetry_recompiles_total 0",
            "atpu_telemetry_device_busy_ms",
            # native step-latency histogram (docs/telemetry.md §endpoint)
            "# TYPE atpu_telemetry_step_latency_ms histogram",
            'atpu_telemetry_step_latency_ms_bucket{le="+Inf"} 2',
        ):
            if needle not in body:
                errors.append(f"scrape missing {needle!r}")
        acc.telemetry.close_metrics()

    for error in errors:
        print(f"profile-smoke: FAIL: {error}", file=sys.stderr)
    if errors:
        return 1
    rec = device_records[-1]
    print(
        f"profile-smoke: ok — {len(device_records)} sampled steps, last: "
        f"busy {rec.busy_ms:.2f} ms / idle {rec.idle_ms:.2f} ms of "
        f"{rec.window_ms:.2f} ms window, {rec.op_events} op events, "
        f"collective share {rec.collective_share:.1%}, scrape valid"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
