"""Feature: checkpoint/resume with ``save_state``/``load_state``.

Counterpart of /root/reference/examples/by_feature/checkpointing.py: save the
full training state (model/optimizer/scheduler/sampler/RNG) every epoch or
every N steps, and resume mid-epoch with ``skip_first_batches``.
Lines marked `# New Code #` are what this feature adds to nlp_example.py.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

import accelerate_tpu.nn as nn  # noqa: E402
import accelerate_tpu.optim as optim  # noqa: E402
from accelerate_tpu import Accelerator  # noqa: E402
from accelerate_tpu.models import BertConfig, BertForSequenceClassification  # noqa: E402


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    nn.manual_seed(args.seed)
    train_dl, val_dl, vocab = get_dataloaders(accelerator, args.batch_size, args.seed)

    cfg = BertConfig.small() if args.small else BertConfig.base()
    cfg.vocab_size = max(cfg.vocab_size, vocab)
    model = BertForSequenceClassification(cfg)
    optimizer = optim.AdamW(model.parameters(), lr=args.lr)
    scheduler = optim.get_linear_schedule_with_warmup(
        optimizer, 100, len(train_dl) * args.num_epochs * accelerator.num_devices
    )
    model, optimizer, train_dl, val_dl, scheduler = accelerator.prepare(
        model, optimizer, train_dl, val_dl, scheduler
    )

    # New Code #
    # resume: restore model/optimizer/scheduler/sampler/RNG state, then skip
    # the batches the checkpointed epoch already consumed
    start_epoch = 0
    resume_step = 0
    if args.resume_from_checkpoint:
        accelerator.load_state(args.resume_from_checkpoint)
        tag = os.path.basename(args.resume_from_checkpoint.rstrip("/"))
        if "epoch" in tag:
            start_epoch = int(tag.replace("epoch_", "")) + 1
        elif "step" in tag:
            resume_step = int(tag.replace("step_", ""))
            start_epoch = resume_step // len(train_dl)
            resume_step -= start_epoch * len(train_dl)

    overall_step = 0
    for epoch in range(start_epoch, args.num_epochs):
        model.train()
        # New Code #
        active_dl = train_dl
        if args.resume_from_checkpoint and epoch == start_epoch and resume_step:
            active_dl = accelerator.skip_first_batches(train_dl, resume_step)
        for step, batch in enumerate(active_dl):
            optimizer.zero_grad()
            out = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                labels=batch["labels"],
            )
            accelerator.backward(out["loss"])
            optimizer.step()
            scheduler.step()
            overall_step += 1
            # New Code #
            if args.checkpointing_steps == "step":
                accelerator.save_state(os.path.join(args.output_dir, f"step_{overall_step}"))
        # New Code #
        if args.checkpointing_steps == "epoch":
            accelerator.save_state(os.path.join(args.output_dir, f"epoch_{epoch}"))
        accelerator.print(f"epoch {epoch}: loss={float(out['loss'].item()):.4f}")
    return model


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", type=str, default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=2e-5)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--small", action="store_true")
    # New Code #
    parser.add_argument("--checkpointing_steps", type=str, default="epoch", choices=["epoch", "step", "no"])
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    parser.add_argument("--output_dir", type=str, default="ckpt_example")
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
