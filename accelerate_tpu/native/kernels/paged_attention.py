"""Paged-attention decode: walk the block table in VMEM (docs/kernels.md
§paged-attention; the vLLM move).

The reference decode (``serving/engine.py``) attends each slot with
``kp_l[row]`` — a gather that MATERIALIZES the slot's full page span
``(blocks_per_slot, n_kv, block_size, d)`` in HBM for every slot × every
layer × every token, then hands the copy to ``cached_attention``.  The
kernel here runs one grid program per slot: it walks the slot's block-table
row, streams each page into VMEM scratch (direct dynamic-index loads in
interpreter mode; double-buffered ``make_async_copy`` DMA from
HBM-resident pools on TPU), and attends over the virtually-contiguous span
in place — the batched full-span gather never exists, which
``inspect.check_paged_attention`` proves from the lowered IR (no tensor of
the gathered ``(slots, blocks_per_slot, n_kv, block_size, d)`` shape).

Numerics contract: the attend math IS ``cached_attention`` — the kernel
body calls it on the walked span, so per-slot logits (and therefore greedy
serving tokens) are **bitwise-identical** to the gather-then-attend path
under jit.  Verified end-to-end against ``DecodeService`` in
tests/test_kernels.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["paged_attention", "reference_paged_attention"]


def _paged_attn_kernel(table_ref, pos_ref, q_ref, kp_ref, vp_ref, o_ref,
                       k_scratch, v_scratch, *, bps: int, cfg,
                       interpret: bool):
    from ...models.generation import cached_attention

    row = table_ref[0]  # (blocks_per_slot,) — this slot's block-table row
    p_s = pos_ref[0]
    if interpret:
        # interpreter lowering: dynamic-index loads walk the table; each
        # page lands in scratch one block at a time — no batched gather
        for j in range(bps):
            k_scratch[j] = kp_ref[row[j]]
            v_scratch[j] = vp_ref[row[j]]
    else:
        from jax.experimental.pallas import tpu as pltpu

        def dma_pages(sems):
            # pools stay HBM-resident; pages stream into VMEM per walk step
            # (trash-block pages — table entries past the live span — are
            # masked out by cached_attention's causal mask, same as the
            # reference's gathered padding)
            for j in range(bps):
                kd = pltpu.make_async_copy(
                    kp_ref.at[row[j]], k_scratch.at[j], sems.at[0]
                )
                vd = pltpu.make_async_copy(
                    vp_ref.at[row[j]], v_scratch.at[j], sems.at[1]
                )
                kd.start()
                vd.start()
                kd.wait()
                vd.wait()

        pl.run_scoped(dma_pages, pltpu.SemaphoreType.DMA((2,)))
    n_kv, bs, d = k_scratch.shape[1], k_scratch.shape[2], k_scratch.shape[3]
    # table order IS logical order: the flattened walk is a virtually
    # contiguous cache, so the ONE attention implementation applies
    # unchanged — which is the bitwise-parity contract
    kc = k_scratch[:].transpose(1, 0, 2, 3).reshape(n_kv, bps * bs, d)
    vc = v_scratch[:].transpose(1, 0, 2, 3).reshape(n_kv, bps * bs, d)
    q_s = q_ref[0]  # (H, 1, d)
    o_ref[0] = cached_attention(
        q_s[None], kc[None], vc[None], p_s[None], cfg
    )[0].astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, block_tables, positions, *, cfg,
                    interpret: bool = True):
    """Attend the whole slot batch one token against the paged KV pool.

    ``q: (slots, H, 1, d)``; ``k_pool/v_pool: (num_blocks, n_kv, bs, d)``
    (ONE layer's pools — the caller's layer scan passes each layer);
    ``block_tables: (slots, blocks_per_slot)``; ``positions: (slots,)``.
    Returns ``(slots, H, 1, d)`` in the pool dtype, bitwise-equal to the
    reference gather-then-attend."""
    slots, n_heads, _, d = q.shape
    bps = block_tables.shape[1]
    kernel = functools.partial(
        _paged_attn_kernel, bps=bps, cfg=cfg, interpret=interpret
    )
    pool_spec_space = {}
    scratch_dtype = k_pool.dtype
    if not interpret:
        from jax.experimental.pallas import tpu as pltpu

        # TPU: pools are far too big for VMEM — leave them where they live
        # and DMA pages on demand (the whole point of the walk)
        pool_spec_space = {"memory_space": pltpu.ANY}
    return pl.pallas_call(
        kernel,
        grid=(slots,),
        in_specs=[
            pl.BlockSpec((1, bps), lambda i: (i, 0)),
            pl.BlockSpec((1,), lambda i: (i,)),
            pl.BlockSpec((1, n_heads, 1, d), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec(k_pool.shape, lambda i: (0, 0, 0, 0), **pool_spec_space),
            pl.BlockSpec(v_pool.shape, lambda i: (0, 0, 0, 0), **pool_spec_space),
        ],
        out_specs=pl.BlockSpec((1, n_heads, 1, d), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((slots, n_heads, 1, d), v_pool.dtype),
        scratch_shapes=[
            _scratch((bps,) + k_pool.shape[1:], scratch_dtype, interpret),
            _scratch((bps,) + v_pool.shape[1:], scratch_dtype, interpret),
        ],
        interpret=interpret,
    )(block_tables, positions, q, k_pool, v_pool)


def _scratch(shape, dtype, interpret: bool):
    from jax.experimental.pallas import tpu as pltpu

    del interpret  # VMEM scratch lowers on both paths (interpreter emulates)
    return pltpu.VMEM(shape, dtype)


def reference_paged_attention(q, k_pool, v_pool, block_tables, positions, *,
                              cfg):
    """The unfused reference (``serving/engine.py``'s ``attend_one`` shape):
    materialize each slot's full page span, then attend — the contrast half
    of ``inspect.check_paged_attention`` and the parity baseline."""
    from ...models.generation import cached_attention

    def attend_one(q_s, row, p_s):
        kc = k_pool[row].transpose(1, 0, 2, 3).reshape(
            k_pool.shape[1], -1, k_pool.shape[3]
        )
        vc = v_pool[row].transpose(1, 0, 2, 3).reshape(
            v_pool.shape[1], -1, v_pool.shape[3]
        )
        return cached_attention(q_s[None], kc[None], vc[None], p_s[None], cfg)[0]

    return jax.vmap(attend_one)(q, block_tables, positions).astype(v_pool.dtype)
