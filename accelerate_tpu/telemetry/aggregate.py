"""Pillar 6 — multi-host aggregation: one fleet-wide telemetry view.

Every process owns a rank-local :class:`~.Telemetry` hub; on a multi-host
mesh the JSONL/TensorBoard export therefore used to describe one rank and
say nothing about the fleet's actual step time — which is gated by the
*slowest* rank.  ``Telemetry.aggregate_fleet()`` (called automatically by
``Accelerator.end_training`` on multi-process runs, and on demand anywhere)
gathers every rank's retained records to rank 0 with ``gather_object``,
tags each record with its ``rank``, and appends one ``kind: "fleet"``
record of per-rank skew statistics: per-rank replay step-time means, the
slowest/fastest ranks, the skew between them, and which phase the
straggler's extra time sits in.

The gather is COLLECTIVE — every process must call it (the accelerator's
``end_training`` does); non-main ranks contribute and get ``None`` back.
All the merge math is plain host code over record dicts, so it tests on a
single process with synthetic per-rank lists.
"""

from __future__ import annotations

from typing import Optional

# phases eligible for straggler attribution (StepRecord schema)
_SKEW_PHASES = (
    "dataloader_wait_ms",
    "assembly_ms",
    "dispatch_ms",
    "retry_wait_ms",
)


def _replay_steps(records: list) -> list:
    return [
        r for r in records
        if r.get("kind") == "step" and not r.get("built")
        and isinstance(r.get("total_ms"), (int, float))
    ]


def fleet_skew(per_rank: list) -> dict:
    """Per-rank replay step-time means + slowest/fastest skew + the phase
    that explains the straggler's delta.  Ranks with no replay steps are
    reported but excluded from the skew comparison."""
    rank_stats = []
    for rank, records in enumerate(per_rank):
        replays = _replay_steps(records)
        stat = {"rank": rank, "replay_steps": len(replays)}
        if replays:
            stat["replay_total_ms_mean"] = round(
                sum(r["total_ms"] for r in replays) / len(replays), 3
            )
            for phase in _SKEW_PHASES:
                values = [r.get(phase, 0.0) for r in replays]
                stat[f"{phase}_mean"] = round(sum(values) / len(values), 3)
        rank_stats.append(stat)
    out = {"kind": "fleet", "ranks": len(per_rank), "per_rank": rank_stats}
    usable = [s for s in rank_stats if s.get("replay_total_ms_mean") is not None]
    if len(usable) >= 2:
        slowest = max(usable, key=lambda s: s["replay_total_ms_mean"])
        fastest = min(usable, key=lambda s: s["replay_total_ms_mean"])
        skew_ms = slowest["replay_total_ms_mean"] - fastest["replay_total_ms_mean"]
        out["slowest_rank"] = slowest["rank"]
        out["fastest_rank"] = fastest["rank"]
        out["skew_ms"] = round(skew_ms, 3)
        out["skew_pct"] = round(
            100.0 * skew_ms / fastest["replay_total_ms_mean"], 1
        ) if fastest["replay_total_ms_mean"] > 0 else None
        # straggler attribution: the phase where the slowest rank spends the
        # most extra time over the fastest
        deltas = {
            phase: slowest.get(f"{phase}_mean", 0.0) - fastest.get(f"{phase}_mean", 0.0)
            for phase in _SKEW_PHASES
        }
        phase, delta = max(deltas.items(), key=lambda kv: kv[1])
        out["straggler_phase"] = phase
        out["straggler_phase_delta_ms"] = round(delta, 3)
    return out


def merge_rank_records(per_rank: list) -> list:
    """Rank-tag every record (without mutating the inputs) and append the
    fleet skew record — the JSONL schema stays per-record valid, each line
    just carries which rank produced it.

    Periodic ``kind="fleet"`` records are kept from rank 0 only: the
    mid-run cadence retains the IDENTICAL record on every rank (the
    autopilot needs rank-symmetric inputs, telemetry/__init__.py), so the
    merged dump would otherwise carry world-size duplicates per tick and
    any post-mortem counting them would over-count by that factor."""
    merged = []
    for rank, records in enumerate(per_rank):
        for record in records:
            if (
                rank != 0
                and record.get("kind") == "fleet"
                and record.get("periodic")
            ):
                continue
            tagged = dict(record)
            tagged["rank"] = rank
            merged.append(tagged)
    merged.append(fleet_skew(per_rank))
    return merged


def gather_fleet(local_records: list) -> Optional[list]:
    """COLLECTIVE: gather every rank's record list; returns the per-rank
    list-of-lists on the main process, ``None`` elsewhere.  On a single
    process this degenerates to ``[local_records]`` with no communication."""
    from ..state import PartialState
    from ..utils.operations import gather_object

    # gather_object flattens one list level across processes, so each rank
    # contributes [its records] and main receives [rank0_records, rank1_...]
    gathered = gather_object([local_records])
    if PartialState._shared_state and not PartialState().is_main_process:
        return None
    return gathered
