"""On-disk per-module analysis cache.

One JSON file per analyzed source file (named by a hash of its rel_path),
holding:

* ``content_hash`` — sha256 of the source text.  A mismatch discards the
  entry wholesale: summary extraction is re-run and findings are dropped.
* ``summary`` — the :class:`program.ModuleSummary` digest.  Replaying it
  lets a warm run build the whole-program graph without parsing a single
  unchanged file.
* ``results`` — findings keyed by *environment hash* (everything outside
  the file that its findings depend on: the module's cross-module reached
  set, the axis universe, visible donors/escapers/blockers, the rule list,
  checkpoint specs — see ``engine._module_env_hash``).  Editing file A
  therefore invalidates A by content and invalidates B only when A's edit
  changed what B actually sees.

The cache is best-effort: any IO/parse error on load or store is treated as
a miss and never surfaces to the caller.  ``ANALYSIS_VERSION`` is baked into
every entry so an analyzer upgrade starts cold instead of replaying stale
findings.

Entries live in a **per-branch namespace** under ``cache_dir``
(``.graftlint_cache/<branch>/``): content hashes differ between two
long-lived branches, so a shared flat directory ping-pongs — every
``git switch`` invalidates almost every entry the other branch just wrote.
The namespace is ``git rev-parse --abbrev-ref HEAD`` (sanitized), falling
back to ``detached`` on a detached HEAD or outside a repository.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import subprocess
import tempfile
from typing import Optional

from .engine import ANALYSIS_VERSION


def branch_namespace(root: Optional[str] = None) -> str:
    """Cache namespace for the git branch at ``root`` — the *analyzed* tree,
    not the process CWD, which may be a different repo (or none) when
    graftlint targets an out-of-tree path.  'detached' when there is no
    branch to key on: detached HEAD, outside a work tree, no git."""
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "--abbrev-ref", "HEAD"],
            capture_output=True,
            text=True,
            timeout=10,
            cwd=root or None,
        )
    except (OSError, subprocess.SubprocessError):
        return "detached"
    name = proc.stdout.strip()
    if proc.returncode != 0 or not name or name == "HEAD":
        return "detached"
    # branch names may contain path separators and worse; keep the namespace
    # a single safe path component
    return re.sub(r"[^A-Za-z0-9._-]", "_", name)[:80] or "detached"


class AnalysisCache:
    def __init__(
        self,
        cache_dir: str,
        namespace: Optional[str] = None,
        root: Optional[str] = None,
    ):
        if namespace is None:
            namespace = branch_namespace(root)
        self.namespace = namespace
        self.dir = os.path.join(cache_dir, namespace)
        os.makedirs(self.dir, exist_ok=True)

    def _entry_path(self, rel_path: str) -> str:
        key = hashlib.sha1(rel_path.replace(os.sep, "/").encode("utf-8")).hexdigest()
        return os.path.join(self.dir, f"{key}.json")

    def load(self, rel_path: str, content_hash: str) -> Optional[dict]:
        try:
            with open(self._entry_path(rel_path), encoding="utf-8") as f:
                entry = json.load(f)
        except (OSError, ValueError):
            return None
        if (
            entry.get("version") != ANALYSIS_VERSION
            or entry.get("path") != rel_path
            or entry.get("content_hash") != content_hash
            or not isinstance(entry.get("summary"), dict)
            or not isinstance(entry.get("results"), dict)
        ):
            return None
        return entry

    def store(self, rel_path: str, content_hash: str, entry: dict) -> None:
        entry = dict(entry)
        entry["version"] = ANALYSIS_VERSION
        entry["path"] = rel_path
        entry["content_hash"] = content_hash
        path = self._entry_path(rel_path)
        try:
            fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(entry, f)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except (OSError, UnboundLocalError):
                pass
