"""Feature: schedule-free training with ``optim.AdamWScheduleFree``.

Counterpart of /root/reference/examples/by_feature/schedule_free.py (which
uses the schedulefree package): no LR scheduler at all — the optimizer
maintains fast/averaged iterates internally.  The one training-loop contract
is switching the optimizer (and with it the model weights) between
``.train()`` and ``.eval()`` around evaluation.  Lines marked `# New Code #`
are what this feature adds to nlp_example.py.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

import accelerate_tpu.nn as nn  # noqa: E402
import accelerate_tpu.optim as optim  # noqa: E402
from accelerate_tpu import Accelerator  # noqa: E402
from accelerate_tpu.models import BertConfig, BertForSequenceClassification  # noqa: E402


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    nn.manual_seed(args.seed)
    train_dl, val_dl, vocab = get_dataloaders(accelerator, args.batch_size, args.seed)

    cfg = BertConfig.small() if args.small else BertConfig.base()
    cfg.vocab_size = max(cfg.vocab_size, vocab)
    model = BertForSequenceClassification(cfg)
    # New Code #
    # schedule-free: no scheduler object anywhere; warmup happens inside
    optimizer = optim.AdamWScheduleFree(
        model.parameters(), lr=args.lr, warmup_steps=args.warmup_steps
    )
    model, optimizer, train_dl, val_dl = accelerator.prepare(
        model, optimizer, train_dl, val_dl
    )

    for epoch in range(args.num_epochs):
        model.train()
        # New Code #
        optimizer.train()  # gradients must be taken at the fast y iterates
        for batch in train_dl:
            optimizer.zero_grad()
            out = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                labels=batch["labels"],
            )
            accelerator.backward(out["loss"])
            optimizer.step()

        model.eval()
        # New Code #
        optimizer.eval()  # swap in the averaged x weights for evaluation
        correct = total = 0
        for batch in val_dl:
            with nn.no_grad():
                out = model(
                    batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                )
            preds = out["logits"].data.argmax(-1)
            preds, refs = accelerator.gather_for_metrics((preds, batch["labels"]))
            correct += int((np.asarray(preds) == np.asarray(refs)).sum())
            total += int(np.asarray(refs).size)
        accelerator.print(f"epoch {epoch}: accuracy={correct / max(total, 1):.3f}")
    return model


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", type=str, default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=5e-4)
    parser.add_argument("--warmup_steps", type=int, default=10)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--small", action="store_true")
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
