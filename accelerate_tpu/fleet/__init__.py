"""Elastic fleet runtime (``accelerator.fleet``) — docs/elastic.md.

The "survive and resize" layer over the resilience/checkpoint/AOT-cache
subsystems, default-OFF (off = byte-identical capture hot path, one
``None``-check, matching the telemetry/resilience/aot-cache precedent).
Three pillars:

1. **Coordinated multi-host drain + rollback** (`coordinate.py`) — on retry
   exhaustion every rank offers its visible complete checkpoints to a
   gather/vote barrier; all ranks agree on the newest all-ranks-visible
   restore point BEFORE any rank issues the collective ``load_state``.
   Replaces the resilience layer's single-process-only rollback refusal.
2. **Elastic dp resize** (`resize.py`) — a lost host (``host_lost``
   fault-plan verb on CPU; a reclamation notice in production) trips
   ``fleet.should_resize``; ``fleet.resize()`` drains a complete
   checkpoint, re-meshes at the surviving topology, re-lays ZeRO-1
   masters/moments + compression residuals onto it, restores the
   spec-carrying checkpoint (reshard, not reinit), and prewarms the
   new-topology programs from the AOT executable cache.
3. **Fleet signal** — ``FleetKwargs(aggregate_every_n=N)`` graduates
   ``telemetry.aggregate_fleet()`` from end-of-training-only to periodic
   mid-run skew/straggler records (``kind="fleet"``), the
   autoscaler/resize input read back via :meth:`Fleet.fleet_signal`.
4. **Grow-side resize** (`grow.py`) — a returned host (``host_gained``
   fault-plan verb; a rejoin beacon in production) trips
   ``fleet.should_grow``; ``fleet.grow()`` drains, runs the grow
   rendezvous barrier (all ranks agree on the widened topology), re-meshes
   dp *up*, re-lays/reshards state onto the wider mesh, and prewarms the
   AOT store — the torchelastic new-member half PR 11 deferred.
5. **Autopilot** (`autopilot.py`) — ``FleetKwargs(autopilot=...)`` /
   ``$ACCELERATE_FLEET_AUTOPILOT`` closes signal→decision→action: a pure,
   rank-deterministic policy over the fleet/serving signal window
   (debounce + hysteresis + cooldown) drives ``resize``/``grow`` from the
   captured-step dispatch path itself — no caller polling loop.

Enable with ``ACCELERATE_FLEET=1`` or
``Accelerator(kwargs_handlers=[FleetKwargs(enabled=True)])``.
"""

from __future__ import annotations

import os
from typing import Optional

from ..resilience.inject import FaultInjector
from .autopilot import Autopilot, AutopilotPolicy, evaluate_window
from .coordinate import (
    agree_restore_point,
    coordinated_rollback,
    local_restore_candidates,
    vote_restore_point,
)
from .grow import agree_grow, grow_rendezvous, grown_mesh, max_growable_dp
from .resize import prewarm_aot_cache, remesh_accelerator, surviving_mesh


class Fleet:
    """Per-Accelerator elastic-fleet hub; inert when disabled."""

    def __init__(self, handler=None, telemetry=None, resilience=None):
        if handler is None:
            from ..utils.dataclasses import FleetKwargs

            handler = FleetKwargs()
        self.handler = handler
        self.enabled = bool(handler.enabled)
        # events always land here (tests / diagnostics need them with
        # telemetry off); they additionally flow into the telemetry export
        # stream as kind="fleet_event" records when telemetry is on
        self.telemetry = (
            telemetry
            if (telemetry is not None and getattr(telemetry, "enabled", False))
            else None
        )
        self.resilience = resilience
        self.events: list[dict] = []
        self.injector: Optional[FaultInjector] = None
        self.autopilot: Optional[Autopilot] = None
        self.dispatch_calls = 0
        self.resizes_total = 0
        self.grows_total = 0
        self._host_lost = False
        self._host_gained = False
        # collective host-lost/-gained poll memo, same discipline as the
        # resilience preemption poll: at most ONE gather per dispatch (both
        # flags ride the same collective), sticky once set
        self._poll_cache: Optional[tuple[int, bool, bool]] = None
        self._lost_resolved = False
        self._gained_resolved = False
        if not self.enabled:
            return
        self.injector = FaultInjector.from_spec(handler.fault_plan)
        policy = getattr(handler, "autopilot_policy", None)
        if policy is not None:
            self.autopilot = Autopilot(self, policy)

    # -- events --------------------------------------------------------------
    def record_event(self, event: str, **fields) -> dict:
        payload = {"event": event, **fields}
        self.events.append(payload)
        if self.telemetry is not None:
            self.telemetry.record_fleet(dict(payload))
        # mirror the scalar shape into the flight ring (docs/telemetry.md
        # §flight recorder): vote / rendezvous / resize phases must survive
        # a crash even when the telemetry hub is off or its JSONL unflushed
        from ..telemetry import flightrec

        # payload keys colliding with the ring's slot schema (autopilot
        # decisions carry their own "kind") come back ``field_``-prefixed
        flightrec.record(
            "fleet",
            event=event,
            **{k: v for k, v in fields.items()
               if v is None or isinstance(v, (bool, int, float, str))},
        )
        return payload

    # -- capture-path hook ---------------------------------------------------
    def on_dispatch(self, step=None) -> int:
        """Called by every fleet-armed CapturedStep at the top of its call:
        counts calls (the ``host_lost`` fault verb's step axis), fires any
        scheduled host loss, and runs the periodic fleet-aggregation
        cadence.  One None-check and an integer bump on the armed hot path;
        fleet-off steps never reach this."""
        index = self.dispatch_calls
        self.dispatch_calls += 1
        if self.injector is not None:
            if not self._host_lost and self.injector.maybe_host_lost(index):
                self._host_lost = True
                self.record_event("host_lost", dispatch_calls=index)
            if not self._host_gained and self.injector.maybe_host_gained(index):
                self._host_gained = True
                self.record_event("host_gained", dispatch_calls=index)
        every = self.handler.aggregate_every_n
        if every and self.telemetry is not None and self.dispatch_calls % every == 0:
            # COLLECTIVE, but cadence-aligned: every rank counts the same
            # SPMD dispatches, so all ranks enter the gather together
            self.telemetry.aggregate_fleet(periodic=True)
        return index

    def on_dispatch_end(self, step) -> None:
        """Called by autopilot-armed CapturedSteps after writeback — the
        step boundary, so a fired resize/grow never lands mid-step.  The
        capture path guards on ``fleet.autopilot``: plain fleet-armed runs
        (manual ``should_resize`` loop) never reach this."""
        if self.autopilot is not None:
            self.autopilot.on_dispatch_end(step)

    # -- host-lost / host-gained flags ---------------------------------------
    def _poll(self) -> tuple[bool, bool]:
        """(host_lost, host_gained), each sticky once any rank observed it.
        Both flags ride ONE gather per dispatch on multi-process runs."""
        if self._lost_resolved and self._gained_resolved:
            return True, True
        local = (self._host_lost, self._host_gained)
        from ..state import PartialState

        if PartialState._shared_state and PartialState().num_processes > 1:
            if (
                self._poll_cache is not None
                and self._poll_cache[0] == self.dispatch_calls
            ):
                lost, gained = self._poll_cache[1], self._poll_cache[2]
            else:
                from ..utils import operations as ops

                flags = ops.gather_object([local])
                lost = any(bool(pair[0]) for pair in flags)
                gained = any(bool(pair[1]) for pair in flags)
                self._poll_cache = (self.dispatch_calls, lost, gained)
        else:
            lost, gained = local
        lost = lost or self._lost_resolved
        gained = gained or self._gained_resolved
        if lost:
            self._lost_resolved = True
        if gained:
            self._gained_resolved = True
        return lost, gained

    def consume_host_lost(self) -> None:
        """Reset the sticky host-lost flag after it was handled (a resize,
        or an at-the-floor suppression) — a LATER loss re-trips it; all
        ranks reset together, they all handled the same event."""
        self._host_lost = False
        self._lost_resolved = False
        self._poll_cache = None

    def consume_host_gained(self) -> None:
        """Reset the sticky host-gained flag after it was handled (a grow,
        or an at-the-ceiling suppression)."""
        self._host_gained = False
        self._gained_resolved = False
        self._poll_cache = None

    @property
    def should_resize(self) -> bool:
        """True once any rank observed a host loss.  Collective on
        multi-process — call it on every rank (the survivors must agree to
        drain and re-mesh together, exactly like the preemption flags)."""
        return self._poll()[0]

    @property
    def should_grow(self) -> bool:
        """True once any rank observed a host RETURN (``host_gained``) —
        the grow-side twin of ``should_resize``; same collective/sticky
        contract."""
        return self._poll()[1]

    # -- pillar 1: coordinated restore ---------------------------------------
    def coordinated_rollback(self, accelerator) -> Optional[str]:
        """Vote on the newest all-ranks-visible complete checkpoint and have
        every rank restore it collectively (coordinate.py); ``None`` when no
        agreement exists."""
        return coordinated_rollback(accelerator, fleet=self)

    # -- pillar 2: elastic resize --------------------------------------------
    def drain(self, accelerator, output_dir: Optional[str] = None) -> str:
        """Write a COMPLETE checkpoint now and block until durable — the
        pre-resize barrier.  Delegates to the resilience drain when that
        subsystem is armed (same async save machinery + event stream);
        otherwise drives save_state/wait_for_checkpoint directly."""
        target = output_dir or self.handler.checkpoint_dir
        if target is None and not (
            accelerator.project_configuration.automatic_checkpoint_naming
            and accelerator.project_dir
        ):
            # autopilot-driven drains have no caller to pass output_dir:
            # derive a path every rank computes identically from the shared
            # counters (dispatch count and resize tally are SPMD-aligned).
            # Production fleets should pin FleetKwargs.checkpoint_dir — a
            # durable shared filesystem — this fallback is the rehearsal/
            # single-host default.  Single-process runs add their pid so
            # two unrelated jobs on one machine cannot write the same
            # folder; multi-process runs have no communication-free shared
            # unique token, so the counters stand and the warning below
            # tells the operator to pin a real dir.
            import tempfile

            from ..state import PartialState

            multi = (
                bool(PartialState._shared_state)
                and PartialState().num_processes > 1
            )
            token = "" if multi else f"_{os.getpid()}"
            if multi:
                from ..logging import get_logger

                get_logger(__name__).warning(
                    "fleet drain falling back to a counter-derived tmp path; "
                    "set FleetKwargs.checkpoint_dir (shared, durable) — "
                    "concurrent jobs on one filesystem could collide"
                )
            base = accelerator.project_dir or tempfile.gettempdir()
            target = os.path.join(
                base,
                "atpu_fleet_drain"
                f"{token}_"
                f"{self.resizes_total + self.grows_total}_{self.dispatch_calls}",
            )
        resilience = self.resilience
        if resilience is not None and resilience.enabled:
            out = resilience.drain(accelerator, target)
        else:
            out = accelerator.save_state(target, async_save=True)
            accelerator.wait_for_checkpoint()
        self.record_event("drain", checkpoint=out)
        return out

    def resize(
        self,
        accelerator,
        target_dp: Optional[int] = None,
        output_dir: Optional[str] = None,
        checkpoint: Optional[str] = None,
        lost_blocks: Optional[list] = None,
    ) -> dict:
        """Shrink the dp axis to the surviving topology and resume from a
        complete checkpoint: drain → re-mesh → relayout → AOT prewarm →
        spec-carrying reshard restore.  ``checkpoint`` skips the drain (the
        caller already has a durable restore point — e.g. the host died
        AFTER a scheduled save).  ``lost_blocks`` names the dead dp-axis
        block indices (from the reclamation notice) so the survivors —
        not the dead host's devices — make up the new mesh.  Returns a
        summary dict (also recorded as a ``resize`` fleet event)."""
        if not self.enabled:
            raise RuntimeError("fleet.resize() needs FleetKwargs(enabled=True)")
        if not self.handler.elastic:
            raise RuntimeError("elastic resize disabled (FleetKwargs.elastic=False)")
        mesh = accelerator.state.mesh
        # the resolved ParallelPlan owns the dp axis (docs/parallel_plan.md)
        # — no local mesh-dict rediscovery
        old_dp = accelerator.plan.dp
        if target_dp is None:
            # default survivor model: half the fleet gone (one of two hosts)
            target_dp = max(self.handler.min_dp, old_dp // 2)
        if target_dp > old_dp:
            # one resize verb either direction: a wider target routes to
            # the grow path (rendezvous + widened mesh) — what used to be a
            # "growing is a relaunch" refusal before grow.py existed
            return self.grow(
                accelerator, target_dp=target_dp, output_dir=output_dir,
                checkpoint=checkpoint,
            )
        if target_dp < self.handler.min_dp:
            raise ValueError(
                f"resize to dp={target_dp} is below the configured floor "
                f"(FleetKwargs.min_dp={self.handler.min_dp})"
            )
        ckpt = checkpoint or self.drain(accelerator, output_dir)
        new_mesh = surviving_mesh(mesh, target_dp, lost_blocks=lost_blocks)
        remesh_accelerator(accelerator, new_mesh)
        warmed = prewarm_aot_cache(accelerator)
        # reshard restore: relayout above re-laid masters/moments/residuals
        # on the survivors, load_state now fills that layout with the
        # checkpointed values (per-leaf specs recorded at save time make
        # the N→M move exact) — resharded, never reinitialized
        accelerator.load_state(ckpt)
        self.resizes_total += 1
        # the resize handled the loss: consume the sticky flag so the
        # documented `if fleet.should_resize: fleet.resize(...)` loop does
        # not re-drain/re-mesh on every subsequent step (a LATER host loss
        # re-trips it; all ranks reset together — they all ran this resize)
        self.consume_host_lost()
        info = {
            "checkpoint": ckpt,
            "old_mesh": dict(mesh.shape),
            "new_mesh": dict(new_mesh.shape),
            "old_dp": old_dp,
            "dp": target_dp,
            "direction": "shrink",
            "aot_prewarmed": warmed,
            "resumed_step": accelerator.step,
        }
        self.record_event("resize", **info)
        return info

    def grow(
        self,
        accelerator,
        target_dp: Optional[int] = None,
        output_dir: Optional[str] = None,
        checkpoint: Optional[str] = None,
        devices: Optional[list] = None,
    ) -> dict:
        """Widen the dp axis over rejoined device blocks and resume from a
        complete checkpoint: drain → grow rendezvous (all ranks agree on
        the widened topology) → re-mesh up → relayout → AOT prewarm →
        spec-carrying reshard restore.  The grow-side twin of
        :meth:`resize` (docs/elastic.md §grow); ``devices`` overrides the
        rejoined-device pool (default: every process-visible device)."""
        if not self.enabled:
            raise RuntimeError("fleet.grow() needs FleetKwargs(enabled=True)")
        if not self.handler.elastic:
            raise RuntimeError("elastic resize disabled (FleetKwargs.elastic=False)")
        mesh = accelerator.state.mesh
        # dp and the re-mesh constraint (devices per dp block) come from the
        # resolved plan, not a local mesh-dict walk (docs/parallel_plan.md)
        old_dp = accelerator.plan.dp
        if target_dp is None:
            # default rejoin model: the lost half came back
            target_dp = min(
                old_dp * 2,
                max_growable_dp(
                    mesh, devices=devices,
                    non_dp_extent=accelerator.plan.non_dp_extent,
                ),
            )
        ckpt = checkpoint or self.drain(accelerator, output_dir)
        plan = grow_rendezvous(accelerator, target_dp, fleet=self, devices=devices)
        if plan is None:
            raise RuntimeError(
                "grow rendezvous found no agreement: some rank proposed a "
                "different topology (rejoined host not yet visible there?) "
                "— growing onto divergent meshes would deadlock the first "
                "collective"
            )
        new_mesh = grown_mesh(mesh, plan["target_dp"], devices=devices)
        remesh_accelerator(accelerator, new_mesh)
        warmed = prewarm_aot_cache(accelerator)
        # same reshard-restore contract as the shrink: relayout laid the
        # wider-mesh layouts first, the spec-carrying load fills them with
        # the checkpointed values — masters/moments bitwise vs a
        # from-checkpoint cold start at the wide topology (test-pinned)
        accelerator.load_state(ckpt)
        self.grows_total += 1
        self.consume_host_gained()
        info = {
            "checkpoint": ckpt,
            "old_mesh": dict(mesh.shape),
            "new_mesh": dict(new_mesh.shape),
            "old_dp": old_dp,
            "dp": plan["target_dp"],
            "direction": "grow",
            "aot_prewarmed": warmed,
            "resumed_step": accelerator.step,
        }
        self.record_event("resize", **info)
        return info

    # -- pillar 3: fleet signal ----------------------------------------------
    def fleet_signal(self) -> Optional[dict]:
        """The latest periodic skew/straggler record (``kind="fleet"``), or
        ``None`` before the first cadence fires — what an autoscaler polls
        to decide a resize."""
        if self.telemetry is None:
            return None
        for record in reversed(self.telemetry.fleet_events):
            if record.get("kind") == "fleet":
                return record
        return None

    def serving_signal(self) -> Optional[dict]:
        """The latest decode-service step record (``kind="serving"``,
        ``event="step"``) — queue depth / occupancy / pool back-pressure,
        the serving half of the autopilot's input (docs/serving.md §fleet
        signal); ``None`` when no service reported yet."""
        if self.telemetry is None:
            return None
        for record in reversed(self.telemetry.serving_events):
            if record.get("event") == "step":
                return record
        return None


__all__ = [
    "Autopilot",
    "AutopilotPolicy",
    "Fleet",
    "agree_grow",
    "agree_restore_point",
    "coordinated_rollback",
    "evaluate_window",
    "grow_rendezvous",
    "grown_mesh",
    "local_restore_candidates",
    "max_growable_dp",
    "prewarm_aot_cache",
    "remesh_accelerator",
    "surviving_mesh",
    "vote_restore_point",
]
