"""Big-model loading & inference — meta init, dispatch, offload, GSPMD.

Capability parity with the reference's ``big_modeling.py``
(``init_empty_weights`` :58, ``init_on_device`` :94, ``cpu_offload`` :192,
``disk_offload`` :250, ``dispatch_model`` :306, ``load_checkpoint_and_dispatch``
:511), redesigned TPU-first:

* the *preferred* way to run a model too big for one chip on a TPU slice is
  :func:`shard_for_inference` — GSPMD parameter sharding over the mesh, where
  XLA overlaps the collectives and every chip computes (the reference's
  device_map pipeline keeps one GPU busy at a time,
  reference: benchmarks/big_model_inference/README.md:40-42);
* :func:`dispatch_model` remains for the overflow regimes the reference
  covers — weights parked in host RAM or disk memmaps, streamed into HBM
  block-by-block via :mod:`accelerate_tpu.hooks`.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from .hooks import (
    AlignDevicesHook,
    CpuOffload,
    UserCpuOffloadHook,
    add_hook_to_module,
    attach_align_device_hook,
    attach_align_device_hook_on_blocks,
    remove_hook_from_submodules,
)
from .nn.meta import MetaArray, is_meta, meta_init
from .nn.module import Module
from .utils.modeling import (
    _resolve_device,
    check_device_map,
    compute_module_sizes,
    find_tied_parameters,
    get_balanced_memory,
    get_max_memory,
    infer_auto_device_map,
    load_checkpoint_in_model,
    named_module_tensors,
    retie_parameters,
    set_module_tensor_to_device,
)
from .utils.offload import OffloadedWeightsLoader, offload_state_dict


@contextmanager
def init_empty_weights(include_buffers: bool = True):
    """Instantiate a model with zero memory: parameters come out as
    :class:`MetaArray` (reference: big_modeling.py:58). No RNG is consumed, so
    later materialisation is deterministic regardless of planning order."""
    with meta_init(include_buffers=include_buffers):
        yield


@contextmanager
def init_on_device(device):
    """Instantiate with all freshly-created arrays committed to ``device``
    (reference: big_modeling.py:94) — e.g. the JAX CPU backend to keep HBM
    clean during setup, or a specific chip."""
    with jax.default_device(_resolve_device(device)):
        yield


def materialize_meta_module(model: Module, device="cpu", init: str = "zeros") -> Module:
    """Replace every MetaArray with a real array on ``device`` (the analog of
    torch's ``to_empty`` + init; used when no checkpoint will be loaded)."""
    target = _resolve_device(device)
    for name, t in list(model.named_parameters()) + list(model.named_buffers()):
        if is_meta(t.data):
            arr = jnp.zeros(t.shape, t.dtype) if init == "zeros" else jnp.empty(t.shape, t.dtype)
            t.data = jax.device_put(arr, target)
    return model


def cpu_offload(
    model: Module,
    execution_device=None,
    offload_buffers: bool = False,
    state_dict: Optional[dict] = None,
    preload_module_classes: Optional[list] = None,
) -> Module:
    """Park all weights in host RAM; stream each block to the chip at forward
    (reference: big_modeling.py:192)."""
    if execution_device is None:
        execution_device = 0
    if state_dict is None:
        cpu = _resolve_device("cpu")
        state_dict = {
            n: jax.device_put(t.data, cpu)
            for n, t in named_module_tensors(model, include_buffers=offload_buffers, recurse=True)
            if not is_meta(t.data)
        }
    attach_align_device_hook(
        model,
        execution_device=execution_device,
        offload=True,
        offload_buffers=offload_buffers,
        weights_map=state_dict,
        preload_module_classes=preload_module_classes,
        tied_params_map={},
    )
    return model


def cpu_offload_with_hook(
    model: Module,
    execution_device=None,
    prev_module_hook: Optional[UserCpuOffloadHook] = None,
):
    """Whole-model host↔chip swapping with a user-controlled handle
    (reference: big_modeling.py:231). Chain hooks for pipelines that cycle
    through several models (UNet loop keeps its chip residency)."""
    hook = CpuOffload(execution_device=execution_device, prev_module_hook=prev_module_hook)
    add_hook_to_module(model, hook, append=True)
    user_hook = UserCpuOffloadHook(model, hook)
    return model, user_hook


def disk_offload(
    model: Module,
    offload_dir: str,
    execution_device=None,
    offload_buffers: bool = False,
    preload_module_classes: Optional[list] = None,
) -> Module:
    """Park all weights as disk memmaps; stream per block
    (reference: big_modeling.py:250)."""
    if not os.path.isdir(offload_dir) or not os.path.isfile(
        os.path.join(offload_dir, "index.json")
    ):
        state_dict = {
            n: np.asarray(t.data)
            for n, t in named_module_tensors(model, include_buffers=offload_buffers, recurse=True)
            if not is_meta(t.data)
        }
        offload_state_dict(offload_dir, state_dict)
    if execution_device is None:
        execution_device = 0
    weights_map = OffloadedWeightsLoader(save_folder=offload_dir)
    attach_align_device_hook(
        model,
        execution_device=execution_device,
        offload=True,
        offload_buffers=offload_buffers,
        weights_map=weights_map,
        preload_module_classes=preload_module_classes,
        tied_params_map={},
    )
    return model


def dispatch_model(
    model: Module,
    device_map: dict,
    main_device=None,
    state_dict: Optional[dict] = None,
    offload_dir: Optional[str] = None,
    offload_index: Optional[dict] = None,
    offload_buffers: bool = False,
    skip_keys=None,
    preload_module_classes: Optional[list] = None,
    force_hooks: bool = False,
) -> Module:
    """Place each block per ``device_map`` and attach streaming hooks
    (reference: big_modeling.py:306).

    Single-entry maps short-circuit to a plain move. "cpu"/"disk" blocks get
    offload hooks; chip-resident blocks get execution-device alignment and
    the root hook pins outputs to ``main_device``.
    """
    check_device_map(model, device_map)

    if len(set(map(str, device_map.values()))) == 1 and not force_hooks:
        only = list(device_map.values())[0]
        if only == "disk":
            if offload_dir is None:
                raise ValueError(
                    "device_map sends the whole model to disk: an offload_dir "
                    "is required"
                )
            return disk_offload(
                model, offload_dir, execution_device=0,
                offload_buffers=offload_buffers,
                preload_module_classes=preload_module_classes,
            )
        if only == "cpu":
            model.to(_resolve_device("cpu"))
            return model
        model.to(_resolve_device(only))
        model.atpu_device_map = device_map
        return model

    if main_device is None:
        chips = [d for d in device_map.values() if d not in ("cpu", "disk")]
        main_device = chips[0] if chips else "cpu"

    cpu_modules = [n for n, d in device_map.items() if d == "cpu"]
    if state_dict is None and cpu_modules:
        cpu = _resolve_device("cpu")
        state_dict = {}
        for prefix in cpu_modules:
            for name, t in named_module_tensors(model, recurse=True):
                full = name
                if (full == prefix or full.startswith(prefix + ".")) and not is_meta(t.data):
                    state_dict[full] = jax.device_put(t.data, cpu)

    disk_modules = [n for n, d in device_map.items() if d == "disk"]
    if disk_modules and offload_dir is None:
        # with or without a prebuilt offload_index, disk weights are read
        # from offload_dir at forward time — fail here, not inside a hook
        raise ValueError(
            f"device_map sends {disk_modules} to disk: an offload_dir is required"
        )
    if disk_modules and offload_index is None:
        existing = os.path.isfile(os.path.join(offload_dir, "index.json"))
        if not existing:
            disk_state = {}
            for prefix in disk_modules:
                for name, t in named_module_tensors(
                    model, include_buffers=offload_buffers, recurse=True
                ):
                    if (name == prefix or name.startswith(prefix + ".")) and not is_meta(t.data):
                        disk_state[name] = np.asarray(t.data)
            offload_state_dict(offload_dir, disk_state)

    weights_map = None
    if cpu_modules or disk_modules:
        weights_map = OffloadedWeightsLoader(
            state_dict=state_dict, save_folder=offload_dir if disk_modules else None,
            index=offload_index,
        )

    tied_params = find_tied_parameters(model)
    execution_device = {
        name: main_device if dev in ("cpu", "disk") else dev
        for name, dev in device_map.items()
    }
    offload = {name: dev in ("cpu", "disk") for name, dev in device_map.items()}
    # tied groups with a chip-resident member: pin the shared Parameter so the
    # offloaded twin's hook neither parks nor re-fetches it (None sentinel)
    from .utils.modeling import _device_for

    tied_params_map: dict = {}
    params_by_name = dict(model.named_parameters(remove_duplicate=False))
    for group in tied_params:
        devices_of = [_device_for(n, device_map) for n in group]
        if any(d not in ("cpu", "disk") for d in devices_of):
            tied_params_map[id(params_by_name[group[0]])] = None
    attach_align_device_hook_on_blocks(
        model,
        execution_device=execution_device,
        offload=offload,
        weights_map=weights_map,
        offload_buffers=offload_buffers,
        skip_keys=skip_keys,
        preload_module_classes=preload_module_classes,
        tied_params_map=tied_params_map,
    )
    retie_parameters(model, tied_params)
    model.atpu_device_map = device_map
    return model


def load_checkpoint_and_dispatch(
    model: Module,
    checkpoint: str,
    device_map: Optional[Union[str, dict]] = None,
    max_memory: Optional[dict] = None,
    no_split_module_classes: Optional[list] = None,
    offload_folder: Optional[str] = None,
    offload_buffers: bool = False,
    dtype=None,
    offload_state_dict_flag: bool = False,
    skip_keys=None,
    preload_module_classes: Optional[list] = None,
    force_hooks: bool = False,
    strict: bool = False,
) -> Module:
    """One-call big-model load (reference: big_modeling.py:511): plan the map
    (``"auto"``/``"balanced"``/``"balanced_low_0"``/``"sequential"``), stream
    the checkpoint straight to mapped devices, attach hooks."""
    if isinstance(device_map, str):
        if device_map not in ("auto", "balanced", "balanced_low_0", "sequential"):
            raise ValueError(
                "device_map must be a dict or one of 'auto', 'balanced', "
                "'balanced_low_0', 'sequential'"
            )
        if device_map != "sequential":
            max_memory = get_balanced_memory(
                model, max_memory=max_memory,
                no_split_module_classes=no_split_module_classes, dtype=dtype,
                low_zero=(device_map == "balanced_low_0"),
            )
        device_map = infer_auto_device_map(
            model, max_memory=max_memory,
            no_split_module_classes=no_split_module_classes, dtype=dtype,
            offload_buffers=offload_buffers,
        )
    if device_map is not None:
        load_checkpoint_in_model(
            model, checkpoint, device_map=device_map, offload_folder=offload_folder,
            dtype=dtype, offload_buffers=offload_buffers, strict=strict,
        )
        return dispatch_model(
            model, device_map=device_map, offload_dir=offload_folder,
            offload_buffers=offload_buffers, skip_keys=skip_keys,
            preload_module_classes=preload_module_classes, force_hooks=force_hooks,
        )
    load_checkpoint_in_model(
        model, checkpoint, dtype=dtype, strict=strict,
    )
    return model


# ---------------------------------------------------------------------------
# TPU-first: GSPMD sharded inference
# ---------------------------------------------------------------------------

def shard_for_inference(model: Module, mesh=None, tp_plan: Optional[dict] = None) -> Module:
    """Shard parameters over the slice — the TPU-native answer to
    ``device_map="auto"`` when the model fits in aggregate HBM.

    Unlike the layer-streaming pipeline (one device computing at a time),
    GSPMD keeps every chip busy: weights live sharded on the ``tp``/``fsdp``
    mesh axes, XLA inserts all-gathers overlapped with compute. Use
    ``dispatch_model`` only when the model exceeds total HBM.
    """
    from .parallel.mesh import make_mesh
    from .parallel.sharding import shard_module_params
    from .utils.dataclasses import FullyShardedDataParallelPlugin, TensorParallelPlugin

    if mesh is None:
        n = len(jax.devices())
        mesh = make_mesh({"tp": n})
    tp_plugin = TensorParallelPlugin(tp_plan=tp_plan) if tp_plan else None
    if is_meta(next(iter(model.parameters())).data):
        raise ValueError(
            "shard_for_inference needs materialised weights; load a checkpoint "
            "first (load_checkpoint_in_model) or materialize_meta_module"
        )
    fsdp = FullyShardedDataParallelPlugin() if "fsdp" in mesh.axis_names and mesh.shape.get("fsdp", 1) > 1 else None
    shard_module_params(model, mesh, fsdp_plugin=fsdp, tp_plugin=tp_plugin)
    model.atpu_mesh = mesh
    return model


def remove_all_hooks(model: Module) -> None:
    remove_hook_from_submodules(model)
