import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.parallel.pipeline import gpipe
from accelerate_tpu.state import AcceleratorState
from accelerate_tpu.utils.dataclasses import ParallelismConfig


def stage_fn(params, h):
    return jnp.tanh(h @ params["w"] + params["b"])


def make_stages(n_stages, dim, key=0):
    ks = jax.random.split(jax.random.key(key), n_stages)
    return {
        "w": jnp.stack([jax.random.normal(k, (dim, dim)) * 0.5 for k in ks]),
        "b": jnp.zeros((n_stages, dim)),
    }


def sequential(params, x):
    h = x
    for i in range(params["w"].shape[0]):
        h = stage_fn({"w": params["w"][i], "b": params["b"][i]}, h)
    return h


def test_gpipe_matches_sequential():
    state = AcceleratorState(parallelism_config=ParallelismConfig(pp_size=4, dp_size=2))
    params = make_stages(4, 16)
    x = jax.random.normal(jax.random.key(1), (8, 16))
    expected = sequential(params, x)
    out = jax.jit(
        lambda p, x_: gpipe(stage_fn, p, x_, num_microbatches=4, mesh=state.mesh)
    )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_gpipe_differentiable():
    state = AcceleratorState(parallelism_config=ParallelismConfig(pp_size=4, dp_size=2))
    params = make_stages(4, 8)
    x = jax.random.normal(jax.random.key(2), (4, 8))

    def loss_pp(p):
        return gpipe(stage_fn, p, x, num_microbatches=2, mesh=state.mesh).sum()

    def loss_seq(p):
        return sequential(p, x).sum()

    g_pp = jax.grad(loss_pp)(params)
    g_seq = jax.grad(loss_seq)(params)
    np.testing.assert_allclose(np.asarray(g_pp["w"]), np.asarray(g_seq["w"]), rtol=1e-4, atol=1e-6)


def test_gpipe_pp1_fallback():
    state = AcceleratorState()  # pp == 1
    params = make_stages(3, 8)
    x = jax.random.normal(jax.random.key(3), (4, 8))
    out = gpipe(stage_fn, params, x, num_microbatches=2, mesh=state.mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(sequential(params, x)), rtol=1e-5)


def test_gpipe_bad_microbatch():
    state = AcceleratorState(parallelism_config=ParallelismConfig(pp_size=4, dp_size=2))
    params = make_stages(4, 8)
    with pytest.raises(ValueError):
        gpipe(stage_fn, params, jnp.ones((6, 8)), num_microbatches=4, mesh=state.mesh)
