"""dtype-widen: accidental float64 on TPU paths.

TPUs have no f64 ALU: with x64 enabled, every float64 op is emulated at a
fraction of peak FLOPs and doubles HBM traffic; with x64 off (the JAX
default), a float64 dtype request silently truncates to f32 — either way the
author didn't get what they wrote.  Flagged: float64/double dtypes handed to
jnp constructors, ``.astype(jnp.float64)``, ``jnp.float64(...)`` casts, and
library code flipping ``jax_enable_x64`` globally.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Rule

_WIDE_ATTRS = {"jax.numpy.float64", "jax.numpy.double", "numpy.float64", "numpy.double"}
_WIDE_STRS = {"float64", "double", "f8", "<f8", ">f8"}
# jnp constructors whose dtype can also arrive positionally
_DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "asarray": 1, "array": 1, "full": 2}


class DtypeWiden(Rule):
    id = "dtype-widen"
    kind = "reachability"
    description = "float64 promotion on a TPU path (jnp dtype, astype, or jax_enable_x64)"

    def _is_wide(self, module, node: ast.AST, allow_builtin_float: bool) -> bool:
        resolved = module.resolve(node)
        if resolved in _WIDE_ATTRS:
            return True
        if isinstance(node, ast.Constant) and node.value in _WIDE_STRS:
            return True
        if allow_builtin_float and isinstance(node, ast.Name) and node.id == "float":
            return True  # dtype=float means float64 under x64
        return False

    def check(self, module, ctx):
        findings = []

        def hit(node, msg):
            findings.append(
                Finding(self.id, module.rel_path, node.lineno, node.col_offset, msg)
            )

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            resolved = module.resolve(fn) or ""
            leaf = resolved.rsplit(".", 1)[-1]
            if resolved in ("jax.numpy.float64", "jax.numpy.double"):
                hit(node, f"jnp.{leaf}() cast — TPUs emulate f64; use jnp.float32")
            elif resolved.startswith("jax."):
                # dtype= kwarg on any jax/jnp call, plus positional dtype slots
                dtype_expr = None
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        dtype_expr = kw.value
                if dtype_expr is None and leaf in _DTYPE_POS:
                    pos = _DTYPE_POS[leaf]
                    if len(node.args) > pos:
                        dtype_expr = node.args[pos]
                if dtype_expr is not None and self._is_wide(module, dtype_expr, True):
                    hit(
                        node,
                        f"float64 dtype passed to {leaf}() — TPUs emulate f64 "
                        "(or silently truncate with x64 off); use float32/bfloat16",
                    )
                if resolved == "jax.config.update" and node.args:
                    arg0 = node.args[0]
                    truthy = len(node.args) > 1 and not (
                        isinstance(node.args[1], ast.Constant) and not node.args[1].value
                    )
                    if (
                        isinstance(arg0, ast.Constant)
                        and arg0.value == "jax_enable_x64"
                        and truthy
                    ):
                        hit(
                            node,
                            "jax_enable_x64 flipped globally in library code — "
                            "every downstream op widens to f64 on TPU",
                        )
            elif isinstance(fn, ast.Attribute) and fn.attr == "astype" and node.args:
                # .astype(jnp.float64) is unambiguous; .astype(np.float64) only
                # matters inside traced code (host numpy f64 is fine)
                arg = node.args[0]
                if module.resolve(arg) in ("jax.numpy.float64", "jax.numpy.double"):
                    hit(node, ".astype(jnp.float64) — TPUs emulate f64; use float32")
                elif self._is_wide(module, arg, False):
                    reached = module.callgraph.reached
                    for info, _ in module.callgraph.traced_functions():
                        lo = info.node.lineno
                        hi = getattr(info.node, "end_lineno", lo)
                        if lo <= node.lineno <= hi and info.qualname in reached:
                            hit(
                                node,
                                ".astype(float64) inside traced code — TPUs "
                                "emulate f64; use float32",
                            )
                            break
        return findings
