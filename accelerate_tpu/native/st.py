"""Native safetensors-compatible writer/reader.

The reference's checkpoint bodies are written by vendored native
serialization (torch save / safetensors' Rust core behind
``safetensors.numpy``).  This module is the tpu-native equivalent: it speaks
the same on-disk format — 8-byte LE header length, JSON header mapping tensor
name → {dtype, shape, data_offsets}, then raw little-endian tensor bodies —
but streams each body with the chunked parallel pwrite/pread in
``fastloader.cc``, so checkpoint shards never funnel through a single
serialized write() and large reads fill preallocated buffers in parallel.

Files written here load with ``safetensors.numpy.load_file`` / ``safe_open``
and vice versa (round-trip covered by tests/test_native.py).  Callers should
guard with ``native.available()`` and fall back to the safetensors package —
both save paths in utils/fsdp_utils.py do.
"""

from __future__ import annotations

import json
import os
import struct

import numpy as np

from . import available, read_into, write_region

_NP_TO_ST = {
    "float64": "F64",
    "float32": "F32",
    "float16": "F16",
    "int64": "I64",
    "int32": "I32",
    "int16": "I16",
    "int8": "I8",
    "uint8": "U8",
    "uint16": "U16",
    "uint32": "U32",
    "uint64": "U64",
    "bool": "BOOL",
    "bfloat16": "BF16",  # ml_dtypes
}
_ST_TO_NP = {v: k for k, v in _NP_TO_ST.items()}


def _np_dtype(st_name: str) -> np.dtype:
    if st_name == "BF16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(_ST_TO_NP[st_name])


def save_file(tensors: dict[str, np.ndarray], path: str,
              metadata: dict[str, str] | None = None) -> None:
    """Write a safetensors file with parallel native body IO."""
    path = os.fspath(path)
    header: dict = {}
    if metadata:
        header["__metadata__"] = dict(metadata)
    bodies: list[tuple[str, np.ndarray, int, int]] = []
    offset = 0
    for name, arr in tensors.items():
        # ascontiguousarray promotes 0-d to (1,) — restore the true shape so
        # scalar parameters round-trip intact
        arr = np.ascontiguousarray(arr).reshape(np.shape(arr))
        dt = _NP_TO_ST.get(arr.dtype.name)
        if dt is None:
            raise TypeError(f"unsupported dtype for safetensors: {arr.dtype}")
        end = offset + arr.nbytes
        header[name] = {
            "dtype": dt,
            "shape": list(arr.shape),
            "data_offsets": [offset, end],
        }
        bodies.append((name, arr, offset, end))
        offset = end
    raw_header = json.dumps(header, separators=(",", ":")).encode()
    # 8-byte alignment of the first body keeps mmap'd readers happy
    pad = (8 - (len(raw_header) % 8)) % 8
    raw_header += b" " * pad
    base = 8 + len(raw_header)
    # Bodies are laid out contiguously in dict order, so stream small tensors
    # through the buffered Python fd (a 300-entry state dict must not pay 300
    # opens + thread spawns) and hand only large bodies to the parallel
    # region writer.
    big_cutoff = 4 << 20
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(raw_header)))
        f.write(raw_header)
        f.truncate(base + offset)
        for _, arr, lo, _ in bodies:
            if 0 < arr.nbytes <= big_cutoff:
                f.seek(base + lo)
                # tobytes, not memoryview: custom dtypes (ml_dtypes bf16)
                # don't support the buffer protocol; tensors here are small
                f.write(arr.tobytes())
    for _, arr, lo, _ in bodies:
        if arr.nbytes > big_cutoff:
            write_region(path, arr, base + lo)


# safetensors' own Rust core rejects headers above 100 MB; mirror that so a
# corrupt/hostile u64 length can't drive a multi-GB read
_MAX_HEADER = 100 << 20


def _read_header(path: str) -> tuple[dict, int, int]:
    fsize = os.path.getsize(path)
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        if hlen > min(fsize - 8, _MAX_HEADER):
            raise ValueError(
                f"corrupt safetensors header in {path}: declared length {hlen} "
                f"exceeds file size {fsize} (cap {_MAX_HEADER})"
            )
        header = json.loads(f.read(hlen))
    return header, 8 + hlen, fsize


def _check_entry(path: str, name: str, meta: dict, base: int, fsize: int) -> tuple[int, int]:
    """Validate one header entry's offsets against its shape/dtype and the file."""
    try:
        lo, hi = meta["data_offsets"]
        expect = int(np.prod(meta["shape"], dtype=np.int64)) * _np_dtype(meta["dtype"]).itemsize
    except (KeyError, TypeError, OverflowError) as exc:
        # unknown dtype / non-numeric shape must honor the same loud-ValueError
        # contract callers catch for corrupt checkpoints
        raise ValueError(
            f"corrupt safetensors entry {name!r} in {path}: {exc!r}"
        ) from exc
    if lo < 0 or hi < lo or hi - lo != expect or base + hi > fsize:
        raise ValueError(
            f"corrupt safetensors entry {name!r} in {path}: data_offsets "
            f"[{lo}, {hi}) do not match shape {meta['shape']} × {meta['dtype']} "
            f"({expect} bytes) within file of {fsize} bytes"
        )
    return lo, hi


def load_file(path: str, writable: bool = True) -> dict[str, np.ndarray]:
    """Read the whole body in ONE parallel pread, then split per tensor.

    Default (``writable=True``) returns independent writable arrays — the
    same contract as ``safetensors.numpy.load_file``, so programs behave
    identically whether or not the native library built.  ``writable=False``
    skips the per-tensor copy and returns READ-ONLY zero-copy views over the
    shared body buffer (in-place writes raise) — for internal hot paths that
    only read, e.g. the sharded-checkpoint merge.
    """
    path = os.fspath(path)
    header, base, fsize = _read_header(path)
    entries = [(k, m) for k, m in header.items() if k != "__metadata__"]
    for name, meta in entries:
        _check_entry(path, name, meta, base, fsize)
    total = max((m["data_offsets"][1] for _, m in entries), default=0)
    body = np.empty(total, np.uint8)
    if total:
        read_into(path, body, offset=base)
    out: dict[str, np.ndarray] = {}
    for name, meta in entries:
        lo, hi = meta["data_offsets"]
        arr = body[lo:hi].view(_np_dtype(meta["dtype"])).reshape(meta["shape"])
        if writable:
            arr = arr.copy()
        else:
            # an in-place write would silently corrupt the sibling tensors
            # sharing the body buffer — force callers to copy instead
            arr.flags.writeable = False
        out[name] = arr
    return out


def load_tensor(path: str, name: str) -> np.ndarray:
    """Read a single tensor body without touching the rest of the file."""
    path = os.fspath(path)
    header, base, fsize = _read_header(path)
    meta = header[name]
    lo, hi = _check_entry(path, name, meta, base, fsize)
    arr = np.empty(meta["shape"], dtype=_np_dtype(meta["dtype"]))
    if hi > lo:
        read_into(path, arr, offset=base + lo)
    return arr


def pick_save_file():
    """Native ``save_file`` when the library is up, else the safetensors one.

    Single source for the fallback choice so call sites (fsdp_utils save /
    load / merge) cannot drift.
    """
    if available():
        return save_file
    from safetensors.numpy import save_file as pkg_save

    return pkg_save


def pick_load_file():
    """Native ``load_file`` when the library is up, else the safetensors one.

    Both return independent writable arrays (native defaults to
    ``writable=True``), so behavior is machine-independent.  Internal
    read-only hot paths that want the zero-copy views call
    ``load_file(path, writable=False)`` explicitly instead of going through
    this picker.
    """
    if available():
        return load_file
    from safetensors.numpy import load_file as pkg_load

    return pkg_load


__all__ = ["save_file", "load_file", "load_tensor", "available",
           "pick_save_file", "pick_load_file"]
