"""The flagship end-to-end correctness suite, run through the launcher.

Counterpart of ``/root/reference/src/accelerate/test_utils/scripts/test_script.py``
(process control :93, RNG sync :174, DL preparation :192-363, mock_training
:436-454, split_between_processes :519, trigger sync :665-819).  ``accelerate-tpu
test`` runs exactly this script for end users; the pytest suite launches it on
an 8-virtual-device CPU mesh (SURVEY.md §4 Pattern 2/3).

Every check works at any device/process count, including one.
"""

from __future__ import annotations

import os

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, PartialState, prepare_data_loader, set_seed
from accelerate_tpu.data_loader import skip_first_batches
from accelerate_tpu.nn import Tensor
from accelerate_tpu.test_utils.training import RegressionDataset, RegressionModel
from accelerate_tpu.utils.random import synchronize_rng_states


def test_state():
    state = PartialState()
    assert state.num_devices >= 1
    assert 0 <= state.process_index < state.num_processes
    state.wait_for_everyone()

    # split_between_processes covers everything exactly once across processes
    items = list(range(17))
    with state.split_between_processes(items) as mine:
        local = list(mine)
    assert len(local) >= 1
    gathered = []
    # gather via object gather only matters multi-process; single process is identity
    if state.num_processes == 1:
        assert local == items
    print("state ok")


def test_rng_sync():
    synchronize_rng_states(["jax"])
    import jax

    draw = jax.random.uniform(nn.random.default_rng.next_key(), (4,))
    arr = np.asarray(draw)
    # All processes/devices must draw identical numbers after a sync
    acc = Accelerator()
    gathered = np.asarray(acc.gather(arr.reshape(1, -1)))
    assert np.allclose(gathered, gathered[0]), "RNG out of sync across shards"
    print("rng sync ok")


def _dataset(n):
    return [{"x": np.float32(i), "y": np.float32(2 * i + 1)} for i in range(n)]


def _collect_seen(acc, dl) -> list[int]:
    """Iterate a loader, gather across shards, return the flat index list."""
    seen: list[int] = []
    for batch in dl:
        x = np.asarray(acc.gather(batch["x"]))
        seen.extend(int(v) for v in x.ravel())
    return seen


def test_dataloader_coverage():
    acc = Accelerator()
    n, bs = 22, 4  # uneven tail: 22 % (4*shards) != 0 for any shard count >1
    dl = prepare_data_loader(dataset=_dataset(n), batch_size=bs)
    seen = _collect_seen(acc, dl)
    # even_batches loops back to fill final batch: every index appears >= 1×
    assert set(seen) == set(range(n)), f"coverage broken: {sorted(set(seen))[:10]}..."
    assert len(seen) >= n
    print("dataloader coverage ok")


def test_dataloader_even_batches_off():
    acc = Accelerator()
    shards = max(1, acc.num_devices)
    n, bs = 22, 4
    dl = prepare_data_loader(dataset=_dataset(n), batch_size=bs, even_batches=False)
    seen = _collect_seen(acc, dl)
    # nothing is duplicated when even_batches is off
    assert len(seen) == len(set(seen)), "even_batches=False must not duplicate"
    assert set(seen) <= set(range(n))
    print("dataloader even_batches=False ok")


def test_dispatch_loader():
    """Dispatch mode: rank 0 reads, peers receive the global batch via
    broadcast (reference DataLoaderDispatcher, data_loader.py:696) — must
    cover the dataset exactly once at any device/process count (n is sized
    to divide the global batch so no even_batches loop-back occurs)."""
    acc = Accelerator()
    bs = 4
    n = 2 * bs * max(1, acc.num_devices)
    dl = prepare_data_loader(dataset=_dataset(n), batch_size=bs, dispatch_batches=True)
    seen = _collect_seen(acc, dl)
    assert sorted(seen) == list(range(n)), f"dispatch coverage broken: {sorted(seen)}"
    print("dispatch loader ok")


def test_skip_first_batches():
    acc = Accelerator()
    n, bs = 128, 4  # ≥4 global batches at any shard count ≤ 8
    dl = prepare_data_loader(dataset=_dataset(n), batch_size=bs)
    full = [np.asarray(acc.gather(b["x"])).ravel() for b in dl]
    skipped = skip_first_batches(dl, 2)
    rest = [np.asarray(acc.gather(b["x"])).ravel() for b in skipped]
    assert len(rest) == len(full) - 2
    for a, b in zip(full[2:], rest):
        assert np.array_equal(a, b), "skip_first_batches changed batch contents"
    print("skip_first_batches ok")


def mock_training():
    """Distributed training must match a numpy single-process baseline
    exactly (reference test_script.py:436: trained weights equality)."""
    set_seed(42)
    n, bs, lr, epochs = 64, 4, 0.1, 2
    data = RegressionDataset(length=n, seed=96)

    acc = Accelerator()
    model = RegressionModel()
    ds = [{"x": data.x[i], "y": data.y[i]} for i in range(n)]
    dl = prepare_data_loader(dataset=ds, batch_size=bs)
    opt = optim.SGD(model.parameters(), lr=lr)
    model, opt, dl = acc.prepare(model, opt, dl)

    for _ in range(epochs):
        for batch in dl:
            opt.zero_grad()
            pred = model(batch["x"])
            loss = nn.F.mse_loss(pred, Tensor(batch["y"]))
            acc.backward(loss)
            opt.step()

    # numpy baseline over the same global batch sequence
    a, b = 0.0, 0.0
    gbs = dl.total_batch_size
    order = np.arange(n)
    for _ in range(epochs):
        for start in range(0, n, gbs):
            idx = order[start : start + gbs]
            if len(idx) < gbs:  # even_batches loop-back
                idx = np.concatenate([idx, order[: gbs - len(idx)]])
            x, y = data.x[idx], data.y[idx]
            pred = a * x + b
            grad_a = float(np.mean(2 * (pred - y) * x))
            grad_b = float(np.mean(2 * (pred - y)))
            a -= lr * grad_a
            b -= lr * grad_b

    got_a = float(np.asarray(model.a.data))
    got_b = float(np.asarray(model.b.data))
    assert abs(got_a - a) < 1e-3, f"a: {got_a} vs baseline {a}"
    assert abs(got_b - b) < 1e-3, f"b: {got_b} vs baseline {b}"
    print(f"mock training ok (a={got_a:.4f}, b={got_b:.4f})")


def test_gather_for_metrics():
    """Duplicate-tail truncation: gathered sample count == dataset length
    (reference gather_for_metrics remainder logic, accelerator.py:2601)."""
    acc = Accelerator()
    n, bs = 22, 4
    dl = prepare_data_loader(dataset=_dataset(n), batch_size=bs)
    dl = acc.prepare(dl)
    seen = []
    for batch in dl:
        xs = acc.gather_for_metrics(batch["x"])
        seen.extend(int(v) for v in np.asarray(xs).ravel())
    assert sorted(seen) == list(range(n)), (
        f"gather_for_metrics must dedup the looped tail: got {len(seen)} items"
    )
    print("gather_for_metrics ok")


def test_save_load_roundtrip():
    """Multi-process checkpoint: save (rank-gated writes + per-process RNG),
    perturb, load, assert exact restoration on every process."""
    import shutil

    acc = Accelerator()
    model = RegressionModel()
    opt = optim.SGD(model.parameters(), lr=0.1)
    model, opt = acc.prepare(model, opt)
    # one training step so optimizer state is non-trivial
    ds = [{"x": np.float32(i), "y": np.float32(2 * i + 1)} for i in range(8)]
    dl = acc.prepare(prepare_data_loader(dataset=ds, batch_size=4))
    batch = next(iter(dl))
    opt.zero_grad()
    loss = nn.F.mse_loss(model(batch["x"]), Tensor(batch["y"]))
    acc.backward(loss)
    opt.step()
    saved_a = float(np.asarray(model.a.data))

    from accelerate_tpu.test_utils.testing import launch_scoped_tmpdir

    ckpt = launch_scoped_tmpdir("acc_tpu_ckpt")
    try:
        acc.save_state(ckpt)
        model.a.data = model.a.data * 0.0 + 123.0  # clobber
        acc.load_state(ckpt)
        got = float(np.asarray(model.a.data))
        assert abs(got - saved_a) < 1e-7, f"restore mismatch: {got} vs {saved_a}"
        acc.wait_for_everyone()
    finally:
        if acc.is_main_process:
            shutil.rmtree(ckpt, ignore_errors=True)
    print("save/load roundtrip ok")


def test_trigger():
    acc = Accelerator()
    acc.flag_tensor = None
    assert acc.check_trigger() is False
    acc.set_trigger()
    assert acc.check_trigger() is True
    assert acc.check_trigger() is False  # reset after firing
    print("trigger ok")


def main():
    acc = Accelerator()
    state = acc.state
    if state.is_main_process:
        print(f"** Testing on {state.num_devices} device(s), "
              f"{state.num_processes} process(es) **")
    test_state()
    test_rng_sync()
    test_dataloader_coverage()
    test_dataloader_even_batches_off()
    test_dispatch_loader()
    test_skip_first_batches()
    test_gather_for_metrics()
    mock_training()
    test_save_load_roundtrip()
    test_trigger()
    state.wait_for_everyone()
    if state.is_main_process:
        print("All checks passed")


if __name__ == "__main__":
    main()
