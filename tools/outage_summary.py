#!/usr/bin/env python
"""outage_summary — aggregate tools/tpu_when_up.sh probe logs.

    python tools/outage_summary.py TPU_OUTAGE_r*.log
    python tools/outage_summary.py --json TPU_OUTAGE_r05.log
    python tools/outage_summary.py TPU_OUTAGE_r05.log --bench-json BENCH_r05.json

The watcher writes one line per probe: ``<epoch-seconds> <STATE> <detail>``
where STATE is ``TPU_UP`` (probe saw a healthy accelerator) or ``DOWN``
(probe failed; detail is the last stderr line).  The raw logs were
write-only; this renders what the round verdicts actually need: total
up/down time, availability, and the longest DOWN window per log.

Interval attribution: the span between consecutive probes belongs to the
*earlier* probe's state (the probe cadence is ~4-6 min, so this is the
finest resolution the data supports).  The span after the final probe is
unknown and excluded.  Exit 0 on success, 2 when no parseable probe lines
were found in any input.

``--bench-json`` joins the logs' DOWN windows against a benchmark
artifact's init diagnostics (init_attempts/init_detail/fallback — emitted
by bench.py via resilience.backend.InitReport): was the recorded init
failure inside a DOWN window the watcher independently observed?  Accepts
both raw bench output and the driver-wrapped ``{"parsed": {...}}`` form;
the time join needs the ``init_ts`` key (emitted since the library init
path landed) — older artifacts without it report the overlap as unknown.

``--telemetry-jsonl`` joins the logs' DOWN windows against a telemetry
JSONL dump's ``kind="autopilot"`` decision records (docs/elastic.md
§autopilot): a post-mortem then shows what the autopilot DID during each
outage — which signal fired, whether it resized or suppressed, and the
dp move — instead of reconstructing it from scattered logs.  Decisions
carry a wall-clock ``ts``; records without one are counted but cannot be
joined.

``--blackbox`` joins the logs' DOWN windows against per-rank flight-
recorder dumps (``blackbox_rank*.json`` files or directories holding
them — docs/telemetry.md §flight recorder): each dump's wall-clock
``time_unix`` stamp places the watchdog stall / fatal signal on the same
absolute timeline as the probe log, answering whether a recorded hang
happened while the watcher independently saw the accelerator DOWN.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def parse_log(path: str) -> list[tuple[int, bool]]:
    """[(epoch_seconds, is_up), ...] in file order; unparseable lines skipped."""
    probes: list[tuple[int, bool]] = []
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            parts = line.split(None, 2)
            if len(parts) < 2 or not parts[0].isdigit():
                continue
            state = parts[1].upper()
            if state not in ("TPU_UP", "UP", "DOWN"):
                continue
            probes.append((int(parts[0]), state != "DOWN"))
    return probes


def summarize(probes: list[tuple[int, bool]]) -> dict:
    up_s = down_s = 0
    transitions = 0
    longest_down = {"seconds": 0, "start": None, "end": None}
    run_start: int | None = None  # start epoch of the current DOWN run
    for (t0, state0), (t1, state1) in zip(probes, probes[1:]):
        span = max(0, t1 - t0)
        if state0:
            up_s += span
        else:
            down_s += span
            if run_start is None:
                run_start = t0
        if state0 != state1:
            transitions += 1
        # a DOWN run ends when the *next* probe is up (or at the last probe)
        if run_start is not None and (state1 or (t1, state1) == probes[-1]):
            if t1 - run_start > longest_down["seconds"]:
                longest_down = {"seconds": t1 - run_start, "start": run_start, "end": t1}
            if state1:
                run_start = None
    observed = up_s + down_s
    return {
        "probes": len(probes),
        "probes_up": sum(1 for _, up in probes if up),
        "probes_down": sum(1 for _, up in probes if not up),
        "first_probe": probes[0][0] if probes else None,
        "last_probe": probes[-1][0] if probes else None,
        "observed_s": observed,
        "up_s": up_s,
        "down_s": down_s,
        "availability_pct": round(100.0 * up_s / observed, 1) if observed else None,
        "transitions": transitions,
        "longest_down_s": longest_down["seconds"],
        "longest_down_start": longest_down["start"],
        "longest_down_end": longest_down["end"],
    }


def down_windows(probes: list[tuple[int, bool]]) -> list[dict]:
    """Every DOWN window as {start, end, seconds}: from its first DOWN probe
    to the next UP probe (or the last probe for a trailing run) — the same
    attribution summarize() uses for longest_down."""
    windows: list[dict] = []
    run_start: int | None = None
    last = probes[-1] if probes else None
    for (t0, state0), (t1, state1) in zip(probes, probes[1:]):
        if not state0 and run_start is None:
            run_start = t0
        if run_start is not None and (state1 or (t1, state1) == last):
            windows.append({"start": run_start, "end": t1, "seconds": t1 - run_start})
            run_start = None
    return windows


def load_bench_diag(path: str) -> dict:
    """Init diagnostics out of a bench JSON artifact (raw bench.py output or
    the driver's {"parsed": {...}} wrapper)."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    parsed = data
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        parsed = data["parsed"]
    if not isinstance(parsed, dict):
        return {}
    keys = (
        "init_attempts", "init_detail", "platform_requested", "fallback",
        "init_ts", "platform",
    )
    return {k: parsed[k] for k in keys if parsed.get(k) is not None}


def join_bench(path: str, diag: dict, windows: list[dict]) -> dict:
    """Did this bench's init failure land inside an observed DOWN window?"""
    out = {"bench": path, **diag}
    out["init_failed"] = bool(diag.get("fallback")) or (
        (diag.get("init_attempts") or 0) > 1
    )
    ts = diag.get("init_ts")
    if ts is None:
        out["in_down_window"] = None  # pre-init_ts artifact: overlap unknown
        return out
    for window in windows:
        if window["start"] <= ts <= window["end"]:
            out["in_down_window"] = True
            out["down_window"] = window
            return out
    out["in_down_window"] = False
    return out


def load_autopilot_records(path: str) -> list[dict]:
    """``kind="autopilot"`` decision records out of a telemetry JSONL dump;
    unparseable lines are skipped (the dump interleaves every record
    kind)."""
    records: list[dict] = []
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError:
                continue
            if isinstance(record, dict) and record.get("kind") == "autopilot":
                records.append(record)
    return records


def _decision_summary(record: dict) -> dict:
    out = {
        "ts": record.get("ts"),
        "signal": record.get("signal"),
        "action": record.get("action"),
        "fired": bool(record.get("fired")),
        "suppressed": bool(record.get("suppressed")),
    }
    if record.get("reason"):
        out["reason"] = record["reason"]
    resize = record.get("resize")
    if isinstance(resize, dict):
        out["resize"] = {
            k: resize.get(k) for k in ("old_dp", "dp", "direction")
        }
    return out


def join_autopilot(path: str, records: list[dict], windows: list[dict]) -> dict:
    """What the autopilot did during each observed DOWN window — decisions
    whose ``ts`` falls inside the window, plus totals for decisions outside
    every window and records carrying no timestamp."""
    timed = [r for r in records if isinstance(r.get("ts"), (int, float))]
    per_window = []
    joined_ids = set()
    for window in windows:
        inside = [
            r for r in timed if window["start"] <= r["ts"] <= window["end"]
        ]
        joined_ids.update(id(r) for r in inside)
        per_window.append(
            {
                "window": window,
                "decisions": [_decision_summary(r) for r in inside],
                "fired": sum(1 for r in inside if r.get("fired")),
                "suppressed": sum(1 for r in inside if r.get("suppressed")),
            }
        )
    return {
        "telemetry": path,
        "decisions_total": len(records),
        "decisions_no_ts": len(records) - len(timed),
        "decisions_outside_windows": sum(
            1 for r in timed if id(r) not in joined_ids
        ),
        "windows": per_window,
    }


def render_autopilot_join(joined: dict) -> str:
    lines = [
        f"{joined['telemetry']}: {joined['decisions_total']} autopilot "
        f"decision(s) ({joined['decisions_outside_windows']} outside DOWN "
        "windows"
        + (
            f", {joined['decisions_no_ts']} without ts"
            if joined["decisions_no_ts"]
            else ""
        )
        + ")"
    ]
    for entry in joined["windows"]:
        w = entry["window"]
        lines.append(
            f"  DOWN {_utc(w['start'])} → {_utc(w['end'])} "
            f"({_hms(w['seconds'])}): {len(entry['decisions'])} decision(s), "
            f"{entry['fired']} fired, {entry['suppressed']} suppressed"
        )
        for d in entry["decisions"]:
            offset = (
                f"+{int(d['ts'] - w['start'])}s" if d.get("ts") is not None else "?"
            )
            verdict = "fired" if d["fired"] else (
                "suppressed" if d["suppressed"] else "quiet"
            )
            detail = f"    {offset} {d.get('action')}({d.get('signal')}) {verdict}"
            resize = d.get("resize")
            if resize and resize.get("old_dp") is not None:
                detail += f" dp {resize['old_dp']}->{resize['dp']}"
            if d.get("reason"):
                detail += f" ({d['reason']})"
            lines.append(detail)
    return "\n".join(lines)


def load_blackbox_dumps(path: str) -> list[dict]:
    """Per-rank blackbox payloads from a dump file or a directory of them
    (tools/blackbox_report.py owns the parsing rules)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import blackbox_report

    dumps = []
    for p in blackbox_report.find_dumps([path]):
        dump = blackbox_report.load_dump(p)
        if dump is not None:
            dumps.append(dump)
    return dumps


def join_blackbox(path: str, dumps: list[dict], windows: list[dict]) -> dict:
    """Did each rank's dump land inside an observed DOWN window?"""
    per_dump = []
    for dump in dumps:
        entry = {
            "rank": dump.get("rank"),
            "reason": dump.get("reason"),
            "collective_seq": dump.get("collective_seq"),
            "time_unix": dump.get("time_unix"),
        }
        ts = dump.get("time_unix")
        if ts is None:
            entry["in_down_window"] = None
        else:
            entry["in_down_window"] = False
            for window in windows:
                if window["start"] <= ts <= window["end"]:
                    entry["in_down_window"] = True
                    entry["down_window"] = window
                    break
        per_dump.append(entry)
    return {
        "blackbox": path,
        "dumps": per_dump,
        "in_down_windows": sum(1 for d in per_dump if d["in_down_window"]),
    }


def render_blackbox_join(joined: dict) -> str:
    lines = [
        f"{joined['blackbox']}: {len(joined['dumps'])} blackbox dump(s), "
        f"{joined['in_down_windows']} inside observed DOWN windows"
    ]
    for d in joined["dumps"]:
        if d["in_down_window"] is None:
            verdict = "no timestamp"
        elif d["in_down_window"]:
            w = d["down_window"]
            verdict = f"inside DOWN {_utc(w['start'])} → {_utc(w['end'])}"
        else:
            verdict = "NOT inside any observed DOWN window"
        lines.append(
            f"  rank {d['rank']} ({d['reason']}, seq={d['collective_seq']}) "
            f"at {_utc(d['time_unix'])}: {verdict}"
        )
    return "\n".join(lines)


def render_bench_join(joined: dict) -> str:
    label = "init failed" if joined["init_failed"] else "init ok"
    detail = (
        f"{joined['bench']}: {label} "
        f"(attempts={joined.get('init_attempts', '?')}"
        + (f", fallback={joined['fallback']}" if joined.get("fallback") else "")
        + ")"
    )
    if joined["in_down_window"] is None:
        verdict = "overlap unknown (no init_ts in bench JSON)"
    elif joined["in_down_window"]:
        w = joined["down_window"]
        verdict = (
            f"inside DOWN window {_utc(w['start'])} → {_utc(w['end'])} "
            f"({_hms(w['seconds'])})"
        )
    else:
        verdict = "NOT inside any observed DOWN window"
    return f"{detail}\n  {verdict}"


def _hms(seconds) -> str:
    if not seconds:
        return "0m"
    h, rem = divmod(int(seconds), 3600)
    m = rem // 60
    return f"{h}h{m:02d}m" if h else f"{m}m"


def _utc(epoch) -> str:
    if epoch is None:
        return "-"
    return time.strftime("%Y-%m-%d %H:%MZ", time.gmtime(epoch))


def render(path: str, s: dict) -> str:
    avail = f"{s['availability_pct']}%" if s["availability_pct"] is not None else "n/a"
    lines = [
        f"{path}: {s['probes']} probes "
        f"({_utc(s['first_probe'])} → {_utc(s['last_probe'])})",
        f"  up   {_hms(s['up_s']):>7}   down {_hms(s['down_s']):>7}   "
        f"availability {avail}   transitions {s['transitions']}",
        f"  longest DOWN window: {_hms(s['longest_down_s'])}"
        + (
            f" ({_utc(s['longest_down_start'])} → {_utc(s['longest_down_end'])})"
            if s["longest_down_start"] is not None
            else ""
        ),
    ]
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="outage_summary", description=__doc__)
    parser.add_argument("logs", nargs="+", help="TPU_OUTAGE_r*.log files")
    parser.add_argument("--json", action="store_true", help="machine output")
    parser.add_argument(
        "--bench-json",
        nargs="+",
        default=[],
        metavar="BENCH",
        help="BENCH_r*.json artifacts to join against the logs' DOWN windows",
    )
    parser.add_argument(
        "--telemetry-jsonl",
        nargs="+",
        default=[],
        metavar="JSONL",
        help="telemetry JSONL dumps whose kind=\"autopilot\" decision "
        "records are joined against the logs' DOWN windows",
    )
    parser.add_argument(
        "--blackbox",
        nargs="+",
        default=[],
        metavar="DUMP",
        help="blackbox_rank*.json flight-recorder dumps (or directories of "
        "them) whose wall-clock stamps are joined against the logs' DOWN "
        "windows",
    )
    args = parser.parse_args(argv)

    summaries = {}
    all_windows: list[dict] = []
    for path in args.logs:
        try:
            probes = parse_log(path)
        except OSError as e:
            print(f"outage_summary: cannot read {path}: {e}", file=sys.stderr)
            continue
        if not probes:
            print(f"outage_summary: no probe lines in {path}", file=sys.stderr)
            continue
        summaries[path] = summarize(probes)
        all_windows.extend(down_windows(probes))

    if not summaries:
        return 2

    bench_joins: list[dict] = []
    for path in args.bench_json:
        try:
            diag = load_bench_diag(path)
        except (OSError, ValueError) as e:
            print(f"outage_summary: cannot read bench {path}: {e}", file=sys.stderr)
            continue
        bench_joins.append(join_bench(path, diag, all_windows))

    autopilot_joins: list[dict] = []
    for path in args.telemetry_jsonl:
        try:
            records = load_autopilot_records(path)
        except OSError as e:
            print(
                f"outage_summary: cannot read telemetry {path}: {e}",
                file=sys.stderr,
            )
            continue
        autopilot_joins.append(join_autopilot(path, records, all_windows))

    blackbox_joins: list[dict] = []
    for path in args.blackbox:
        try:
            dumps = load_blackbox_dumps(path)
        except OSError as e:
            print(
                f"outage_summary: cannot read blackbox {path}: {e}",
                file=sys.stderr,
            )
            continue
        if not dumps:
            print(
                f"outage_summary: no blackbox dumps in {path}", file=sys.stderr
            )
            continue
        blackbox_joins.append(join_blackbox(path, dumps, all_windows))

    if args.json:
        payload: dict = dict(summaries)
        if bench_joins:
            payload["bench_join"] = bench_joins
        if autopilot_joins:
            payload["autopilot_join"] = autopilot_joins
        if blackbox_joins:
            payload["blackbox_join"] = blackbox_joins
        print(json.dumps(payload, indent=2))
    else:
        for path, s in summaries.items():
            print(render(path, s))
        for joined in bench_joins:
            print(render_bench_join(joined))
        for joined in autopilot_joins:
            print(render_autopilot_join(joined))
        for joined in blackbox_joins:
            print(render_blackbox_join(joined))
    return 0


if __name__ == "__main__":
    sys.exit(main())
