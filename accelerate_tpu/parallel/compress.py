"""Quantized dp-axis collectives: ONE compression layer for gradient and
ZeRO-1 weight-update traffic inside the captured step.

EQuARX (PAPERS.md #3) shows ~2x effective-bandwidth wins from quantized
all-reduce inside XLA; the cross-replica weight-update paper (PAPERS.md #2)
is the basis of our ZeRO-1 reduce-scatter → 1/dp-local-update → all-gather
shape.  This module is where both meet: a :class:`CompressionPolicy`
(``none`` / ``int8`` / ``fp8`` / ``powersgd``) that owns

* **the dp-collective pair of the ZeRO-1 captured update** —
  :meth:`CompressionPolicy.reduce_scatter` quantizes the gradient's trip to
  the dp-sharded update (per-block scales, one scale per index of the
  sharded axis so every block is shard-local) and
  :meth:`CompressionPolicy.all_gather` transports the updated param back as
  a quantized *delta* against the replica's current value;
* **error feedback** — the reduce-scatter side carries a residual with the
  SAME ``NamedSharding`` as the ZeRO-1 optimizer state (1/dp bytes per
  replica), threaded through ``CapturedStep`` exactly like optax moments
  (``Optimizer.capture_state``) so replays cost zero extra recompiles; the
  all-gather side needs none — transporting the *delta* against the
  replica's current value is implicitly error-feedback (see
  :meth:`CompressionPolicy.all_gather`);
* **the comm-hook boundary** — PowerSGD's rank-k + error-feedback
  recurrence lives here now (moved from ``utils/powersgd.py``, which
  delegates), selected through the same policy surface, so hook selection,
  eligibility gates and error-feedback state management are one code path;
* **collective-bytes attribution** — :func:`collective_bytes` computes the
  analytic per-step dp-axis wire bytes for a policy, recorded through
  telemetry (``kind="collectives"``) and A/B'd by ``bench.py``.

Error-feedback semantics (docs/compression.md): in the GSPMD formulation
the dp gradient *sum* happens inside the backward (XLA's psum), so the
summed gradient is replicated when it reaches the update.  The
reduce-scatter entry transmits ``Q(g)`` and corrects shard-locally:
``g_used = Q(g) + err_prev``, ``err_new = g_shard - Q(g)_shard`` — the
injected error telescopes across steps, the standard EF guarantee, and the
residual never needs gathering.  The all-gather entry transports the
quantized delta against the replica's current value, whose feedback is
implicit (the untransmitted part of this step's delta IS next step's).

Quantization grid: one fp32 scale per index of the dp-sharded axis
("per-block", block = one slice), ``amax``-scaled, so quantize/dequantize
are shard-local for every dp extent dividing the axis.  int8 rounds to
±127; fp8 rides ``float8_e4m3fn`` (±448).

Enable with ``ACCELERATE_COMPRESSION=int8`` (or ``fp8``/``powersgd``/
``batched_powersgd``) or
``Accelerator(kwargs_handlers=[CompressionKwargs(policy="int8")])``.
``none`` (the default) leaves every existing code path byte-identical.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .sharding import canonical_spec

__all__ = [
    "CompressionPolicy",
    "NoneCompression",
    "Int8Compression",
    "Fp8Compression",
    "PowerSGDCompression",
    "quantize",
    "dequantize",
    "shard_accumulation",
    "collective_bytes",
    "resolve_policy",
    "eligible_matrix_shape",
    "init_powersgd_state",
    "apply_powersgd",
    "init_batched_powersgd_state",
    "apply_batched_powersgd",
]


# ---------------------------------------------------------------------------
# quantization primitives (per-block scales along the sharded axis)
# ---------------------------------------------------------------------------

# saturation value of each wire dtype: int8 rounds onto ±127, float8_e4m3fn
# encodes ±448 natively
_QMAX = {"int8": 127.0, "float8_e4m3fn": 448.0}


def _qmax(wire_dtype) -> float:
    name = jnp.dtype(wire_dtype).name
    if name not in _QMAX:
        raise ValueError(f"unsupported wire dtype {name!r}; use int8 or float8_e4m3fn")
    return _QMAX[name]


def quantize(x, axis: int, wire_dtype=jnp.int8):
    """``x`` (fp32) → ``(payload, scales)`` with one scale per index of
    ``axis``.

    Blocks are the slices along ``axis`` — the axis ZeRO-1 shards over dp —
    so quantization is independent per block and therefore shard-local for
    any dp extent dividing the axis.  Zero blocks quantize to zero payload
    with a zero scale (dequantize returns exact zeros).
    """
    qmax = _qmax(wire_dtype)
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    amax = jnp.max(jnp.abs(x), axis=reduce_axes, keepdims=True)
    scales = amax / qmax
    safe = jnp.where(scales > 0, scales, 1.0)
    y = x / safe
    if jnp.issubdtype(jnp.dtype(wire_dtype), jnp.integer):
        payload = jnp.clip(jnp.round(y), -qmax, qmax).astype(wire_dtype)
    else:
        payload = jnp.clip(y, -qmax, qmax).astype(wire_dtype)
    return payload, scales


def dequantize(payload, scales):
    """Inverse of :func:`quantize`: broadcast-multiply the per-block scales
    back in.  The ONLY sanctioned way to widen a wire payload — a bare
    ``payload.astype(float32)`` discards the scales (graftlint's
    ``dtype-widen`` rule flags exactly that outside this module)."""
    return payload.astype(jnp.float32) * scales


def _to_layout(x, sharding):
    """Commit/constrain ``x`` to ``sharding`` — ``with_sharding_constraint``
    for tracers (captured step), ``device_put`` eagerly (same split as
    ``Optimizer._on_param_layout``)."""
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)


def _scales_sharding(sharding: jax.sharding.NamedSharding, axis: int, ndim: int):
    """Sharding for the keepdims scale vector: same mesh, the sharded-axis
    entry preserved, every size-1 dim unsharded."""
    spec = list(sharding.spec) + [None] * (ndim - len(sharding.spec))
    out = [None] * ndim
    out[axis] = spec[axis]
    return jax.sharding.NamedSharding(
        sharding.mesh, canonical_spec(jax.sharding.PartitionSpec(*out), sharding.mesh)
    )


def _drop_axis_entry(sharding: jax.sharding.NamedSharding, axis: int, ndim: int):
    """The same layout with the dp entry at ``axis`` removed — the
    replicated-over-dp target of the all-gather."""
    spec = list(sharding.spec) + [None] * (ndim - len(sharding.spec))
    spec[axis] = None
    return jax.sharding.NamedSharding(
        sharding.mesh, canonical_spec(jax.sharding.PartitionSpec(*spec), sharding.mesh)
    )


def shard_accumulation(grad, sharding):
    """ZeRO-2 entry point: keep an accumulated gradient reduce-scattered
    between micro-steps, so the accumulation buffer is 1/dp per replica.

    Layout-only by design: re-quantizing a running fp32 accumulation every
    micro-step would pass the sum through wire rounding ``num_steps`` times
    (the same reason ``Accelerator.backward`` compresses only at the sync
    boundary).  On hardware the backward's psum against a dp-sharded
    consumer lowers to a reduce-scatter; the value is unchanged.
    """
    return _to_layout(grad, sharding)


# ---------------------------------------------------------------------------
# policies
# ---------------------------------------------------------------------------
class CompressionPolicy:
    """One compression strategy for dp-axis traffic.

    Two independent capabilities, so one abstraction covers both stories:

    * ``quantizes_collectives`` — the policy compresses the ZeRO-1
      reduce-scatter / all-gather pair (:meth:`reduce_scatter` /
      :meth:`all_gather`), with per-param residuals managed by the
      Optimizer (dp-sharded, capture-threaded);
    * ``hook_name`` — the policy runs at the backward sync boundary as a
      comm hook (PowerSGD); ``None`` for the quantizing policies.
    """

    name: str = "none"
    wire_dtype = None
    quantizes_collectives: bool = False
    hook_name: Optional[str] = None

    def __init__(self, min_size: int = 2048, min_block: int = 8,
                 error_feedback: bool = True):
        self.min_size = int(min_size)
        self.min_block = int(min_block)
        self.error_feedback = bool(error_feedback)

    # -- eligibility (shared gate for both directions) -----------------------
    def eligible(self, shape: tuple, dtype, axis: Optional[int]) -> bool:
        """min-size / dtype / block-geometry gates: tiny tensors, non-float
        tensors, and tensors whose per-block slice is too small to amortize
        the fp32 scale vector pass through uncompressed."""
        if not self.quantizes_collectives or axis is None:
            return False
        if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
            return False
        n = int(math.prod(shape))
        if n < self.min_size:
            return False
        block = n // int(shape[axis])
        return block >= self.min_block

    # -- ZeRO-1 collective pair ---------------------------------------------
    def reduce_scatter(self, x32, sharding, axis: int, err):
        """Transport a (dp-replicated, already psum'd) fp32 gradient to the
        dp-sharded update layout through the wire dtype.

        Returns ``(g_used, err_new)`` — both dp-sharded fp32.  ``g_used``
        is what the local update consumes; ``err_new`` replaces the
        residual (``None`` stays ``None`` when error feedback is off).
        """
        payload, scales = quantize(x32, axis, self.wire_dtype)
        payload = _to_layout(payload, sharding)  # the wire: 1-byte scatter
        scales = _to_layout(scales, _scales_sharding(sharding, axis, x32.ndim))
        wire = dequantize(payload, scales)
        if err is None:
            return wire, None
        used = wire + err
        # shard-local truth: the replicated input's own slice (no comms)
        truth = _to_layout(x32, sharding)
        return used, truth - wire

    def all_gather(self, new_shard32, base, sharding, axis: int):
        """Transport the dp-sharded updated value back to the replica layout
        as a quantized delta against ``base`` (the replica's current param).

        Returns ``full32`` on the base's layout with the dp entry dropped.
        No explicit residual: the delta formulation is IMPLICITLY
        error-feedback — the replica accumulates every transmitted wire, so
        whatever Q dropped this step reappears in the next step's delta
        (``m_t − w_{t−1}``) automatically, and the replica tracks the exact
        master within ONE quantization step of the (lr-small) delta.
        Carrying an explicit residual on top would only widen the worst
        case to two steps while doubling the threaded state.
        """
        base32 = base.astype(jnp.float32)
        base_shard = _to_layout(base32, sharding)
        delta = new_shard32 - base_shard
        payload, scales = quantize(delta, axis, self.wire_dtype)
        # the wire: all-gather of the 1-byte payload + the tiny scale vector
        out = _drop_axis_entry(sharding, axis, new_shard32.ndim)
        payload = _to_layout(payload, out)
        scales = _to_layout(scales, _scales_sharding(out, axis, new_shard32.ndim))
        return base32 + dequantize(payload, scales)

    def init_residual(self, shape: tuple, sharding) -> Any:
        """Zero residual on the ZeRO-1 state sharding (1/dp per replica)."""
        if not self.error_feedback:
            return None
        return jax.device_put(jnp.zeros(shape, jnp.float32), sharding)

    # -- wire accounting ------------------------------------------------------
    def wire_bytes(self, shape: tuple, axis: int) -> int:
        """Analytic bytes one direction moves for one tensor: payload at the
        wire width plus the fp32 per-block scale vector."""
        n = int(math.prod(shape))
        return n * jnp.dtype(self.wire_dtype).itemsize + int(shape[axis]) * 4

    # -- comm-hook surface (PowerSGD overrides) -------------------------------
    def init_hook_state(self, named_shapes: dict, key):
        return None

    def apply_hook(self, named_grads: dict, state, rng_key=None):
        return named_grads, state

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name!r})"


class NoneCompression(CompressionPolicy):
    """The default: no compression anywhere; every path byte-identical to
    the pre-compression library."""

    name = "none"


class Int8Compression(CompressionPolicy):
    name = "int8"
    wire_dtype = jnp.int8
    quantizes_collectives = True


class Fp8Compression(CompressionPolicy):
    name = "fp8"
    wire_dtype = jnp.float8_e4m3fn
    quantizes_collectives = True


class PowerSGDCompression(CompressionPolicy):
    """Rank-k + error-feedback gradient compression at the backward sync
    boundary (Vogels et al., arXiv:1905.13727) — the reference's
    ``DDPCommunicationHookType.POWER_SGD`` / ``BATCHED_POWER_SGD``.

    Selected through the same :class:`CompressionPolicy` surface as the
    wire-dtype policies; the (Q, error) hook state is built by
    :meth:`init_hook_state` and applied by :meth:`apply_hook` (the
    Accelerator threads it through the captured step like optimizer state).
    The algorithm lives in this module now; ``utils/powersgd.py`` delegates.
    """

    quantizes_collectives = False

    def __init__(self, rank: int = 1, use_error_feedback: bool = True,
                 warm_start: bool = True, batched: bool = False,
                 wrapper_dtype=None, **kwargs):
        super().__init__(error_feedback=use_error_feedback, **kwargs)
        self.rank = int(rank)
        self.use_error_feedback = bool(use_error_feedback)
        self.warm_start = bool(warm_start)
        self.batched = bool(batched)
        self.wrapper_dtype = wrapper_dtype
        self.name = "batched_powersgd" if batched else "powersgd"
        self.hook_name = self.name

    def init_hook_state(self, named_shapes: dict, key):
        init = init_batched_powersgd_state if self.batched else init_powersgd_state
        return init(named_shapes, self.rank, key)

    def apply_hook(self, named_grads: dict, state, rng_key=None):
        apply = apply_batched_powersgd if self.batched else apply_powersgd
        return apply(
            named_grads,
            state,
            use_error_feedback=self.use_error_feedback,
            warm_start=self.warm_start,
            rng_key=rng_key,
            wrapper_dtype=self.wrapper_dtype,
        )


_POLICY_NAMES = ("none", "int8", "fp8", "powersgd", "batched_powersgd")


def resolve_policy(handler=None, ddp_handler=None) -> CompressionPolicy:
    """Resolve the active policy from a ``CompressionKwargs`` handler (or
    the ``ACCELERATE_COMPRESSION`` env var it reads), with the legacy
    ``DistributedDataParallelKwargs(comm_hook="powersgd")`` spelling folding
    into the SAME :class:`PowerSGDCompression` object — one code path for
    hook selection, eligibility and error-feedback state.
    """
    if handler is None:
        from ..utils.dataclasses import CompressionKwargs

        handler = CompressionKwargs()
    name = str(handler.policy).lower()
    if name not in _POLICY_NAMES:
        raise ValueError(
            f"unsupported compression policy {handler.policy!r}; use one of "
            f"{_POLICY_NAMES}"
        )
    gates = dict(
        min_size=handler.min_size,
        min_block=handler.min_block,
        error_feedback=handler.error_feedback,
    )
    if name in ("powersgd", "batched_powersgd"):
        return PowerSGDCompression(
            rank=handler.powersgd_rank,
            use_error_feedback=handler.error_feedback,
            warm_start=handler.powersgd_warm_start,
            batched=name == "batched_powersgd",
            wrapper_dtype=_wrapper_dtype(handler.powersgd_wrapper),
            min_size=handler.min_size,
            min_block=handler.min_block,
        )
    if name == "none":
        legacy = powersgd_from_ddp(ddp_handler)
        if legacy is not None:
            return legacy
    if name == "int8":
        return Int8Compression(**gates)
    if name == "fp8":
        return Fp8Compression(**gates)
    return NoneCompression(**gates)


def powersgd_from_ddp(ddp_handler) -> Optional["PowerSGDCompression"]:
    """The legacy ``DistributedDataParallelKwargs(comm_hook="powersgd")``
    spelling as a policy object — also what lets the powersgd hook compose
    with an int8/fp8 collective policy when both are configured."""
    if ddp_handler is None:
        return None
    hook = _normalize_hook(getattr(ddp_handler, "comm_hook", None))
    if hook not in ("powersgd", "batched_powersgd"):
        return None
    opts = dict(getattr(ddp_handler, "comm_state_option", None) or {})
    return PowerSGDCompression(
        rank=int(opts.get("matrix_approximation_rank", 1)),
        use_error_feedback=bool(opts.get("use_error_feedback", True)),
        warm_start=bool(opts.get("warm_start", True)),
        batched=hook == "batched_powersgd",
        wrapper_dtype=_wrapper_dtype(
            _normalize_hook(getattr(ddp_handler, "comm_wrapper", None))
        ),
    )


def _normalize_hook(value) -> Optional[str]:
    """Bare value or its enum stringification → canonical lowercase name
    (``DDPCommunicationHookType.POWER_SGD`` → ``powersgd``)."""
    if value is None:
        return None
    hook = str(value).lower().rsplit(".", 1)[-1]
    if hook in ("no", "none"):
        return None
    if hook in ("power_sgd", "batched_power_sgd"):
        hook = hook.replace("_sgd", "sgd")
    return hook


def _wrapper_dtype(wrapper: Optional[str]):
    if wrapper is None:
        return None
    w = str(wrapper).lower()
    if w == "fp16":
        return jnp.float16
    if w == "bf16":
        return jnp.bfloat16
    raise ValueError(f"unsupported powersgd wrapper {wrapper!r}; use 'fp16' or 'bf16'")


# ---------------------------------------------------------------------------
# collective-bytes attribution (telemetry kind="collectives"; bench A/B)
# ---------------------------------------------------------------------------
def collective_bytes(policy: CompressionPolicy, entries: list) -> dict:
    """Analytic per-step dp-axis collective bytes under ``policy``.

    ``entries`` — one ``(shape, axis, param_itemsize[, ag_wire_ok])`` per
    parameter whose ZeRO-1 state actually carries the dp axis (``axis`` is
    that axis; ``None`` marks the replicated fallback, which moves nothing
    over dp; ``ag_wire_ok=False`` marks params whose all-gather stays exact
    — fp32 params keep no master, so the quantized delta has no exact base).
    Two directions per step: the gradient's trip to the sharded update
    (fp32 uncompressed) and the updated param's trip back (param dtype
    uncompressed).  Joined with the backend's ``cost_analysis`` collective
    keys by telemetry when the compiler reports them
    (``telemetry/resources.py``); this analytic figure exists so the A/B is
    measurable on every backend, CPU mesh included.
    """
    rs = ag = rs_raw = ag_raw = 0
    compressed = 0
    for entry in entries:
        shape, axis, itemsize = entry[0], entry[1], entry[2]
        ag_wire_ok = entry[3] if len(entry) > 3 else True
        if axis is None:
            continue  # replicated fallback: no dp traffic for this tensor
        n = int(math.prod(shape))
        raw_rs = n * 4  # fp32 gradient
        raw_ag = n * int(itemsize)  # param dtype
        rs_raw += raw_rs
        ag_raw += raw_ag
        if policy.eligible(tuple(shape), jnp.float32, axis):
            rs += policy.wire_bytes(tuple(shape), axis)
            ag += policy.wire_bytes(tuple(shape), axis) if ag_wire_ok else raw_ag
            compressed += 1
        else:
            rs += raw_rs
            ag += raw_ag
    total, total_raw = rs + ag, rs_raw + ag_raw
    return {
        "policy": policy.name,
        "dp_rs_bytes": rs,
        "dp_ag_bytes": ag,
        "dp_collective_bytes": total,
        "dp_collective_bytes_uncompressed": total_raw,
        "compression_ratio": round(total_raw / total, 3) if total else 1.0,
        "tensors_total": len(entries),
        "tensors_compressed": compressed,
    }


# ---------------------------------------------------------------------------
# PowerSGD core (moved verbatim in behavior from utils/powersgd.py, which
# now delegates here — the torch-parity notes live in that module docstring)
# ---------------------------------------------------------------------------
def eligible_matrix_shape(shape, rank: int) -> Optional[tuple[int, int]]:
    """(n, m) matrix view for tensors PowerSGD compresses, else None.

    Mirrors torch's rule: tensors are viewed as ``(shape[0], rest)``; only
    tensors where the rank-k factors are actually smaller than the matrix
    (both dims > rank) are compressed — 1-D tensors (biases, norms) and
    tiny matrices pass through uncompressed.
    """
    if len(shape) < 2:
        return None
    n = int(shape[0])
    m = int(math.prod(shape[1:]))
    if n <= rank or m <= rank:
        return None
    return n, m


def _orthonormalize(p):
    # torch orthogonalizes with modified Gram-Schmidt; reduced QR spans the
    # same subspace (up to column signs, which cancel in P·Qᵀ) and maps to
    # one fused XLA op
    q, _ = jnp.linalg.qr(p)
    return q


def _compress_matrix(m32, q_prev, err, *, use_error_feedback: bool, wrapper_dtype=None):
    """One warm-started subspace iteration on fp32 matrix ``m32``.

    ``wrapper_dtype`` rounds the transported factors (the reference's
    fp16/bf16 comm wrappers): the decompressed gradient AND the error
    residual are computed from the rounded factors, so error feedback also
    carries the rounding error forward.  The warm-start Q stays unrounded
    (state quality is a local concern, not wire traffic)."""
    if use_error_feedback:
        m32 = m32 + err
    p = _orthonormalize(m32 @ q_prev)
    q_new = m32.T @ p
    if wrapper_dtype is not None:
        p_used = p.astype(wrapper_dtype).astype(jnp.float32)
        q_used = q_new.astype(wrapper_dtype).astype(jnp.float32)
    else:
        p_used, q_used = p, q_new
    approx = p_used @ q_used.T
    new_err = m32 - approx if use_error_feedback else err
    return approx, q_new, new_err


def init_powersgd_state(named_shapes: dict, rank: int, key) -> dict:
    """Per-tensor state: warm-start Q (m, k) gaussian + fp32 error buffer.

    ``named_shapes`` maps param name → shape; ineligible tensors get no
    entry (and pass through uncompressed at apply time).  Built eagerly at
    ``prepare()`` so the captured-step state pytree is structurally stable
    from the first call.
    """
    qs, errs = {}, {}
    names = sorted(n for n in named_shapes if eligible_matrix_shape(named_shapes[n], rank))
    keys = jax.random.split(key, max(len(names), 1))
    for sub, name in zip(keys, names):
        n, m = eligible_matrix_shape(named_shapes[name], rank)
        qs[name] = jax.random.normal(sub, (m, rank), jnp.float32)
        errs[name] = jnp.zeros((n, m), jnp.float32)
    return {"q": qs, "err": errs}


def apply_powersgd(
    named_grads: dict,
    state: dict,
    *,
    use_error_feedback: bool = True,
    warm_start: bool = True,
    rng_key=None,
    wrapper_dtype=None,
) -> tuple[dict, dict]:
    """Compress every eligible gradient in place of its full-rank value.

    Returns ``(new_named_grads, new_state)`` — pure function of arrays, so
    it works identically eagerly and inside a captured trace.
    ``wrapper_dtype`` emulates the reference's fp16/bf16 comm wrappers: the
    transported factors P/Q are rounded through that dtype before
    decompression.
    """
    new_grads = dict(named_grads)
    qs, errs = dict(state["q"]), dict(state["err"])
    names = sorted(qs)
    if not warm_start:
        if rng_key is None:
            raise ValueError("warm_start=False needs an rng_key to re-draw Q")
        subkeys = dict(zip(names, jax.random.split(rng_key, max(len(names), 1))))
    for name in names:
        g = named_grads.get(name)
        if g is None:
            continue
        shape, dtype = g.shape, g.dtype
        m32 = g.reshape(shape[0], -1).astype(jnp.float32)
        q_prev = qs[name]
        if not warm_start:
            q_prev = jax.random.normal(subkeys[name], q_prev.shape, jnp.float32)
        approx, q_new, err_new = _compress_matrix(
            m32, q_prev, errs[name],
            use_error_feedback=use_error_feedback, wrapper_dtype=wrapper_dtype,
        )
        new_grads[name] = approx.reshape(shape).astype(dtype)
        qs[name] = q_new
        errs[name] = err_new
    return new_grads, {"q": qs, "err": errs}


def init_batched_powersgd_state(named_shapes: dict, rank: int, key) -> dict:
    """Batched variant: ONE square matrix over the concatenation of every
    gradient (torch batched_powerSGD_hook): flat length padded up to
    side², side = ceil(sqrt(total))."""
    total = sum(int(math.prod(s)) for s in named_shapes.values())
    side = int(math.ceil(math.sqrt(max(total, 1))))
    return {
        "q": jax.random.normal(key, (side, rank), jnp.float32),
        "err": jnp.zeros((side, side), jnp.float32),
    }


def apply_batched_powersgd(
    named_grads: dict,
    state: dict,
    *,
    use_error_feedback: bool = True,
    warm_start: bool = True,
    rng_key=None,
    wrapper_dtype=None,
) -> tuple[dict, dict]:
    """Compress the whole gradient set as one padded square matrix.

    CONTRACT: the caller must pass the SAME name set on every call (the
    accelerator passes every parameter, zero-filling absent grads) — the
    error buffer is a flat layout over the concatenation, so a name set
    that varies between calls would shift the offsets and add one tensor's
    residual into another's gradient region."""
    names = sorted(named_grads)
    flats = [named_grads[n].astype(jnp.float32).ravel() for n in names]
    sizes = [f.shape[0] for f in flats]
    flat = jnp.concatenate(flats) if flats else jnp.zeros((0,), jnp.float32)
    side = state["q"].shape[0]
    pad = side * side - flat.shape[0]
    m32 = jnp.pad(flat, (0, pad)).reshape(side, side)
    q_prev = state["q"]
    if not warm_start:
        if rng_key is None:
            raise ValueError("warm_start=False needs an rng_key to re-draw Q")
        q_prev = jax.random.normal(rng_key, q_prev.shape, jnp.float32)
    approx, q_new, err_new = _compress_matrix(
        m32, q_prev, state["err"],
        use_error_feedback=use_error_feedback, wrapper_dtype=wrapper_dtype,
    )
    out_flat = approx.ravel()[: flat.shape[0]]
    new_grads = dict(named_grads)
    off = 0
    for name, size in zip(names, sizes):
        g = named_grads[name]
        new_grads[name] = out_flat[off : off + size].reshape(g.shape).astype(g.dtype)
        off += size
    return new_grads, {"q": q_new, "err": err_new}
