"""PowerSGD gradient compression: rank-k approximation + error feedback.

Reference surface: ``DDPCommunicationHookType.POWER_SGD`` /
``BATCHED_POWER_SGD`` wiring torch's ``powerSGD_hook`` with a
``PowerSGDState`` (reference utils/dataclasses.py:137-215,
accelerator.py register_comm_hook).  TPU-native redesign of the same
algorithm (Vogels et al., arXiv:1905.13727):

- per sync boundary each eligible gradient, viewed as an (n, m) matrix, is
  replaced by the rank-k product P·Qᵀ where P = orth(M·Q_prev) and
  Q = Mᵀ·P (one warm-started subspace iteration), with the residual
  M − P·Qᵀ carried into the next step's gradient (error feedback — what
  makes low-rank SGD converge);
- under GSPMD the gradients entering the boundary are already dp-reduced
  (XLA inserts the psum inside the backward), so unlike torch there is no
  separate all-reduce to replace: every rank runs the identical
  deterministic recurrence on identical inputs.  What compression buys
  here is the same thing the fp16/bf16 hooks buy — a low-rank (P, Q)
  representation for any cross-slice DCN gradient traffic issued after
  this point, plus the documented convergence semantics of the reference
  hook so training recipes port unchanged;
- state (Q per tensor, error buffer) consists of plain jax arrays, so the
  whole recurrence traces into a captured step and the buffers thread
  through CapturedStep exactly like optimizer state.

torch-parity notes: ``warm_start=False`` re-draws Q from the threaded RNG
every application; ``use_error_feedback=False`` skips the residual;
``start_powerSGD_iter`` is accepted but ignored (a step-count branch would
force a second compiled variant of every captured step — compression is
active from step 0, which only makes the early steps MORE compressed than
torch's vanilla-allreduce warmup, never less correct).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = [
    "eligible_matrix_shape",
    "init_powersgd_state",
    "apply_powersgd",
    "init_batched_powersgd_state",
    "apply_batched_powersgd",
]


def eligible_matrix_shape(shape, rank: int) -> Optional[tuple[int, int]]:
    """(n, m) matrix view for tensors PowerSGD compresses, else None.

    Mirrors torch's rule: tensors are viewed as ``(shape[0], rest)``; only
    tensors where the rank-k factors are actually smaller than the matrix
    (both dims > rank) are compressed — 1-D tensors (biases, norms) and
    tiny matrices pass through uncompressed.
    """
    if len(shape) < 2:
        return None
    n = int(shape[0])
    m = int(math.prod(shape[1:]))
    if n <= rank or m <= rank:
        return None
    return n, m


def _orthonormalize(p):
    # torch orthogonalizes with modified Gram-Schmidt; reduced QR spans the
    # same subspace (up to column signs, which cancel in P·Qᵀ) and maps to
    # one fused XLA op
    q, _ = jnp.linalg.qr(p)
    return q


def _compress_matrix(m32, q_prev, err, *, use_error_feedback: bool, wrapper_dtype=None):
    """One warm-started subspace iteration on fp32 matrix ``m32``.

    ``wrapper_dtype`` rounds the transported factors (the reference's
    fp16/bf16 comm wrappers): the decompressed gradient AND the error
    residual are computed from the rounded factors, so error feedback also
    carries the rounding error forward.  The warm-start Q stays unrounded
    (state quality is a local concern, not wire traffic)."""
    if use_error_feedback:
        m32 = m32 + err
    p = _orthonormalize(m32 @ q_prev)
    q_new = m32.T @ p
    if wrapper_dtype is not None:
        p_used = p.astype(wrapper_dtype).astype(jnp.float32)
        q_used = q_new.astype(wrapper_dtype).astype(jnp.float32)
    else:
        p_used, q_used = p, q_new
    approx = p_used @ q_used.T
    new_err = m32 - approx if use_error_feedback else err
    return approx, q_new, new_err


def init_powersgd_state(named_shapes: dict, rank: int, key) -> dict:
    """Per-tensor state: warm-start Q (m, k) gaussian + fp32 error buffer.

    ``named_shapes`` maps param name → shape; ineligible tensors get no
    entry (and pass through uncompressed at apply time).  Built eagerly at
    ``prepare()`` so the captured-step state pytree is structurally stable
    from the first call.
    """
    qs, errs = {}, {}
    names = sorted(n for n in named_shapes if eligible_matrix_shape(named_shapes[n], rank))
    keys = jax.random.split(key, max(len(names), 1))
    for sub, name in zip(keys, names):
        n, m = eligible_matrix_shape(named_shapes[name], rank)
        qs[name] = jax.random.normal(sub, (m, rank), jnp.float32)
        errs[name] = jnp.zeros((n, m), jnp.float32)
    return {"q": qs, "err": errs}


def apply_powersgd(
    named_grads: dict,
    state: dict,
    *,
    use_error_feedback: bool = True,
    warm_start: bool = True,
    rng_key=None,
    wrapper_dtype=None,
) -> tuple[dict, dict]:
    """Compress every eligible gradient in place of its full-rank value.

    Returns ``(new_named_grads, new_state)`` — pure function of arrays, so
    it works identically eagerly and inside a captured trace.
    ``wrapper_dtype`` emulates the reference's fp16/bf16 comm wrappers: the
    transported factors P/Q are rounded through that dtype before
    decompression.
    """
    new_grads = dict(named_grads)
    qs, errs = dict(state["q"]), dict(state["err"])
    names = sorted(qs)
    if not warm_start:
        if rng_key is None:
            raise ValueError("warm_start=False needs an rng_key to re-draw Q")
        subkeys = dict(zip(names, jax.random.split(rng_key, max(len(names), 1))))
    for name in names:
        g = named_grads.get(name)
        if g is None:
            continue
        shape, dtype = g.shape, g.dtype
        m32 = g.reshape(shape[0], -1).astype(jnp.float32)
        q_prev = qs[name]
        if not warm_start:
            q_prev = jax.random.normal(subkeys[name], q_prev.shape, jnp.float32)
        approx, q_new, err_new = _compress_matrix(
            m32, q_prev, errs[name],
            use_error_feedback=use_error_feedback, wrapper_dtype=wrapper_dtype,
        )
        new_grads[name] = approx.reshape(shape).astype(dtype)
        qs[name] = q_new
        errs[name] = err_new
    return new_grads, {"q": qs, "err": errs}


def init_batched_powersgd_state(named_shapes: dict, rank: int, key) -> dict:
    """Batched variant: ONE square matrix over the concatenation of every
    gradient (torch batched_powerSGD_hook): flat length padded up to
    side², side = ceil(sqrt(total))."""
    total = sum(int(math.prod(s)) for s in named_shapes.values())
    side = int(math.ceil(math.sqrt(max(total, 1))))
    return {
        "q": jax.random.normal(key, (side, rank), jnp.float32),
        "err": jnp.zeros((side, side), jnp.float32),
    }


def apply_batched_powersgd(
    named_grads: dict,
    state: dict,
    *,
    use_error_feedback: bool = True,
    warm_start: bool = True,
    rng_key=None,
    wrapper_dtype=None,
) -> tuple[dict, dict]:
    """Compress the whole gradient set as one padded square matrix.

    CONTRACT: the caller must pass the SAME name set on every call (the
    accelerator passes every parameter, zero-filling absent grads) — the
    error buffer is a flat layout over the concatenation, so a name set
    that varies between calls would shift the offsets and add one tensor's
    residual into another's gradient region."""
    names = sorted(named_grads)
    flats = [named_grads[n].astype(jnp.float32).ravel() for n in names]
    sizes = [f.shape[0] for f in flats]
    flat = jnp.concatenate(flats) if flats else jnp.zeros((0,), jnp.float32)
    side = state["q"].shape[0]
    pad = side * side - flat.shape[0]
    m32 = jnp.pad(flat, (0, pad)).reshape(side, side)
    q_prev = state["q"]
    if not warm_start:
        if rng_key is None:
            raise ValueError("warm_start=False needs an rng_key to re-draw Q")
        q_prev = jax.random.normal(rng_key, q_prev.shape, jnp.float32)
    approx, q_new, err_new = _compress_matrix(
        m32, q_prev, state["err"],
        use_error_feedback=use_error_feedback, wrapper_dtype=wrapper_dtype,
    )
    out_flat = approx.ravel()[: flat.shape[0]]
    new_grads = dict(named_grads)
    off = 0
    for name, size in zip(names, sizes):
        g = named_grads[name]
        new_grads[name] = out_flat[off : off + size].reshape(g.shape).astype(g.dtype)
        off += size
    return new_grads, {"q": q_new, "err": err_new}
