"""Step capture: trace the imperative loop body into one jitted XLA program.

This is the resolution of SURVEY.md §7 hard-part #2 ("eager-shaped API over
lazy compiled execution"): the user's Python step — forward through tape
Modules, ``accelerator.backward``, ``optimizer.step()`` — executes inside a
``jax.jit`` trace exactly once per (shapes, sync_gradients, training-mode)
variant.  The tape's per-op ``jax.vjp`` closures compose into the backward
graph; optimizer math and GSPMD collectives land in the same program; state
(params, grads, optax state, fp32 masters, RNG key) is threaded through as
donated arguments so replays are a single device launch with zero host work
beyond argument assembly.

Scheduler steps are recorded at trace time and replayed python-side after
every call: their LR lands in ``opt_state.hyperparams`` which is *data* to the
compiled program, so LR schedules work across replays without recompiles.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from .nn import random as nn_random
from .nn.tape import Tensor


class _CaptureState(threading.local):
    def __init__(self):
        self.active: Optional["CaptureContext"] = None


_capture_state = _CaptureState()


def current_capture() -> Optional["CaptureContext"]:
    return _capture_state.active


class CaptureContext:
    """Book-keeping for one trace: deferred scheduler steps, accumulate use."""

    def __init__(self, owner_advances_accumulate: bool = False):
        self.deferred_scheduler_steps: list[tuple[Any, tuple, dict]] = []
        # `with accelerator.accumulate(model):` inside the captured body —
        # legal: the owning CapturedStep advances the schedule host-side once
        # per replay, so the trace-time flag is already the replay-time flag
        self.used_accumulate = False
        self.owner_advances_accumulate = owner_advances_accumulate
        self._schedule_advanced = False  # sticky: a re-trace must not re-advance
        self._accumulate_calls_in_trace = 0

    def defer_scheduler(self, scheduler, args, kwargs) -> None:
        self.deferred_scheduler_steps.append((scheduler, args, kwargs))

    def begin_trace(self) -> None:
        """Reset per-trace bookkeeping (a re-trace must not double-count)."""
        self.deferred_scheduler_steps.clear()
        self._accumulate_calls_in_trace = 0

    def on_accumulate(self, accelerator) -> None:
        """Called by ``accelerator.accumulate()`` at trace time.

        Only the very first trace of a CapturedStep advances the schedule
        here (the step's variant wasn't known yet when ``__call__`` computed
        its cache key); afterwards the CapturedStep owns the advance and
        trace-time accumulate() is purely a marker."""
        self._accumulate_calls_in_trace += 1
        if self._accumulate_calls_in_trace > 1:
            # eager would advance the schedule once per block; a compiled
            # program advances once per CALL and bakes a single
            # sync_gradients value into the trace — silently different math
            raise RuntimeError(
                "compile_step body enters accelerator.accumulate() more than "
                "once; the captured program can only advance the "
                "accumulation schedule once per call. Process one "
                "micro-batch per captured call (loop outside), or capture a "
                "step without accumulate() and drive no_sync() manually."
            )
        self.used_accumulate = True
        if not self.owner_advances_accumulate and not self._schedule_advanced:
            accelerator._do_sync()
            self._schedule_advanced = True


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda x: x.data if isinstance(x, Tensor) else x,
        tree,
        is_leaf=lambda x: isinstance(x, Tensor),
    )


def _is_offloaded(x) -> bool:
    """True when the array lives outside TPU device memory (host-offloaded
    optimizer state / params) — the predicate behind the donation split
    below.  On the CPU backend every array reports ``unpinned_host``, so CPU
    runs donate nothing; that matches historical behavior and keeps eager
    references valid in the virtual-mesh test suite."""
    s = getattr(x, "sharding", None)
    return getattr(s, "memory_kind", None) not in (None, "device")


_DEFAULT_MEMORY_KIND: Optional[str] = None


def _default_memory_kind() -> str:
    global _DEFAULT_MEMORY_KIND
    if _DEFAULT_MEMORY_KIND is None:
        try:
            _DEFAULT_MEMORY_KIND = jax.devices()[0].default_memory().kind
        except Exception:
            _DEFAULT_MEMORY_KIND = "device"
    return _DEFAULT_MEMORY_KIND


def _nondefault_memory(x) -> bool:
    """True only for genuinely offloaded leaves (pinned_host on TPU *or*
    CPU).  Unlike ``_is_offloaded`` this compares against the backend's
    default memory kind — the CPU backend's default is ``unpinned_host``,
    and treating that as "offloaded" would disable the layout pin exactly
    where the virtual-mesh tests need it (a ZeRO-1 state-sharded program
    would then drift its unpinned grad outputs to the dp layout and
    silently re-trace on call 2)."""
    s = getattr(x, "sharding", None)
    kind = getattr(s, "memory_kind", None)
    return kind is not None and kind not in ("device", _default_memory_kind())


def _zeros_like_on_device(x):
    """zeros_like, but always in device memory: a placeholder grad for a
    host-OFFLOADED param must not inherit pinned_host (the backward
    accumulates real device grads into it — XLA refuses mixed spaces)."""
    if isinstance(x, jax.Array) and _is_offloaded(x):
        s = x.sharding
        return jax.device_put(
            jnp.zeros(x.shape, x.dtype), jax.sharding.NamedSharding(s.mesh, s.spec)
        )
    return jnp.zeros_like(x)


class CapturedStep:
    """Callable produced by ``accelerator.compile_step``."""

    def __init__(self, accelerator, fn: Callable):
        self.accelerator = accelerator
        self.fn = fn
        self._cache: dict = {}
        # host-side argument-assembly accounting (collect/flatten/key/split
        # before each dispatch): replay calls only — trace/compile calls are
        # excluded so bench.py can report steady-state host overhead per step
        self.host_assembly_ms_total = 0.0
        self.host_assembly_calls = 0
        # None until the first trace reveals whether the body contains
        # `with accelerator.accumulate(...):`; True → __call__ advances the
        # accumulation schedule host-side before each replay
        self._uses_accumulate: Optional[bool] = None

    # -- state threading -----------------------------------------------------
    def _collect_state(self) -> dict:
        acc = self.accelerator
        models = acc._models
        optimizers = acc._optimizers
        state = {
            "params": [m.param_pytree() for m in models],
            "buffers": [m.buffer_pytree() for m in models],
            "grads": [
                {
                    name: (p.grad if p.grad is not None else _zeros_like_on_device(p.data))
                    for name, p in m.named_parameters()
                }
                for m in models
            ],
            "opt": [o.optimizer.capture_state() for o in optimizers],
            "rng": nn_random.next_key(),
            "scaler": acc.scaler.capture_state() if acc.scaler is not None else None,
            # PowerSGD comm-hook (Q, error) buffers — persistent across steps
            "comm": acc._comm_hook_capture_state(),
        }
        return state

    def _bind_state(self, state: dict) -> None:
        acc = self.accelerator
        for m, params, buffers, grads in zip(
            acc._models, state["params"], state["buffers"], state["grads"]
        ):
            m.bind_params(params)
            m.bind_buffers(buffers)
            named = dict(m.named_parameters())
            for name, g in grads.items():
                named[name].grad = g
        for o, s in zip(acc._optimizers, state["opt"]):
            o.optimizer.bind_capture_state(s)
        if state.get("scaler") is not None and acc.scaler is not None:
            acc.scaler.bind_capture_state(state["scaler"])
        acc._bind_comm_hook_state(state.get("comm"))

    def _snapshot_state(self) -> dict:
        acc = self.accelerator
        return {
            "params": [m.param_pytree() for m in acc._models],
            "buffers": [m.buffer_pytree() for m in acc._models],
            "grads": [
                {
                    name: (p.grad if p.grad is not None else _zeros_like_on_device(p.data))
                    for name, p in m.named_parameters()
                }
                for m in acc._models
            ],
            "opt": [o.optimizer.capture_state() for o in acc._optimizers],
            "scaler": acc.scaler.capture_state() if acc.scaler is not None else None,
            "comm": acc._comm_hook_capture_state(),
        }

    # -- call ----------------------------------------------------------------
    def __call__(self, *args):
        t_call = _time.perf_counter()
        acc = self.accelerator
        if self._uses_accumulate:
            # body contains `with accelerator.accumulate(...)`: advance the
            # micro-step schedule here, host-side, so the sync_gradients flag
            # in the cache key below already selects the right compiled
            # variant (trace-time accumulate() is then a no-op marker)
            acc._do_sync()
        args = _unwrap_tree(args)
        flat_args, args_treedef = jax.tree_util.tree_flatten(args)
        import numpy as _np

        key = (
            args_treedef,
            tuple(
                (tuple(_np.shape(a)), str(getattr(a, "dtype", _np.result_type(a))))
                for a in flat_args
            ),
            acc.gradient_state.sync_gradients,
            tuple(m.training for m in acc._models),
        )
        entry = self._cache.get(key)
        state = self._collect_state()
        flat_state, cur_treedef = jax.tree_util.tree_flatten(state)
        if entry is not None and cur_treedef != entry[2]:
            # state structure changed since this entry was built (e.g. more
            # objects prepared): rebuild, exactly where plain jit would
            # silently re-trace
            entry = None
        built = entry is None
        if built:
            entry = self._build(key, state, args)
        jitted, ctx, _, host_mask = entry
        dev_leaves = tuple(x for x, h in zip(flat_state, host_mask) if not h)
        host_leaves = tuple(x for x, h in zip(flat_state, host_mask) if h)
        if not built:
            self.host_assembly_ms_total += (_time.perf_counter() - t_call) * 1e3
            self.host_assembly_calls += 1
        new_state, out = jitted(dev_leaves, host_leaves, *flat_args)
        self._writeback(new_state)
        if self._uses_accumulate is None:
            # first ever call: the trace just revealed whether the body
            # accumulates.  If it advanced the schedule mid-trace, the key
            # computed above used the stale flag — re-file the entry under
            # the flag the program was actually traced with.
            self._uses_accumulate = ctx.used_accumulate
            if ctx.used_accumulate:
                ctx.owner_advances_accumulate = True
                new_key = (key[0], key[1], acc.gradient_state.sync_gradients, key[3])
                if new_key != key:
                    self._cache[new_key] = entry
                    self._cache.pop(key, None)
        elif ctx.used_accumulate != self._uses_accumulate:
            # a later variant disagrees with the first trace (e.g. the body
            # enters `accumulate()` only when model.training) — the schedule
            # advance would silently skip or double-count; fail loudly
            raise RuntimeError(
                "compile_step body uses accelerator.accumulate() in some "
                "trace variants but not others (e.g. behind a training-mode "
                "or warmup branch); the accumulation schedule cannot track "
                "such a step. Call accumulate() unconditionally inside the "
                "body, or move it outside the captured call."
            )
        # deferred scheduler steps run for real, python-side, every replay
        for scheduler, s_args, s_kwargs in ctx.deferred_scheduler_steps:
            scheduler.step(*s_args, _from_capture_replay=True, **s_kwargs)
        return out

    def _build(self, key, state_template, args_template):
        acc = self.accelerator
        _, args_treedef = jax.tree_util.tree_flatten(args_template)
        captured_ctx = CaptureContext(
            owner_advances_accumulate=bool(self._uses_accumulate)
        )

        # Pin the carried state's layout to the layout it arrives with.
        # jax.jit caches on input *shardings* as well as shapes: left alone,
        # GSPMD picks arbitrary output layouts for the first step's new state
        # (e.g. a transposed spec for a weight grad), those feed back in as
        # call 2's inputs, and the whole program re-traces and re-compiles —
        # a second multi-minute XLA compile for byte-identical computation.
        # Constraining every output leaf to its input sharding makes the state
        # layout a fixed point from the first call.
        _NOPIN = object()

        def _leaf_sharding(x):
            s = getattr(x, "sharding", None)
            if not isinstance(s, jax.sharding.NamedSharding):
                return _NOPIN
            if _nondefault_memory(x):
                # host-offloaded leaves: with_sharding_constraint cannot pin
                # a non-default memory space on every backend — their
                # placement is re-established eagerly after each replay
                # (optim.reoffload_state_to_host), so leave them unpinned
                return _NOPIN
            return s

        ref_shardings = {
            k: jax.tree_util.tree_map(_leaf_sharding, state_template[k])
            for k in ("params", "buffers", "grads", "opt", "scaler", "comm")
            if state_template.get(k) is not None
        }

        def _pin_layout(new_state):
            pinned = dict(new_state)
            for k, shardings in ref_shardings.items():
                pinned[k] = jax.tree_util.tree_map(
                    lambda x, s: x if s is _NOPIN else jax.lax.with_sharding_constraint(x, s),
                    new_state[k],
                    shardings,
                )
            return pinned

        # Split the carried state by memory space: donation aliases input
        # buffers to outputs, which is illegal across memory spaces (a
        # pinned_host moment donated to — or passed through a micro-step
        # variant into — a device-resident output trips XLA's memory-kind
        # check at dispatch).  Donation is per-argument, so device leaves
        # (params/grads/masters — the big HBM win) keep aliasing and only
        # host-offloaded leaves ride a second, non-donated argument.
        flat_template, state_treedef = jax.tree_util.tree_flatten(state_template)
        host_mask = tuple(_is_offloaded(x) for x in flat_template)

        def traced(dev_leaves, host_leaves, *flat_args):
            dev_iter, host_iter = iter(dev_leaves), iter(host_leaves)
            flat = [next(host_iter) if h else next(dev_iter) for h in host_mask]
            state = jax.tree_util.tree_unflatten(state_treedef, flat)
            call_args = jax.tree_util.tree_unflatten(args_treedef, flat_args)
            prev_rng_state = nn_random.default_rng.get_state()
            prev_capture = _capture_state.active
            prev_acc_ctx = acc._capture_ctx
            _capture_state.active = captured_ctx
            acc._capture_ctx = captured_ctx
            # re-traces (e.g. after an input-layout change) must not double-
            # count python side effects recorded during a previous trace
            captured_ctx.begin_trace()
            try:
                self._bind_state(state)
                nn_random.default_rng.set_key(state["rng"])
                out = self.fn(*call_args)
                out = _unwrap_tree(out)
                new_state = _pin_layout(self._snapshot_state())
                return new_state, out
            finally:
                _capture_state.active = prev_capture
                acc._capture_ctx = prev_acc_ctx
                nn_random.default_rng.set_state(prev_rng_state)

        jitted = jax.jit(traced, donate_argnums=(0,))
        entry = (jitted, captured_ctx, state_treedef, host_mask)
        self._cache[key] = entry
        return entry

    def _writeback(self, new_state: dict) -> None:
        acc = self.accelerator
        for m, params, buffers, grads in zip(
            acc._models, new_state["params"], new_state["buffers"], new_state["grads"]
        ):
            m.bind_params(params)
            m.bind_buffers(buffers)
            named = dict(m.named_parameters())
            for name, g in grads.items():
                named[name].grad = g
        for o, s in zip(acc._optimizers, new_state["opt"]):
            o.optimizer.bind_capture_state(s)
            # host-offloaded optimizer state (and, with param offload, the
            # params): the compiled program's outputs land in HBM; re-pin to
            # pinned_host so the saving is real and the next call's input
            # placement (and thus the jit cache key) stays fixed.  No-ops
            # unless offload was requested.
            o.optimizer.reoffload_state_to_host()
            o.optimizer.reoffload_params_to_host()
        if new_state.get("scaler") is not None and acc.scaler is not None:
            acc.scaler.bind_capture_state(new_state["scaler"])
        acc._bind_comm_hook_state(new_state.get("comm"))
