"""Block/paged KV cache for the serving engine (docs/serving.md).

The single-request decode engine (models/generation.py) allocates one
contiguous ``(b, n_kv, prompt+max_new, d)`` cache per call — the cache
*shape* encodes the request geometry, so every distinct length compiles a
fresh program and two requests can never share a batch.  Serving inverts
that: the cache is ONE preallocated pool of fixed-size blocks

    ``k_pool, v_pool : (L, num_blocks, n_kv_head, block_size, head_dim)``

plus an int32 **block table** per batch slot mapping logical position
``p`` to pool block ``table[slot, p // block_size]``.  Every shape the
captured programs see (pool, tables, per-slot scalars) is fixed at service
construction, so slots holding a 7-token and a 900-token sequence replay
the SAME pinned program — the zero-recompile contract continuous batching
needs (PAPERS.md #1: serving economics are batch occupancy + recompile
avoidance).

Block 0 is the **trash block**: it is never handed to a request, and empty
slots' table rows point at it, so the decode program's unconditional
scatter (writing every slot's current-token k/v) lands harmlessly for
inactive slots instead of corrupting a neighbour's cache.  Allocation is
host-side and O(blocks) — the pool itself never moves; only tables do.

Blocks for a request are reserved up front at admission
(:func:`blocks_for_request`) and freed the step the request finishes, so a
full pool back-pressures admission (requests wait in the queue) rather than
failing mid-decode.  With a multi-token decode block (``decode_steps=n``,
docs/serving.md §device-resident decode) the reservation additionally
covers the ≤ ``n-1`` micro-step OVERRUN past a request's budget/eos — the
device cannot know a sequence finished until the host reads the token
block, so the discarded trailing micro-steps still scatter k/v, and those
writes must land inside the slot's own reservation, never a neighbour's.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


def bucket_length(n: int, multiple: int, cap: Optional[int] = None) -> int:
    """Round ``n`` up to a multiple of ``multiple`` (optionally clamped to
    ``cap``, never below ``n``) — the shape-bucketing helper every captured
    serving/decode entry must sit behind (graftlint's recompile-hazard rule
    checks the contract): feeding raw request-length shapes into a pinned
    program compiles one variant per distinct length.  Delegates to the one
    rounding implementation (``models.generation.bucket_up``) so serving
    and one-shot ``generate()`` can never bucket differently."""
    if n < 1:
        raise ValueError(f"bucket_length({n}, {multiple}): n must be >= 1")
    from ..models.generation import bucket_up

    return bucket_up(n, multiple, cap)


def blocks_for_request(prompt_len: int, max_new: int, bucket_len: int,
                       block_size: int, decode_steps: int = 1,
                       blocks_per_slot: Optional[int] = None) -> int:
    """Up-front block reservation for one request — the ONE place the
    admission math lives (submit validation and the pool gate both read it).

    The decode span is rounded up to whole ``decode_steps`` blocks: an
    n-token captured decode executes up to ``n-1`` micro-steps past the
    request's budget/eos before the host sees the token block, and every
    overrun micro-step scatters one (discarded) k/v row at the next
    position.  Covering the bucketed horizon keeps those writes inside the
    slot's own reservation — at most one extra block per request.
    ``decode_steps=1`` reduces to the classic
    ``ceil(max(bucket_len, prompt_len + max_new) / block_size)`` exactly.

    ``blocks_per_slot`` clamps the result to the slot's table length: a
    near-capacity request's overrun horizon may round past the table, and
    those tail writes are already safe without blocks behind them (table
    entries past the row are the trash block; a position past the whole
    table clamps into the slot's own last block — both masked stale data
    for any future owner)."""
    # prefill emits token 1; the decode loop emits the remaining max_new-1
    # in ceil((max_new-1)/n) blocks of n micro-steps
    steps = max(1, decode_steps)
    horizon = 1 + -(-(max_new - 1) // steps) * steps
    needed = -(-max(bucket_len, prompt_len + horizon) // block_size)
    if blocks_per_slot is not None:
        needed = min(needed, blocks_per_slot)
    return needed


@dataclasses.dataclass
class BlockPool:
    """Host-side allocator over the device block pool.

    ``num_blocks`` INCLUDES the reserved trash block 0; requests draw from
    ids ``1..num_blocks-1``.  Per-slot allocations keep logical order —
    ``rows[slot][j]`` covers logical positions ``[j*bs, (j+1)*bs)`` — so a
    gathered table row reads back as a contiguous (virtually addressed)
    cache and the causal mask stays the plain ``t <= q_pos`` formula.
    """

    num_blocks: int
    block_size: int
    max_slots: int
    blocks_per_slot: int

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError("BlockPool needs >= 2 blocks (block 0 is trash)")
        self._free: list[int] = list(range(self.num_blocks - 1, 0, -1))
        self._rows: dict[int, list[int]] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1

    def can_alloc(self, n_blocks: int) -> bool:
        return n_blocks <= len(self._free)

    def alloc(self, slot: int, n_blocks: int) -> list[int]:
        """Reserve ``n_blocks`` for ``slot``; the returned ids are in logical
        order.  Raises when the pool is short — the scheduler must gate
        admission on :meth:`can_alloc` (back-pressure, not failure)."""
        if slot in self._rows:
            raise ValueError(f"slot {slot} already holds an allocation")
        if n_blocks > self.blocks_per_slot:
            raise ValueError(
                f"request needs {n_blocks} blocks > blocks_per_slot "
                f"({self.blocks_per_slot}) — raise max_request_len or block_size"
            )
        if not self.can_alloc(n_blocks):
            raise ValueError(
                f"pool exhausted: need {n_blocks}, free {len(self._free)}"
            )
        row = [self._free.pop() for _ in range(n_blocks)]
        self._rows[slot] = row
        return row

    def free_slot(self, slot: int) -> int:
        """Return ``slot``'s blocks to the free list (eviction/completion);
        returns how many were freed.  Freed ids are immediately reusable —
        stale pool contents are masked by the causal ``t <= q_pos`` until
        the new owner overwrites them."""
        row = self._rows.pop(slot, None)
        if row is None:
            return 0
        self._free.extend(reversed(row))
        return len(row)

    def row(self, slot: int) -> list[int]:
        return list(self._rows.get(slot, ()))

    def check_no_leaks(self) -> None:
        """Invariant: every non-trash block is exactly once free or owned."""
        owned = [b for row in self._rows.values() for b in row]
        seen = set(owned) | set(self._free)
        if len(owned) + len(self._free) != self.usable_blocks or len(seen) != self.usable_blocks or 0 in seen:
            raise AssertionError(
                f"block accounting broken: {len(owned)} owned + "
                f"{len(self._free)} free != {self.usable_blocks} usable"
            )


def make_pools(n_layers: int, num_blocks: int, n_kv_head: int,
               block_size: int, head_dim: int, dtype):
    """Zero-initialised device pools ``(L, NB, n_kv, bs, d)`` — zeros (not
    empty) so never-written trash/stale positions stay finite: masked
    attention multiplies their probs by exactly 0.0, and 0 * finite is 0
    while 0 * inf would poison the row with NaN."""
    import jax.numpy as jnp

    shape = (n_layers, num_blocks, n_kv_head, block_size, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)
