"""Pillar 3 — resource accounting: live HBM bytes and per-program costs.

Two sources, both best-effort (every backend exposes a different subset —
missing analyses degrade to absent keys, never to an exception on the hot
path):

* ``live_bytes_by_device()`` walks ``jax.live_arrays()`` and sums per-shard
  ``nbytes`` by device — the "what is resident *right now*" view, sampled at
  capture time and on demand (``Telemetry.sample_resources``).
* ``program_stats(compiled)`` reads the compiled executable's
  ``memory_analysis()`` (argument/output/temp/alias bytes — the *static*
  footprint XLA reserved for one launch) and ``cost_analysis()`` (FLOPs,
  bytes accessed, and any collective bytes the backend reports) — the
  EQuARX-style comms/FLOP denominator per captured program.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


def live_bytes_by_device() -> dict[str, int]:
    """Bytes of live jax.Arrays per addressable device (host view)."""
    import jax

    per_device: dict[str, int] = {}
    try:
        arrays = jax.live_arrays()
    except Exception:
        return per_device
    for x in arrays:
        try:
            for shard in x.addressable_shards:
                data = shard.data
                if data is None:
                    continue
                dev = str(shard.device)
                per_device[dev] = per_device.get(dev, 0) + int(data.nbytes)
        except Exception:
            continue
    return per_device


def _memory_analysis_dict(compiled) -> dict:
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    out = {}
    for name in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        value = getattr(mem, name, None)
        if isinstance(value, (int, float)):
            out[name.replace("_in_bytes", "_bytes")] = int(value)
    return out


def _cost_analysis_dict(compiled) -> dict:
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if cost is None:
        return {}
    # jax returns either a per-device list of dicts or a single dict
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out = {}
    for key, value in cost.items():
        if not isinstance(value, (int, float)):
            continue
        if key == "flops":
            out["flops"] = float(value)
        elif key in ("bytes accessed", "bytes_accessed"):
            out["bytes_accessed"] = float(value)
        elif "utilization" in key:
            continue  # per-operand noise; the totals above are the signal
        elif any(tag in key.lower() for tag in ("collective", "all-reduce", "rendezvous", "bytes accessed output")):
            out[key.replace(" ", "_")] = float(value)
    return out


def program_stats(compiled) -> dict:
    """memory_analysis + cost_analysis of one compiled executable."""
    stats = {}
    stats.update(_memory_analysis_dict(compiled))
    stats.update(_cost_analysis_dict(compiled))
    return stats


@dataclass
class ProgramRecord:
    key: str  # cache-key id of the captured variant
    label: str  # e.g. "capture:0"
    stats: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"kind": "program", "key": self.key, "label": self.label, **self.stats}


@dataclass
class CollectiveRecord:
    """dp-axis collective-bytes attribution under a compression policy
    (``parallel.compress.collective_bytes``): the analytic per-step wire
    bytes of the ZeRO-1 reduce-scatter/all-gather pair, recorded once per
    ``prepare()``.  Complements ``cost_analysis`` — the backend reports
    collective bytes only on some platforms (the keys ``program_stats``
    scrapes), while this figure exists on every backend, CPU mesh included,
    so bench.py can A/B ``none`` vs ``int8`` vs ``fp8`` anywhere."""

    policy: str
    stats: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"kind": "collectives", "policy": self.policy, **self.stats}


@dataclass
class KernelRecord:
    """One armed Pallas hot-path kernel (docs/kernels.md), recorded at
    ``prepare()`` like :class:`CollectiveRecord`: which reference path the
    kernel replaces and how it lowers (compiled Mosaic vs interpreter) —
    the join key for bench.py's kernel A/B and the per-phase device-time
    split."""

    kernel: str
    stats: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"kind": "kernel", "kernel": self.kernel, **self.stats}


@dataclass
class ResourceSample:
    tag: str
    time: float = field(default_factory=time.time)
    devices: dict = field(default_factory=dict)

    @property
    def total_bytes(self) -> int:
        return int(sum(self.devices.values()))

    def to_dict(self) -> dict:
        return {
            "kind": "resources",
            "tag": self.tag,
            "time": self.time,
            "devices": dict(self.devices),
            "total_bytes": self.total_bytes,
        }


def sample_live(tag: str) -> ResourceSample:
    return ResourceSample(tag=tag, devices=live_bytes_by_device())
