"""int8/int4 weight-quantized KV-cache decode for the fused decoder families
(reference counterpart: the bnb int8 big-model-inference benchmark,
/root/reference/benchmarks/big_model_inference). Weights stream through the
decode scan at 1 (or 0.5) byte/param and widen per layer inside the step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
from accelerate_tpu.models import (
    GPTConfig,
    GPTJConfig,
    GPTJForCausalLM,
    GPTLMHeadModel,
    GPTNeoXConfig,
    GPTNeoXForCausalLM,
    LlamaConfig,
    LlamaForCausalLM,
    OPTConfig,
    OPTForCausalLM,
)

_FAMILIES = {
    "llama": lambda: LlamaForCausalLM(LlamaConfig.tiny()),
    "opt": lambda: OPTForCausalLM(OPTConfig.tiny()),
    "gpt": lambda: GPTLMHeadModel(GPTConfig.tiny()),
    "gptj": lambda: GPTJForCausalLM(GPTJConfig.tiny()),
    "neox": lambda: GPTNeoXForCausalLM(GPTNeoXConfig.tiny()),
}


def _snap_params_to_int8_grid(model):
    """Round every 2-D matmul weight onto its own int8 quantization grid so
    quantize→dequantize is EXACT — quantized decode must then match the
    full-precision decode token for token."""
    for name, p in model.named_parameters():
        w = np.asarray(p.data)
        if w.ndim != 2:
            continue
        amax = np.maximum(np.abs(w).max(axis=-1, keepdims=True), 1e-12)
        scale = (amax / 127.0).astype(np.float32)
        p.data = jnp.asarray(np.round(w / scale) * scale)


@pytest.mark.parametrize("family", sorted(_FAMILIES))
def test_int8_decode_exact_on_grid(family):
    """EVERY fused decoder family decodes exactly under int8 when weights
    sit on the quantization grid (the engine is family-generic via
    DecoderSpec; round-3 session note wrongly assumed otherwise)."""
    nn.manual_seed(0)
    model = _FAMILIES[family]()
    vocab = model.config.vocab_size
    _snap_params_to_int8_grid(model)
    ids = np.random.default_rng(0).integers(0, vocab, (2, 9)).astype(np.int32)
    full = np.asarray(model.generate(ids, max_new_tokens=6))
    quant = np.asarray(model.generate(ids, max_new_tokens=6, quantize_weights=8))
    np.testing.assert_array_equal(quant, full)


def test_int8_decode_caches_int8_stacks():
    """The cached stacked layers really are int8 + fp32 scales (the memory
    win), and the cache keys on the bits so modes don't cross-serve."""
    nn.manual_seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    ids = np.zeros((1, 4), np.int32)
    model.generate(ids, max_new_tokens=2, quantize_weights=8)
    _, by_mode = model._generation_param_cache
    g, (plain, qd, sd) = by_mode[8]
    assert qd and all(v.dtype == jnp.int8 for v in qd.values())
    assert all(v.dtype == jnp.float32 for v in sd.values())
    assert all(v.ndim != 3 for v in plain.values())  # matmul stacks all quantized
    # both modes stay cached side by side (A/B runs must not restack)
    model.generate(ids, max_new_tokens=2)
    assert set(model._generation_param_cache[1]) == {0, 8}


def test_int4_decode_runs_and_packs():
    nn.manual_seed(0)
    model = OPTForCausalLM(OPTConfig.tiny())
    ids = np.random.default_rng(1).integers(0, model.config.vocab_size, (1, 8)).astype(np.int32)
    out = np.asarray(model.generate(ids, max_new_tokens=4, quantize_weights=4))
    assert out.shape == (1, 12)
    _, by_mode = model._generation_param_cache
    g, (plain, qd, sd) = by_mode[4]
    assert qd and all(v.dtype == jnp.uint8 for v in qd.values())
    # packed: stored inner dim is half the logical one
    hidden = model.config.hidden_size
    assert any(v.shape[-1] == hidden // 2 for v in qd.values())
    assert (out[:, :8] == ids).all()


def test_invalid_bits_raises():
    nn.manual_seed(0)
    model = OPTForCausalLM(OPTConfig.tiny())
    with pytest.raises(ValueError, match="quantize_weights"):
        model.generate(np.zeros((1, 4), np.int32), max_new_tokens=2, quantize_weights=2)
