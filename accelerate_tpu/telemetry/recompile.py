"""Pillar 2 — recompile forensics.

``CapturedStep`` keys its compiled variants on
``(args_treedef, per-leaf (shape, dtype), sync_gradients, training_modes)``
and silently builds a new program whenever a component moves.  bench.py could
previously only report *that* a recompile happened; this module says *what
changed*: each new cache key is diffed against the previously used one and the
differences become human-readable cause strings on a structured
:class:`RecompileEvent`.

State-structure invalidations (the carried pytree grew/shrank, or the
donation split between device and host-offloaded leaves moved) don't change
the cache key at all — the capture path detects them separately and passes a
pre-built cause string in, so they surface through the same event stream.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Optional


def key_id(key) -> str:
    """Short stable id for a CapturedStep cache key (``repr`` is stable for
    the tuple-of-hashables keys the capture path builds)."""
    return "k" + hashlib.sha1(repr(key).encode("utf-8")).hexdigest()[:10]


def _clip(text, limit: int = 200) -> str:
    text = str(text)
    return text if len(text) <= limit else text[: limit - 1] + "…"


def diff_keys(prev, new) -> list[str]:
    """Name every component that moved between two cache keys."""
    causes: list[str] = []
    p_tree, p_shapes, p_sync, p_train = prev
    n_tree, n_shapes, n_sync, n_train = new
    if p_tree != n_tree:
        # treedef reprs of nested batches run to kilobytes, and cause
        # strings flow verbatim into every tracker backend — cap them like
        # the layout path caps exception text
        causes.append(
            f"argument pytree structure changed: {_clip(p_tree)} -> {_clip(n_tree)}"
        )
    if p_shapes != n_shapes:
        if len(p_shapes) != len(n_shapes):
            causes.append(
                f"argument count changed: {len(p_shapes)} -> {len(n_shapes)} leaves"
            )
        else:
            for i, ((ps, pd), (ns, nd)) in enumerate(zip(p_shapes, n_shapes)):
                if ps != ns:
                    causes.append(
                        f"arg[{i}] shape changed: {tuple(ps)} -> {tuple(ns)}"
                    )
                if pd != nd:
                    causes.append(f"arg[{i}] dtype changed: {pd} -> {nd}")
    if p_sync != n_sync:
        causes.append(
            f"sync_gradients flipped {p_sync} -> {n_sync} "
            "(gradient-accumulation boundary variant)"
        )
    if p_train != n_train:
        for i, (pt, nt) in enumerate(zip(p_train, n_train)):
            if pt != nt:
                causes.append(
                    f"model[{i}].training changed {pt} -> {nt} (train/eval switch)"
                )
        if len(p_train) != len(n_train):
            causes.append(
                f"model count changed: {len(p_train)} -> {len(n_train)}"
            )
    return causes


@dataclass
class RecompileEvent:
    step: int  # global captured-call index at which the rebuild happened
    key: str  # key_id of the newly built variant
    prev_key: Optional[str]  # key_id of the variant used just before
    causes: list[str] = field(default_factory=list)
    # "key" (cache-key component moved), "state" (carried pytree structure /
    # donation split changed), "layout" (AOT executable rejected drifted
    # input shardings — the case plain jit re-traces silently)
    kind: str = "key"

    @property
    def cause(self) -> str:
        return self.causes[0] if self.causes else "unknown"

    def to_dict(self) -> dict:
        return {
            "kind": "recompile",
            "step": self.step,
            "key": self.key,
            "prev_key": self.prev_key,
            "cause": self.cause,
            "causes": list(self.causes),
            "recompile_kind": self.kind,
        }
