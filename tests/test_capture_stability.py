"""The captured step must compile exactly once per shape variant.

Round-2 regression guards: GSPMD canonicalizes output shardings (size-1
mesh axes dropped), so non-canonical input specs or uncommitted optimizer
scalars made call 2 arrive with "new" input shardings and silently
re-trace+re-compile the entire train step — a second multi-minute XLA
compile on real hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel


def test_captured_step_traces_once():
    nn.manual_seed(0)
    acc = Accelerator(mixed_precision="bf16")
    model = GPTLMHeadModel(GPTConfig.tiny())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    traces = 0

    def step_fn(ids):
        nonlocal traces
        traces += 1
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 1024, (8, 64), dtype=np.int32))
    batch = batch_to_global_array(ids, mesh=acc.mesh)
    for _ in range(4):
        loss = step(batch)
    assert np.isfinite(float(loss))
    assert traces == 1, f"train step re-traced: {traces} traces for 4 identical calls"
    assert len(step._cache) == 1

    # the carried state's shardings are a fixed point after one call
    s1 = step._collect_state()
    step(batch)
    s2 = step._collect_state()
    l1 = jax.tree_util.tree_leaves(s1)
    l2 = jax.tree_util.tree_leaves(s2)
    for a, b in zip(l1, l2):
        sa, sb = getattr(a, "sharding", None), getattr(b, "sharding", None)
        assert str(sa) == str(sb), (sa, sb)


def test_canonical_spec_rejects_unknown_axis():
    from jax.sharding import PartitionSpec as P

    from accelerate_tpu.parallel.mesh import make_mesh
    from accelerate_tpu.parallel.sharding import canonical_spec

    mesh = make_mesh({"dp": len(jax.devices())})
    with pytest.raises(ValueError, match="does not exist in mesh"):
        # deliberately-bogus axis: the ValueError IS the assertion
        canonical_spec(P("tpp"), mesh)  # graftlint: disable=axis-name-mismatch
