"""Feature: train from a DeepSpeed ``ds_config.json`` without DeepSpeed.

Counterpart of reference examples/by_feature/deepspeed_with_config_support.py.
There is no engine to hand the model to on TPU — ZeRO stages are GSPMD
sharding layouts — but an existing ds_config.json keeps working:
``from_deepspeed_config`` maps stage/precision/accumulation/clipping onto
the native ``Accelerator`` configuration.  Lines marked `# New Code #`.
"""

from __future__ import annotations

import argparse
import json
import tempfile

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator
from accelerate_tpu.data_loader import prepare_data_loader
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel

# New Code #
from accelerate_tpu.utils.deepspeed_compat import from_deepspeed_config

DS_CONFIG = {
    "zero_optimization": {"stage": 3},
    "bf16": {"enabled": True},
    "gradient_accumulation_steps": 2,
    "train_micro_batch_size_per_gpu": "auto",
    "gradient_clipping": 1.0,
}


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--ds_config", type=str, default=None, help="path to ds_config.json")
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--lr", type=float, default=1e-3)
    args = parser.parse_args()

    if args.ds_config is None:
        # ship a self-contained default so the example runs anywhere
        tmp = tempfile.NamedTemporaryFile("w", suffix=".json", delete=False)
        json.dump(DS_CONFIG, tmp)
        tmp.close()
        args.ds_config = tmp.name

    # New Code #
    # zero stage -> fsdp sharding strategy, bf16/fp16 -> mixed_precision,
    # accumulation + clipping + "auto" batch resolution, exactly as the
    # reference's deepspeed_config_process fills them
    compat = from_deepspeed_config(args.ds_config, micro_batch_size=args.batch_size)
    accelerator = Accelerator(**compat.accelerator_kwargs())

    nn.manual_seed(0)
    cfg = GPTConfig.tiny()
    model = GPTLMHeadModel(cfg)
    optimizer = optim.AdamW(model.parameters(), lr=args.lr)
    rng = np.random.default_rng(0)
    data = [
        {"input_ids": rng.integers(1, cfg.vocab_size, 64).astype(np.int32)}
        for _ in range(compat.micro_batch_size * 8)
    ]
    dl = prepare_data_loader(dataset=data, batch_size=compat.micro_batch_size, shuffle=True)
    model, optimizer, dl = accelerator.prepare(model, optimizer, dl)

    for epoch in range(args.num_epochs):
        for batch in dl:
            with accelerator.accumulate(model):
                out = model(batch["input_ids"], labels=batch["input_ids"])
                accelerator.backward(out["loss"])
                # New Code #
                if compat.gradient_clipping is not None and accelerator.sync_gradients:
                    accelerator.clip_grad_norm_(model.parameters(), compat.gradient_clipping)
                optimizer.step()
                optimizer.zero_grad()
        accelerator.print(
            f"epoch {epoch}: loss={float(out['loss'].item()):.4f} "
            f"(zero_stage={compat.zero_stage} -> "
            f"{compat.fsdp_plugin.sharding_strategy if compat.fsdp_plugin else 'NO_SHARD'})"
        )


if __name__ == "__main__":
    main()
