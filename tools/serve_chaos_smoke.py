#!/usr/bin/env python
"""serve_chaos_smoke — `make serve-chaos-smoke`: prove fault-tolerant
serving end-to-end on CPU in seconds (docs/serving.md §fault tolerance,
ISSUE 20 acceptance).

Tiny GPT, staggered requests through a journaled replica while the fault
injector fires a transient decode fault (step 3) and then a real SIGTERM
(step 6) mid-flight; a fresh replica pointed at the same journal resumes
every open request.  The scenario runs TWICE against ONE AOT executable
store.  Exit 0 requires, for both passes:

* the decode fault is retried against the same compiled program (at least
  one retry, zero recompile events);
* the SIGTERM drains the first replica with requests still open;
* the restarted replica completes EVERY journaled request — zero lost;
* every request's greedy tokens are identical to a single-request
  ``generate()`` (recovered continuations are bitwise-deterministic);

and additionally for pass 2 (warm store):

* BOTH replicas — including the recovery re-prefills — dispatch with
  ZERO compiles: replica restart is disk reads, never a compile phase.
"""

import os
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAULT_PLAN = "decode_fault:step=3,times=1; serving_sigterm:step=6"
LENGTHS = [3, 9, 17, 30, 5, 24, 12, 40]
BUDGETS = [15, 12, 18, 9, 16, 11, 14, 10]


def run_pass(model, aot_dir: str, pass_idx: int) -> tuple[list[str], str]:
    import numpy as np

    from accelerate_tpu import CompilationCacheKwargs, DecodeService, ServingConfig
    from accelerate_tpu.native.aot_cache import AOTCompilationCache

    failures: list[str] = []
    leg = f"pass={pass_idx}"
    journal_dir = tempfile.mkdtemp(prefix="chaos-journal-")
    cfg = dict(max_slots=4, block_size=16, prompt_bucket=16,
               journal_dir=journal_dir, retry_backoff_s=0.001)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, model.config.vocab_size, (n,), dtype=np.int32)
        for n in LENGTHS
    ]

    try:
        # replica A: journaled, chaos-injected, staggered admissions
        os.environ["ACCELERATE_FAULT_PLAN"] = FAULT_PLAN
        try:
            a = DecodeService(
                model, ServingConfig(**cfg),
                aot_cache=AOTCompilationCache(
                    CompilationCacheKwargs(cache_dir=aot_dir)
                ),
            )
        finally:
            del os.environ["ACCELERATE_FAULT_PLAN"]
        rids, pending = [], list(zip(prompts, BUDGETS))
        while (pending or a.has_work) and not a.draining:
            for _ in range(2):
                if pending:
                    p, b = pending.pop(0)
                    rids.append(a.submit(p, max_new_tokens=b))
            a.step()
        if not a.draining:
            failures.append(f"[{leg}] SIGTERM never drained replica A")
        if a.stats["decode_retries"] < 1:
            failures.append(f"[{leg}] injected decode fault was never retried")
        if a.recompile_events != 0:
            failures.append(
                f"[{leg}] replica A: {a.recompile_events} recompile event(s) "
                "— the retry did not reuse the compiled program"
            )
        open_rids = a.drain()
        if not open_rids:
            failures.append(f"[{leg}] nothing was in flight at the SIGTERM")
        done_a = {r: a.results[r].output_ids for r in rids if r in a.results
                  and a.results[r].state == "done"}
        a_compiles = a.watcher.compiles_total
        a_retries = a.stats["decode_retries"]
        del a

        # replica B: fresh process stand-in — same journal, same AOT store
        b = DecodeService(
            model, ServingConfig(**cfg),
            aot_cache=AOTCompilationCache(
                CompilationCacheKwargs(cache_dir=aot_dir)
            ),
        )
        resumed = b.resume_from_journal()
        if sorted(resumed) != sorted(open_rids):
            failures.append(
                f"[{leg}] journal lost requests: drained {open_rids}, "
                f"resumed {resumed}"
            )
        b.run()
        done_b = {r: b.results[r].output_ids for r in resumed
                  if r in b.results and b.results[r].state == "done"}
        lost = sorted(set(rids) - set(done_a) - set(done_b))
        if lost:
            failures.append(f"[{leg}] requests lost across the restart: {lost}")
        for rid, p, budget in zip(rids, prompts, BUDGETS):
            want = np.asarray(model.generate(p[None], max_new_tokens=budget))[0]
            got = done_b.get(rid, done_a.get(rid))
            if got is None or not np.array_equal(got, want):
                failures.append(
                    f"[{leg}] request {rid}: tokens diverge from generate() "
                    "after recovery"
                )
        b_compiles = b.watcher.compiles_total
        if pass_idx == 2 and (a_compiles or b_compiles):
            failures.append(
                f"[{leg}] warm-store pass still compiled (replica A: "
                f"{a_compiles}, replica B incl. recovery re-prefills: "
                f"{b_compiles}) — restart must be disk reads only"
            )
        summary = (
            f"serve_chaos_smoke[{leg}]: {len(rids)} requests, "
            f"{len(done_a)} finished pre-preemption, {len(resumed)} resumed, "
            f"{b.stats['recovered']} recovered, "
            f"{a_retries} retry(ies) on A, "
            f"compiles A={a_compiles} B={b_compiles}, 0 lost"
        )
        return failures, summary
    finally:
        shutil.rmtree(journal_dir, ignore_errors=True)


def main() -> int:
    import accelerate_tpu.nn as nn
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel

    nn.manual_seed(0)
    model = GPTLMHeadModel(GPTConfig.tiny())
    model.eval()

    aot_dir = tempfile.mkdtemp(prefix="chaos-aot-")
    failures = []
    try:
        for pass_idx in (1, 2):
            pass_failures, summary = run_pass(model, aot_dir, pass_idx)
            failures.extend(pass_failures)
            print(summary)
    finally:
        shutil.rmtree(aot_dir, ignore_errors=True)

    for failure in failures:
        print(f"serve_chaos_smoke: FAIL: {failure}", file=sys.stderr)
    print(f"serve_chaos_smoke: {'FAILED' if failures else 'ok'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
