"""recompile-hazard: python-scalar control flow / shapes inside jit without
``static_argnums``.

A jit argument used in an ``if``/``while`` test, in ``range()``, or as a
shape raises ConcretizationTypeError at trace time — or, when the caller
papers over it by passing python ints, silently recompiles the whole program
for every distinct value (the multi-minute XLA compile, per step).  The fix
is ``static_argnums``/``static_argnames`` (hashable, cache-keyed) or
``lax.cond``/``jnp.where`` for genuinely dynamic branches.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Rule

# module-level constructors: leaf -> positional index of the shape argument
_SHAPE_CREATORS = {
    "zeros": 0,
    "ones": 0,
    "empty": 0,
    "full": 0,
    "eye": 0,
    "arange": 0,
    "linspace": 2,
    "broadcast_to": 1,
    "reshape": 1,
    "tile": 1,
}
# array methods: every argument is part of the shape
_SHAPE_METHODS = {"reshape", "broadcast_to", "tile"}
_JIT_LEAVES = {"jit", "pjit"}


def _jit_statics(call: ast.Call, module):
    """(static_argnums, static_argnames) literals from a jit(...) call."""
    nums: list[int] = []
    names: list[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            nums.extend(
                e.value for e in elts if isinstance(e, ast.Constant) and isinstance(e.value, int)
            )
        elif kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            names.extend(
                e.value for e in elts if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
    return nums, names


def _jit_sites(module):
    """qualname -> (static_argnums, static_argnames) for every locally
    defined function wrapped by jit (decorator or call form)."""
    sites: dict[str, tuple[list[int], list[str]]] = {}
    cg = module.callgraph
    for info in cg.functions.values():
        for dec in getattr(info.node, "decorator_list", []):
            target = dec.func if isinstance(dec, ast.Call) else dec
            resolved = module.resolve(target) or ""
            leaf = resolved.rsplit(".", 1)[-1]
            if leaf in _JIT_LEAVES:
                statics = _jit_statics(dec, module) if isinstance(dec, ast.Call) else ([], [])
                sites[info.qualname] = statics
            elif leaf == "partial" and isinstance(dec, ast.Call):
                if any(
                    (module.resolve(a) or "").rsplit(".", 1)[-1] in _JIT_LEAVES
                    for a in dec.args
                ):
                    sites[info.qualname] = _jit_statics(dec, module)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = module.resolve(node.func) or ""
        if resolved.rsplit(".", 1)[-1] not in _JIT_LEAVES:
            continue
        if node.args and isinstance(node.args[0], ast.Name):
            for info in cg.by_leaf.get(node.args[0].id, []):
                sites.setdefault(info.qualname, _jit_statics(node, module))
    return sites


def _dynamic_shape_names(expr: ast.AST) -> set[str]:
    """Names a shape expression *dynamically* depends on.  ``x.shape[0]`` /
    ``x.ndim`` / ``len(x)`` are static at trace time, so names that only
    appear under those forms don't make the shape dynamic."""
    static_subtrees: set[int] = set()
    for node in ast.walk(expr):
        is_static = (
            isinstance(node, ast.Attribute) and node.attr in ("shape", "ndim", "size")
        ) or (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "len"
        )
        if is_static:
            for sub in ast.walk(node):
                static_subtrees.add(id(sub))
    return {
        n.id
        for n in ast.walk(expr)
        if isinstance(n, ast.Name) and id(n) not in static_subtrees
    }


def _names_in_concretizing_positions(test: ast.AST):
    """Names whose truthiness/ordering the test depends on — excluding
    trace-safe forms (`x is None`, isinstance/hasattr/callable, len(), and
    `.shape`/`.ndim`/`.size` reads, which are static at trace time)."""
    out: set[str] = set()
    skip: set[int] = set()
    for node in ast.walk(test):
        if id(node) in skip:
            continue
        if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            for sub in ast.walk(node):
                skip.add(id(sub))
        elif isinstance(node, ast.Attribute) and node.attr in ("shape", "ndim", "size"):
            for sub in ast.walk(node):
                skip.add(id(sub))
        elif isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in (
                "isinstance",
                "hasattr",
                "callable",
                "getattr",
                "len",
            ):
                for sub in ast.walk(node):
                    skip.add(id(sub))
    for node in ast.walk(test):
        if id(node) not in skip and isinstance(node, ast.Name):
            out.add(node.id)
    return out


class RecompileHazard(Rule):
    id = "recompile-hazard"
    description = (
        "jit argument used in python control flow / range() / shapes without "
        "static_argnums, or an unhashable static default"
    )

    def check(self, module, ctx):
        findings = []
        cg = module.callgraph
        for qual, (argnums, argnames) in _jit_sites(module).items():
            info = cg.functions[qual]
            node = info.node
            a = node.args
            params = [p.arg for p in a.posonlyargs + a.args]
            static = set(argnames)
            static.update(params[i] for i in argnums if 0 <= i < len(params))
            dynamic = {
                p
                for p in params + [p.arg for p in a.kwonlyargs]
                if p not in static and p not in ("self", "cls")
            }
            # unhashable default on a *static* param breaks the jit cache key
            defaults = dict(zip(params[len(params) - len(a.defaults):], a.defaults))
            for p in sorted(static):
                d = defaults.get(p)
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    findings.append(
                        Finding(
                            self.id,
                            module.rel_path,
                            d.lineno,
                            d.col_offset,
                            f"static argument '{p}' of jitted '{qual}' has an "
                            "unhashable default (list/dict/set) — jit's cache "
                            "key requires hashable statics",
                            symbol=qual,
                        )
                    )
            findings.extend(self._scan_body(module, info, dynamic))
        return findings

    def _scan_body(self, module, info, dynamic):
        findings = []
        qual = info.qualname

        def hit(node, msg):
            findings.append(
                Finding(self.id, module.rel_path, node.lineno, node.col_offset, msg, symbol=qual)
            )

        for node in ast.walk(info.node):
            if isinstance(node, (ast.If, ast.While)):
                used = _names_in_concretizing_positions(node.test) & dynamic
                for p in sorted(used):
                    hit(
                        node,
                        f"python control flow on traced argument '{p}' of jitted "
                        f"'{qual}' — mark it static_argnums/static_argnames or "
                        "use lax.cond/jnp.where",
                    )
            elif isinstance(node, ast.Call):
                fn = node.func
                resolved = module.resolve(fn) or ""
                leaf = resolved.rsplit(".", 1)[-1]
                if isinstance(fn, ast.Name) and fn.id == "range":
                    used = {
                        n.id
                        for a_ in node.args
                        for n in ast.walk(a_)
                        if isinstance(n, ast.Name)
                    } & dynamic
                    for p in sorted(used):
                        hit(
                            node,
                            f"range() over traced argument '{p}' of jitted '{qual}' "
                            "— mark it static or use lax.fori_loop",
                        )
                elif leaf in _SHAPE_CREATORS and resolved.startswith(("jax.numpy", "numpy")):
                    pos = _SHAPE_CREATORS[leaf]
                    shape_arg = node.args[pos] if len(node.args) > pos else None
                    for kw in node.keywords:
                        if kw.arg == "shape":
                            shape_arg = kw.value
                    if shape_arg is not None:
                        used = _dynamic_shape_names(shape_arg) & dynamic
                        for p in sorted(used):
                            hit(
                                node,
                                f"shape of {leaf}() derives from traced argument "
                                f"'{p}' of jitted '{qual}' — shapes must be static "
                                "under jit (static_argnums, or pad to a bucket)",
                            )
                elif (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in _SHAPE_METHODS
                    and not resolved.startswith(("jax.", "numpy"))
                ):
                    used = set().union(
                        set(), *(_dynamic_shape_names(a_) for a_ in node.args)
                    ) & dynamic
                    for p in sorted(used):
                        hit(
                            node,
                            f".{fn.attr}() shape derives from traced argument '{p}' "
                            f"of jitted '{qual}' — shapes must be static under jit",
                        )
        return findings
