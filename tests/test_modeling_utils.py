"""Sizing / placement planner tests (mirrors reference tests/test_modeling_utils.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.nn as nn
from accelerate_tpu.big_modeling import init_empty_weights
from accelerate_tpu.utils.modeling import (
    calculate_maximum_sizes,
    check_device_map,
    clean_device_map,
    compute_module_sizes,
    convert_file_size_to_int,
    dtype_byte_size,
    find_tied_parameters,
    get_balanced_memory,
    infer_auto_device_map,
    load_checkpoint_in_model,
    retie_parameters,
    set_module_tensor_to_device,
)


class SubNet(nn.Module):
    def __init__(self):
        super().__init__()
        self.linear1 = nn.Linear(4, 4)
        self.linear2 = nn.Linear(4, 4)

    def forward(self, x):
        return self.linear2(self.linear1(x))


class BiggerModel(nn.Module):
    def __init__(self):
        super().__init__()
        self.block1 = SubNet()
        self.block2 = SubNet()
        self.head = nn.Linear(4, 2)

    def forward(self, x):
        return self.head(self.block2(self.block1(x)))


def test_dtype_byte_size():
    assert dtype_byte_size(jnp.float32) == 4
    assert dtype_byte_size(jnp.bfloat16) == 2
    assert dtype_byte_size(jnp.int8) == 1
    assert dtype_byte_size("bool") == 1 / 8


def test_convert_file_size():
    assert convert_file_size_to_int("1KB") == 1000
    assert convert_file_size_to_int("1KiB") == 1024
    assert convert_file_size_to_int("2GB") == 2 * 10**9
    assert convert_file_size_to_int(77) == 77
    with pytest.raises(ValueError):
        convert_file_size_to_int("1 potato")


def test_compute_module_sizes():
    model = BiggerModel()
    sizes = compute_module_sizes(model)
    # linear(4,4): 4*4+4 = 20 floats = 80 bytes
    assert sizes["block1.linear1"] == 80
    assert sizes["block1"] == 160
    # head: 4*2+2 = 10 floats
    assert sizes["head"] == 40
    assert sizes[""] == 160 + 160 + 40
    # half-precision sizing
    sizes16 = compute_module_sizes(model, dtype=jnp.bfloat16)
    assert sizes16[""] == sizes[""] // 2


def test_compute_module_sizes_on_meta():
    with init_empty_weights():
        model = BiggerModel()
    sizes = compute_module_sizes(model)
    assert sizes[""] == 360


def test_calculate_maximum_sizes():
    model = BiggerModel()
    total, (largest, name) = calculate_maximum_sizes(model)
    assert total == 360
    assert largest == 80  # a single Linear leaf
    assert name.startswith("block")


def test_find_and_retie_tied_parameters():
    model = BiggerModel()
    assert find_tied_parameters(model) == []
    # tie head weight to block2.linear2 weight (object sharing = tying)
    model.head.weight = model.block2.linear2.weight
    tied = find_tied_parameters(model)
    assert tied == [["block2.linear2.weight", "head.weight"]]
    # tied params counted once in sizes
    sizes = compute_module_sizes(model)
    assert sizes[""] == 360 - 40 + 8  # head.weight (32B) deduped; bias stays

    # break tying, then retie
    model.head.weight = nn.Parameter(jnp.zeros((4, 4)))
    assert find_tied_parameters(model) == []
    retie_parameters(model, tied)
    assert find_tied_parameters(model) == tied


def test_set_module_tensor_to_device():
    import jax

    model = SubNet()
    set_module_tensor_to_device(model, "linear1.weight", "cpu")
    dev = list(model.linear1.weight.data.devices())[0]
    assert dev.platform == "cpu"
    set_module_tensor_to_device(
        model, "linear1.weight", 0, value=np.ones((4, 4), np.float32)
    )
    assert model.linear1.weight.data[0, 0] == 1.0
    set_module_tensor_to_device(model, "linear1.weight", "meta")
    from accelerate_tpu.nn.meta import is_meta

    assert is_meta(model.linear1.weight.data)
    with pytest.raises(ValueError):
        set_module_tensor_to_device(model, "linear1.weight", 0)  # meta, no value


def test_infer_auto_device_map_all_fit():
    model = BiggerModel()
    device_map = infer_auto_device_map(model, max_memory={0: 10_000})
    check_device_map(model, device_map)
    assert set(device_map.values()) == {0}


def test_infer_auto_device_map_splits():
    model = BiggerModel()
    # 200 bytes on chip0: block1 (160) fits, block2 (160) must split/spill
    device_map = infer_auto_device_map(model, max_memory={0: 200, 1: 200})
    check_device_map(model, device_map)
    assert device_map["block1"] == 0
    assert all(v in (0, 1) for v in device_map.values())
    # with no_split, block2 moves wholesale to chip 1
    device_map = infer_auto_device_map(
        model, max_memory={0: 200, 1: 200}, no_split_module_classes=["SubNet"]
    )
    assert device_map["block1"] == 0
    assert device_map["block2"] == 1


def test_infer_auto_device_map_spills_to_cpu_and_disk():
    model = BiggerModel()
    device_map = infer_auto_device_map(
        model,
        max_memory={0: 170, "cpu": 170},
        no_split_module_classes=["SubNet"],
    )
    check_device_map(model, device_map)
    assert device_map["block1"] == 0
    assert device_map["block2"] == "cpu"
    assert device_map["head"] == "disk"


def test_infer_auto_device_map_tied_weights_colocate():
    model = BiggerModel()
    model.head.weight = model.block1.linear1.weight
    # chip0 fits block1 (160B) with 10B slack; block2 overflows to chip1; head
    # (8B after tied dedup) would normally follow onto chip1 — the tied pull
    # brings it back to chip0 where its shared weight lives
    device_map = infer_auto_device_map(
        model, max_memory={0: 170, 1: 400}, no_split_module_classes=["SubNet"]
    )
    check_device_map(model, device_map)
    assert device_map["block1"] == 0
    assert device_map["block2"] == 1
    assert device_map["head"] == 0


def test_clean_device_map():
    dm = {"a.0": 0, "a.1": 0, "b": 1}
    assert clean_device_map(dict(dm)) == {"a": 0, "b": 1}
    dm = {"a.0": 0, "a.1": 1}
    assert clean_device_map(dict(dm)) == dm
    assert clean_device_map({"a": 0, "b": 0}) == {"": 0}


def test_get_balanced_memory():
    model = BiggerModel()
    balanced = get_balanced_memory(model, max_memory={0: 10_000, 1: 10_000})
    # chip 0 capped below full budget, last chip keeps its budget
    assert balanced[0] < 10_000
    assert balanced[1] == 10_000


def test_load_checkpoint_in_model(tmp_path):
    from safetensors.numpy import save_file

    model = SubNet()
    sd = {k: np.asarray(v) for k, v in model.state_dict().items()}
    path = str(tmp_path / "model.safetensors")
    save_file(sd, path)

    with init_empty_weights():
        fresh = SubNet()
    missing = load_checkpoint_in_model(
        fresh, path, device_map={"": 0}, strict=True
    )
    assert missing == []
    np.testing.assert_array_equal(
        np.asarray(fresh.linear1.weight.data), sd["linear1.weight"]
    )


def test_load_checkpoint_in_model_disk_offload(tmp_path):
    from safetensors.numpy import save_file

    model = SubNet()
    sd = {k: np.asarray(v) for k, v in model.state_dict().items()}
    save_file(sd, str(tmp_path / "model.safetensors"))

    with init_empty_weights():
        fresh = SubNet()
    load_checkpoint_in_model(
        fresh,
        str(tmp_path / "model.safetensors"),
        device_map={"linear1": 0, "linear2": "disk"},
        offload_folder=str(tmp_path / "offload"),
    )
    from accelerate_tpu.nn.meta import is_meta

    assert is_meta(fresh.linear2.weight.data)
    assert (tmp_path / "offload" / "index.json").exists()


def test_dtype_byte_size_fp8_variants():
    # fp8 names embed digits that must not be parsed as bit-widths
    assert dtype_byte_size(jnp.float8_e4m3fn) == 1
    assert dtype_byte_size(jnp.float8_e5m2) == 1
    assert dtype_byte_size("int4") == 0.5


def test_infer_auto_device_map_tied_full_falls_back_to_open_chip():
    """When the tied-preferred chip is full, the CURRENT fill chip must be
    tried before spilling to cpu/disk (code-review regression)."""
    model = BiggerModel()
    model.head.weight = model.block1.linear1.weight  # tie head to block1
    # chip0 fits block1 (160B) with 5B slack — too small even for head's
    # bias (8B), so the tied pull to chip0 must fail and fall back to the
    # regular fill device (chip1), NOT skip past it to cpu/disk
    device_map = infer_auto_device_map(
        model,
        max_memory={0: 165, 1: 10_000, "cpu": 10_000},
        no_split_module_classes=["SubNet", "Linear"],
    )
    check_device_map(model, device_map)
    assert device_map["block1"] == 0
    assert device_map["head"] == 1
    assert "disk" not in device_map.values()
    assert "cpu" not in device_map.values()


def test_split_direct_tensors_try_all_devices():
    """Direct tensors of a split module must scan remaining devices before
    hitting disk (code-review regression)."""
    model = BiggerModel()
    device_map = infer_auto_device_map(
        model, max_memory={0: 100, 1: 10_000}
    )
    check_device_map(model, device_map)
    assert "disk" not in device_map.values()
