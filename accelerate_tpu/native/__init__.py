"""Native host-runtime bindings (ctypes over a small C++17 library).

The reference's host-side performance comes from vendored native code —
torch's C++ DataLoader worker pool / collate and native serialization
(reference: src/accelerate/data_loader.py:643-693 drives torch loaders whose
row loops are ATen C++).  accelerate_tpu's equivalent lives in
``src/fastloader.cc``: fused batch assembly (gather/stack/pad-stack) and
chunked parallel checkpoint IO.

Binding strategy (no pybind11 in the image): a plain ``extern "C"`` ABI
loaded with ctypes.  The .so is built on demand with g++ the first time it
is needed, cached next to the source, and keyed by source mtime + ABI probe
so edits rebuild automatically.  Everything here degrades gracefully:

* ``ACCELERATE_TPU_NO_NATIVE=1`` disables the library entirely;
* missing g++ / failed compile / load error → ``available()`` is False and
  callers fall back to their numpy paths (the wrappers below raise if called
  while unavailable — call sites must guard with ``available()``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src", "fastloader.cc")
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src", "_fastloader.so")
_ABI_VERSION = 1

_lock = threading.Lock()
_lib = None
_load_failed: str | None = None


def _threads_default() -> int:
    n = os.environ.get("ACCELERATE_TPU_NATIVE_THREADS")
    if n is not None:
        return max(1, int(n))
    return max(1, os.cpu_count() or 1)


_MIN_BYTES_PER_THREAD = 1 << 20


def _cap_threads(threads: int | None, total_bytes: int) -> int:
    """Never spawn a thread for <1 MiB of work — std::thread create+join costs
    more than a small memcpy, so tiny batches stay single-threaded."""
    t = threads or _threads_default()
    return max(1, min(t, total_bytes // _MIN_BYTES_PER_THREAD or 1))


def _build() -> str | None:
    """Compile the .so if missing/stale; returns an error string on failure."""
    try:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return None
        # per-process tmp name: concurrent first-use builds (pytest-xdist,
        # data workers) must not interleave linker output on a shared path
        tmp = f"{_SO}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            _SRC, "-o", tmp,
        ]
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            return f"g++ failed: {proc.stderr[-500:]}"
        os.replace(tmp, _SO)
        return None
    except (OSError, subprocess.SubprocessError) as e:  # g++ missing, RO fs, ...
        return f"build error: {e}"


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed is not None:
        return
    with _lock:
        if _lib is not None or _load_failed is not None:
            return
        if os.environ.get("ACCELERATE_TPU_NO_NATIVE") == "1":
            _load_failed = "disabled via ACCELERATE_TPU_NO_NATIVE"
            return
        err = _build()
        if err is not None:
            _load_failed = err
            return
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            _load_failed = f"dlopen failed: {e}"
            return
        try:
            if lib.at_abi_version() != _ABI_VERSION:
                _load_failed = "stale ABI; delete src/_fastloader.so"
                return
        except AttributeError:
            _load_failed = "ABI probe symbol missing"
            return
        c = ctypes
        lib.at_gather_rows.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                       c.c_int64, c.c_int64, c.c_int]
        lib.at_stack_rows.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                      c.c_int64, c.c_int]
        lib.at_pad_stack.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                     c.c_int64, c.c_int64, c.c_int64,
                                     c.c_void_p, c.c_int]
        lib.at_write_file.argtypes = [c.c_char_p, c.c_void_p, c.c_int64, c.c_int]
        lib.at_write_file.restype = c.c_int
        lib.at_write_region.argtypes = [c.c_char_p, c.c_void_p, c.c_int64,
                                        c.c_int64, c.c_int]
        lib.at_write_region.restype = c.c_int
        lib.at_read_file.argtypes = [c.c_char_p, c.c_void_p, c.c_int64,
                                     c.c_int64, c.c_int]
        lib.at_read_file.restype = c.c_int
        _lib = lib


def available() -> bool:
    """True when the native library is built and loaded (or buildable)."""
    _load()
    return _lib is not None


def load_error() -> str | None:
    """Why the native library is unavailable (None when it is available)."""
    _load()
    return _load_failed


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def gather_rows(src: np.ndarray, indices: np.ndarray,
                out: np.ndarray | None = None, threads: int | None = None) -> np.ndarray:
    """out[i] = src[indices[i]] for a C-contiguous 2-D+ src (rows on axis 0).

    The DataLoader-worker inner loop (``[dataset[i] for i in batch]`` +
    collate) fused into one native call; src is typically a np.memmap token
    array so nothing but the gathered rows is ever touched.
    """
    _load()
    if _lib is None:
        raise RuntimeError(f"native library unavailable: {_load_failed}")
    if not src.flags.c_contiguous:
        raise ValueError("src must be C-contiguous")
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    if idx.ndim != 1:
        raise ValueError("indices must be 1-D")
    n = idx.shape[0]
    if n and (idx.min() < 0 or idx.max() >= src.shape[0]):
        raise IndexError("gather index out of range")
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    if out is None:
        out = np.empty((n,) + src.shape[1:], dtype=src.dtype)
    else:
        if not out.flags.c_contiguous or out.shape != (n,) + src.shape[1:] or out.dtype != src.dtype:
            raise ValueError("out has wrong shape/dtype/layout")
    _lib.at_gather_rows(_ptr(src), _ptr(idx), _ptr(out), n, row_bytes,
                        _cap_threads(threads, n * row_bytes))
    return out


def stack_rows(samples: list[np.ndarray], out: np.ndarray | None = None,
               threads: int | None = None) -> np.ndarray:
    """np.stack(samples) with the per-sample Python loop in native code."""
    _load()
    if _lib is None:
        raise RuntimeError(f"native library unavailable: {_load_failed}")
    n = len(samples)
    if n == 0:
        raise ValueError("empty sample list")
    first = samples[0]
    row_bytes = first.dtype.itemsize * first.size
    ptrs = (ctypes.c_void_p * n)()
    for i, s in enumerate(samples):
        if s.shape != first.shape or s.dtype != first.dtype or not s.flags.c_contiguous:
            raise ValueError("samples must be homogeneous C-contiguous arrays")
        ptrs[i] = s.ctypes.data
    if out is None:
        out = np.empty((n,) + first.shape, dtype=first.dtype)
    elif (not out.flags.c_contiguous or out.shape != (n,) + first.shape
          or out.dtype != first.dtype):
        raise ValueError("out has wrong shape/dtype/layout")
    _lib.at_stack_rows(ptrs, _ptr(out), n, row_bytes,
                       _cap_threads(threads, n * row_bytes))
    return out


def pad_stack(samples: list[np.ndarray], max_len: int | None = None,
              pad_value=0, threads: int | None = None) -> np.ndarray:
    """Stack ragged 1-D rows into [n, max_len], right-padded with pad_value."""
    _load()
    if _lib is None:
        raise RuntimeError(f"native library unavailable: {_load_failed}")
    n = len(samples)
    if n == 0:
        raise ValueError("empty sample list")
    dtype = samples[0].dtype
    lens = np.empty(n, dtype=np.int64)
    ptrs = (ctypes.c_void_p * n)()
    for i, s in enumerate(samples):
        if s.ndim != 1 or s.dtype != dtype or not s.flags.c_contiguous:
            raise ValueError("samples must be C-contiguous 1-D arrays of one dtype")
        lens[i] = s.shape[0]
        ptrs[i] = s.ctypes.data
    ml = int(lens.max()) if max_len is None else int(max_len)
    if lens.max() > ml:
        raise ValueError(f"sample longer than max_len={ml}")
    out = np.empty((n, ml), dtype=dtype)
    pad = np.asarray(pad_value, dtype=dtype)
    _lib.at_pad_stack(ptrs, _ptr(lens), _ptr(out), n, ml, dtype.itemsize,
                      _ptr(pad), _cap_threads(threads, out.nbytes))
    return out


def write_file(path: str, data: np.ndarray | bytes | memoryview,
               threads: int | None = None) -> None:
    """Write a contiguous buffer to path with chunked parallel pwrite."""
    _load()
    if _lib is None:
        raise RuntimeError(f"native library unavailable: {_load_failed}")
    if isinstance(data, np.ndarray):
        if not data.flags.c_contiguous:
            data = np.ascontiguousarray(data)
        buf, nbytes = _ptr(data), data.nbytes
        rc = _lib.at_write_file(path.encode(), buf, nbytes, _cap_threads(threads, nbytes))
    else:
        raw = bytes(data)
        rc = _lib.at_write_file(path.encode(), raw, len(raw), _cap_threads(threads, len(raw)))
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc), path)


def write_region(path: str, data: np.ndarray, offset: int,
                 threads: int | None = None) -> None:
    """Parallel pwrite of a contiguous array at offset into an existing file."""
    _load()
    if _lib is None:
        raise RuntimeError(f"native library unavailable: {_load_failed}")
    if not data.flags.c_contiguous:
        data = np.ascontiguousarray(data)
    rc = _lib.at_write_region(path.encode(), _ptr(data), data.nbytes, offset,
                              _cap_threads(threads, data.nbytes))
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc), path)


def read_into(path: str, out: np.ndarray, offset: int = 0,
              threads: int | None = None) -> np.ndarray:
    """Fill a preallocated contiguous array from path[offset:offset+nbytes]."""
    _load()
    if _lib is None:
        raise RuntimeError(f"native library unavailable: {_load_failed}")
    if not out.flags.c_contiguous:
        raise ValueError("out must be C-contiguous")
    rc = _lib.at_read_file(path.encode(), _ptr(out), out.nbytes, offset,
                           _cap_threads(threads, out.nbytes))
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc), path)
    return out
