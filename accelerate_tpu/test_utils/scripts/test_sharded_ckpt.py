"""Multi-process sharded checkpoint: each process writes ITS shard files.

Run under ``debug_launcher(num_processes=2)``: the fsdp axis spans the two
processes, so ``save_state`` must produce one ``*.shard-0000R-of-00002``
file per rank for the model AND the optimizer, and ``load_state`` must
reassemble only each process's local blocks.  This is the true multihost
exercise of the round-3 sharded-checkpoint path (single-process tests can
only simulate it with explicit rank arguments).
"""

from __future__ import annotations

import glob
import os
import shutil

import numpy as np

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.utils.constants import MODEL_NAME, OPTIMIZER_NAME


def main():
    import jax.numpy as jnp

    from accelerate_tpu import PartialState

    # the rendezvous must happen BEFORE any jax.devices() query initialises
    # the backend non-distributed (same ordering rule as test_launcher.py)
    PartialState()
    nn.manual_seed(0)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(fsdp_size=acc_devices()),
        mixed_precision="no",
    )
    model = GPTLMHeadModel(GPTConfig.tiny())
    opt = optim.AdamW(model.parameters(), lr=1e-3)
    model, opt = acc.prepare(model, opt)

    def step_fn(ids):
        opt.zero_grad()
        out = model(ids, labels=ids)
        acc.backward(out["loss"])
        opt.step()
        return out["loss"]

    step = acc.compile_step(step_fn)
    # batch rows must divide over the full batch-sharding (dp×fsdp) size —
    # under pytest each worker inherits the 8-device XLA flag, so the global
    # device count is workers × 8, not workers
    rows = max(8, 2 * acc_devices())
    ids = batch_to_global_array(
        jnp.asarray(np.random.default_rng(0).integers(0, 1024, (rows, 32)), jnp.int32),
        mesh=acc.mesh,
    )
    float(step(ids))

    # verify restoration of a GENUINELY fsdp-sharded tensor: wte is
    # fsdp-exempt (replicated), so it would pass even if cross-process
    # reassembly were broken — an attention weight is sharded for real.
    target = model.h[0].attn.c_attn.weight

    def local_sum(p) -> float:
        return float(
            sum(np.asarray(sh.data).sum() for sh in p.data.addressable_shards)
        )

    before = local_sum(target)

    from ..testing import launch_scoped_tmpdir

    ckpt = launch_scoped_tmpdir("acc_tpu_shckpt")
    try:
        acc.save_state(ckpt)
        world = acc.num_processes
        if world > 1:
            # every rank wrote its own shard file for model AND optimizer
            for name in (MODEL_NAME, OPTIMIZER_NAME):
                files = sorted(
                    glob.glob(os.path.join(ckpt, f"{name}.shard-*-of-{world:05d}.safetensors"))
                )
                assert len(files) == world, (name, files)
            print(f"rank{acc.process_index}: per-rank shard files ok")
        # clobber the sharded tensor and restore
        target.data = target.data * 0.0
        assert abs(local_sum(target)) < 1e-6
        acc.load_state(ckpt)
        after = local_sum(target)
        assert abs(after - before) < 1e-4 * max(1.0, abs(before)), (after, before)
        # training continues from the restored state
        loss = float(step(ids))
        assert np.isfinite(loss)
        print(f"rank{acc.process_index}: sharded save/load + resume ok (loss {loss:.4f})")

        # async save under the real multi-process rendezvous: prepare runs
        # the collective/D2H phase at call time, the writer thread does pure
        # file IO, and wait_for_checkpoint runs the collective finalize on
        # every rank.  Steps taken while the writer runs (which donate the
        # live buffers) must not leak into the checkpoint.
        snap = local_sum(target)
        ckpt_async = launch_scoped_tmpdir("acc_tpu_shckpt_async")
        try:
            acc.save_state(ckpt_async, async_save=True)
            for _ in range(2):
                float(step(ids))  # mutates + donates state mid-write
            acc.wait_for_checkpoint()
            if world > 1:
                for name in (MODEL_NAME, OPTIMIZER_NAME):
                    files = glob.glob(
                        os.path.join(
                            ckpt_async, f"{name}.shard-*-of-{world:05d}.safetensors"
                        )
                    )
                    assert len(files) == world, (name, files)
            target.data = target.data * 0.0
            acc.load_state(ckpt_async)
            restored = local_sum(target)
            assert abs(restored - snap) < 1e-4 * max(1.0, abs(snap)), (restored, snap)
            loss = float(step(ids))
            assert np.isfinite(loss)
            print(f"rank{acc.process_index}: ASYNC sharded save/load ok (loss {loss:.4f})")
        finally:
            acc.wait_for_everyone()
            if acc.is_main_process:
                shutil.rmtree(ckpt_async, ignore_errors=True)
    finally:
        acc.wait_for_everyone()
        if acc.is_main_process:
            shutil.rmtree(ckpt, ignore_errors=True)


def acc_devices() -> int:
    import jax

    return len(jax.devices())


if __name__ == "__main__":
    main()
