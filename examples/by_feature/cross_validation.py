"""Feature: k-fold cross validation with metric gathering.

Counterpart of /root/reference/examples/by_feature/cross_validation.py: the
dataset is split into k folds, one model trains per fold on the other k-1,
and per-fold predictions are gathered (deduped through gather_for_metrics)
into one out-of-fold accuracy.  Lines marked `# New Code #` are what this
feature adds to nlp_example.py.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

import accelerate_tpu.nn as nn  # noqa: E402
import accelerate_tpu.optim as optim  # noqa: E402
from accelerate_tpu import Accelerator  # noqa: E402
from accelerate_tpu.models import BertConfig, BertForSequenceClassification  # noqa: E402


def training_function(args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    nn.manual_seed(args.seed)

    # New Code #
    # one dataloader pair per fold: get_dataloaders(fold=i) rotates which
    # slice of the training set is held out for validation
    fold_predictions = []
    fold_labels = []
    for fold in range(args.num_folds):
        train_dl, val_dl, vocab = get_dataloaders(
            accelerator, args.batch_size, args.seed, fold=fold, num_folds=args.num_folds
        )
        cfg = BertConfig.small() if args.small else BertConfig.base()
        cfg.vocab_size = max(cfg.vocab_size, vocab)
        model = BertForSequenceClassification(cfg)
        optimizer = optim.AdamW(model.parameters(), lr=args.lr)
        scheduler = optim.get_linear_schedule_with_warmup(
            optimizer, 10, len(train_dl) * args.num_epochs * accelerator.num_devices
        )
        model, optimizer, train_dl, val_dl, scheduler = accelerator.prepare(
            model, optimizer, train_dl, val_dl, scheduler
        )

        for epoch in range(args.num_epochs):
            model.train()
            for batch in train_dl:
                optimizer.zero_grad()
                out = model(
                    batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                    labels=batch["labels"],
                )
                accelerator.backward(out["loss"])
                optimizer.step()
                scheduler.step()

        # New Code #
        # out-of-fold predictions, deduped across shards
        model.eval()
        for batch in val_dl:
            with nn.no_grad():
                out = model(
                    batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                )
            logits, refs = accelerator.gather_for_metrics(
                (out["logits"].data, batch["labels"])
            )
            fold_predictions.append(np.asarray(logits))
            fold_labels.append(np.asarray(refs))
        accelerator.free_memory()

    # New Code #
    # ensemble metric over every held-out sample of every fold
    preds = np.concatenate(fold_predictions).argmax(-1)
    refs = np.concatenate(fold_labels)
    accuracy = float((preds == refs).mean())
    accelerator.print(f"out-of-fold accuracy over {args.num_folds} folds: {accuracy:.3f}")
    return accuracy


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", type=str, default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--num_folds", type=int, default=3)
    parser.add_argument("--lr", type=float, default=2e-4)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--small", action="store_true")
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
