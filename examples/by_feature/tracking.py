"""Feature: experiment tracking via ``init_trackers``/``log``/``end_training``.

Counterpart of /root/reference/examples/by_feature/tracking.py.  Lines marked
`# New Code #` are what this feature adds to nlp_example.py.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

import accelerate_tpu.nn as nn  # noqa: E402
import accelerate_tpu.optim as optim  # noqa: E402
from accelerate_tpu import Accelerator  # noqa: E402
from accelerate_tpu.models import BertConfig, BertForSequenceClassification  # noqa: E402


def training_function(args):
    # New Code #
    # log_with="all" resolves every installed tracker backend (jsonl always)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        log_with="all" if args.with_tracking else None,
        project_dir=args.project_dir,
    )
    nn.manual_seed(args.seed)
    train_dl, val_dl, vocab = get_dataloaders(accelerator, args.batch_size, args.seed)

    cfg = BertConfig.small() if args.small else BertConfig.base()
    cfg.vocab_size = max(cfg.vocab_size, vocab)
    model = BertForSequenceClassification(cfg)
    optimizer = optim.AdamW(model.parameters(), lr=args.lr)
    scheduler = optim.get_linear_schedule_with_warmup(
        optimizer, 100, len(train_dl) * args.num_epochs * accelerator.num_devices
    )
    model, optimizer, train_dl, val_dl, scheduler = accelerator.prepare(
        model, optimizer, train_dl, val_dl, scheduler
    )

    # New Code #
    if args.with_tracking:
        accelerator.init_trackers("nlp_example_tracking", config=vars(args))

    for epoch in range(args.num_epochs):
        model.train()
        # New Code #
        total_loss = 0.0
        for step, batch in enumerate(train_dl):
            optimizer.zero_grad()
            out = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                labels=batch["labels"],
            )
            accelerator.backward(out["loss"])
            optimizer.step()
            scheduler.step()
            # New Code #
            total_loss += float(out["loss"].item())

        model.eval()
        correct = total = 0
        for batch in val_dl:
            out = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            preds = out["logits"].data.argmax(-1)
            preds = accelerator.gather_for_metrics(preds)
            labels = accelerator.gather_for_metrics(batch["labels"])
            correct += int((np.asarray(preds) == np.asarray(labels)).sum())
            total += len(np.asarray(labels))
        acc = correct / max(total, 1)
        accelerator.print(f"epoch {epoch}: accuracy={acc:.4f}")
        # New Code #
        if args.with_tracking:
            accelerator.log({"train_loss": total_loss / len(train_dl), "accuracy": acc}, step=epoch)
    # New Code #
    if args.with_tracking:
        accelerator.end_training()
    return acc


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mixed_precision", type=str, default="bf16", choices=["no", "fp16", "bf16"])
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--num_epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=2e-5)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--small", action="store_true")
    # New Code #
    parser.add_argument("--with_tracking", action="store_true")
    parser.add_argument("--project_dir", type=str, default="logs")
    args = parser.parse_args()
    training_function(args)


if __name__ == "__main__":
    main()
