#!/usr/bin/env python
"""serving_smoke — `make serve-smoke`: prove the decode service end-to-end
on CPU in seconds (docs/serving.md, ISSUE 7 + ISSUE 14 acceptance).

Tiny GPT, 8 concurrent requests with mixed prompt lengths and staggered
arrivals through the continuous-batching service — TWICE: once on the
classic per-token path (decode_steps=1) and once on the device-resident
multi-token loop (decode_steps=8).  Exit 0 requires, for BOTH legs:

* every request completes, and its greedy tokens are IDENTICAL to a
  single-request ``generate()`` of the same prompt (the parity contract —
  one attention implementation, true positions, same mask);
* ZERO recompile events after warmup (CompileWatcher forensics: one decode
  program + one prefill program per prompt bucket, then pure replays);
* the block pool drains with no leaked blocks;
* telemetry (on for the run) retained ``kind="serving"`` step records with
  occupancy and per-request completion records with TTFT/TPOT;

and additionally for the decode_steps=8 leg:

* ``host_syncs_per_token`` ≤ 1/8 + ε — the hot loop really does sync the
  host once per 8-token block, not per token.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run_leg(model, hub, decode_steps: int) -> tuple[list[str], str]:
    """One full staggered-trace run; returns (failures, summary line)."""
    import numpy as np

    from accelerate_tpu import DecodeService, ServingConfig
    from accelerate_tpu.serving import bucket_length

    service = DecodeService(
        model,
        ServingConfig(max_slots=4, block_size=16, prompt_bucket=16,
                      decode_steps=decode_steps),
        telemetry=hub,
    )

    rng = np.random.default_rng(0)
    lengths = [3, 9, 17, 30, 5, 24, 12, 40]
    # budgets deep enough that the n=8 leg amortizes whole blocks — a
    # request finishing inside its first block would read ~1/(budget-1)
    # syncs/token no matter how good the loop is
    budgets = [25, 17, 33, 9, 28, 19, 24, 14]
    prompts = [
        rng.integers(0, model.config.vocab_size, (n,), dtype=np.int32)
        for n in lengths
    ]

    # warmup: one request per prefill bucket + the decode program (budget
    # = one full decode block, so warmup traffic amortizes like the trace)
    buckets = sorted({bucket_length(n, 16) for n in lengths})
    for b in buckets:
        service.submit(np.ones(b, np.int32), max_new_tokens=decode_steps + 1)
    service.run()
    warm_compiles = service.watcher.compiles_total

    # staggered arrivals: a few requests join per step while earlier ones
    # are mid-decode — the continuous-batching path, not a static batch
    rids = []
    pending = list(zip(prompts, budgets))
    while pending or service.has_work:
        for _ in range(2):
            if pending:
                p, b = pending.pop(0)
                rids.append(service.submit(p, max_new_tokens=b))
        service.step()

    leg = f"decode_steps={decode_steps}"
    failures = []
    if service.recompile_events != 0:
        failures.append(
            f"[{leg}] {service.recompile_events} recompile event(s) after "
            f"warmup (warmup compiled {warm_compiles})"
        )
    for rid, p, b in zip(rids, prompts, budgets):
        want = np.asarray(model.generate(p[None], max_new_tokens=b))[0]
        got = service.results[rid].output_ids
        if not np.array_equal(got, want):
            failures.append(
                f"[{leg}] request {rid}: tokens diverge from generate()"
            )
    try:
        service.pool.check_no_leaks()
        if service.pool.free_blocks != service.pool.usable_blocks:
            failures.append(f"[{leg}] pool did not drain: blocks reserved")
    except AssertionError as exc:
        failures.append(f"[{leg}] {exc}")
    records = [r for r in hub.all_records() if r.get("kind") == "serving"]
    steps = [r for r in records if r.get("event") == "step"]
    completes = [r for r in records if r.get("event") == "complete"]
    if not steps or any("occupancy" not in r for r in steps):
        failures.append(f"[{leg}] no kind='serving' step records with occupancy")
    if len(completes) < len(rids) or any(
        r.get("ttft_ms") is None for r in completes
    ):
        failures.append(f"[{leg}] missing completion records with TTFT")
    # the device-resident loop's whole point: one host sync per n tokens
    # (ε absorbs overrun tokens discarded at stops)
    syncs = service.host_syncs_per_token
    if syncs > 1.0 / decode_steps + 0.05:
        failures.append(
            f"[{leg}] host_syncs_per_token {syncs:.3f} > "
            f"{1.0 / decode_steps:.3f} + 0.05 — the hot loop is syncing "
            "the host more than once per block"
        )
    n_done = len([r for r in rids if r in service.results])
    summary = (
        f"serving_smoke[{leg}]: {n_done}/{len(rids)} requests, "
        f"{service.stats['steps']} steps, mean occupancy "
        f"{service.mean_batch_occupancy:.2f}, {warm_compiles} warmup "
        f"compiles, {service.recompile_events} steady-state recompiles, "
        f"{syncs:.3f} host syncs/token, "
        f"{service.stats['h2d_uploads']} h2d uploads"
    )
    return failures, summary


def main() -> int:
    import accelerate_tpu.nn as nn
    from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
    from accelerate_tpu.telemetry import Telemetry
    from accelerate_tpu.utils.dataclasses import TelemetryKwargs

    nn.manual_seed(0)
    model = GPTLMHeadModel(GPTConfig.tiny())
    model.eval()

    failures = []
    for decode_steps in (1, 8):
        hub = Telemetry(TelemetryKwargs(enabled=True))
        leg_failures, summary = run_leg(model, hub, decode_steps)
        failures.extend(leg_failures)
        print(summary)

    for failure in failures:
        print(f"serving_smoke: FAIL: {failure}", file=sys.stderr)
    print(f"serving_smoke: {'FAILED' if failures else 'ok'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
