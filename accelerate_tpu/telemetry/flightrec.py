"""Black-box flight recorder (docs/telemetry.md §flight recorder).

A bounded, lock-cheap, per-process ring of structured events that is ON BY
DEFAULT — the one deliberate exception to the telemetry package's
default-off convention, because a recorder that must be switched on before
the hang is not a flight recorder.  Producers across the stack append
events the postmortem tooling needs to reconstruct *what the process was
doing when it stopped*:

* captured-step dispatch begin/end with the global step index
  (``capture.py``);
* a **collective-sequence counter** tick at every host collective —
  ``gather`` / ``gather_object`` / ``broadcast`` / ``reduce``
  (``utils/operations.py``) and every ``agree_*`` merge
  (``fleet/coordinate.py``) — the cross-rank alignment key
  ``tools/blackbox_report.py`` joins dumps on;
* stagewise 1F1B tick dispatch (``parallel/stagewise.py``);
* fleet vote / rendezvous / resize phases (``fleet/``);
* serving admissions and decode windows (``serving/``);
* checkpoint and AOT-store I/O (``checkpointing.py``, ``native/aot_cache.py``).

Each event is stamped with ``time.monotonic()`` and a per-process sequence
number; the rank is resolved lazily at dump time (recording must work
before — and during — distributed init).  The ring is a preallocated slot
list guarded by one tiny critical section per append (~100 ns uncontended,
far under the ≤1 % of ``step_ms`` budget the bench A/B row asserts); when
it wraps, the oldest events are overwritten and ``dropped`` counts them.

The recorder never issues a collective, never raises into the hot path, and
its dump (:meth:`FlightRecorder.dump`) writes a *per-rank* JSON file — this
module is declared rank-local-by-design to the graftlint taint pass
(``analysis/taint.py``), which in exchange asserts it contains no
collective sink.

Kill switch: ``ACCELERATE_FLIGHTREC=0`` turns recording into a no-op (the
bench A/B's "off" arm); ``ACCELERATE_FLIGHTREC_CAPACITY`` resizes the ring
(default 2048 events).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Optional

_DEFAULT_CAPACITY = 2048


def _env_capacity() -> int:
    raw = os.environ.get("ACCELERATE_FLIGHTREC_CAPACITY")
    if not raw:
        return _DEFAULT_CAPACITY
    try:
        return max(16, int(raw))
    except ValueError:
        return _DEFAULT_CAPACITY


def _env_enabled() -> bool:
    return os.environ.get("ACCELERATE_FLIGHTREC", "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


def resolve_rank() -> int:
    """Best-effort process rank, resolved at *dump* time only — jax may not
    be importable (or distributed-initialized) when events are recorded."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return int(os.environ.get("ACCELERATE_FLIGHTREC_RANK", "0") or 0)


class FlightRecorder:
    """The per-process event ring.  One module-level instance
    (:func:`recorder`) serves the whole process; constructing private
    instances is for tests."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY, enabled: bool = True):
        self.capacity = max(16, int(capacity))
        self.enabled = bool(enabled)
        self._slots: list = [None] * self.capacity
        self._n = 0  # events ever appended (monotone; ring head = n % cap)
        self._collective_seq = 0
        self._last_monotonic: Optional[float] = None
        self._lock = threading.Lock()
        # monotonic↔wall anchor: collective seqs align ranks *ordinally*;
        # the wall anchor lets tools place per-rank monotonic stamps on one
        # absolute timeline (outage_summary --blackbox join)
        self._anchor_wall = time.time()
        self._anchor_monotonic = time.monotonic()

    # -- producers (hot path) ------------------------------------------------
    @staticmethod
    def _shield_reserved(fields: dict, names: tuple) -> dict:
        """The ring owns the slot schema keys; a producer passing a payload
        dict through (``**payload``) must not collide with them — remap to a
        ``field_`` prefix instead of raising or silently clobbering."""
        for reserved in names:
            if reserved in fields:
                fields[f"field_{reserved}"] = fields.pop(reserved)
        return fields

    def record(self, kind: str, /, **fields) -> None:
        """Append one event.  Never raises; no-op when disabled."""
        if not self.enabled:
            return
        fields = self._shield_reserved(fields, ("kind", "seq", "t"))
        now = time.monotonic()
        with self._lock:
            self._slots[self._n % self.capacity] = (self._n, now, kind, fields)
            self._n += 1
            self._last_monotonic = now

    def note_collective(self, op: str, /, **fields) -> int:
        """Tick the collective-sequence counter and record the event.
        Returns the 1-based sequence number of THIS collective — the value
        every rank must agree on, and the join key the blackbox report
        aligns per-rank dumps with."""
        if not self.enabled:
            return self._collective_seq
        fields = self._shield_reserved(fields, ("kind", "seq", "t", "cseq", "op"))
        now = time.monotonic()
        with self._lock:
            self._collective_seq += 1
            seq = self._collective_seq
            fields["cseq"] = seq
            fields["op"] = op
            self._slots[self._n % self.capacity] = (self._n, now, "collective", fields)
            self._n += 1
            self._last_monotonic = now
        return seq

    # -- consumers -----------------------------------------------------------
    @property
    def collective_seq(self) -> int:
        return self._collective_seq

    @property
    def events_total(self) -> int:
        return self._n

    @property
    def depth(self) -> int:
        return min(self._n, self.capacity)

    @property
    def dropped(self) -> int:
        return max(0, self._n - self.capacity)

    def seconds_since_last_event(self) -> Optional[float]:
        last = self._last_monotonic
        if last is None:
            return None
        return max(0.0, time.monotonic() - last)

    def health(self) -> dict:
        """Recorder self-diagnostics for the Prometheus endpoint
        (telemetry/metrics.py): ring depth, drop count, staleness."""
        age = self.seconds_since_last_event()
        return {
            "depth": self.depth,
            "capacity": self.capacity,
            "events_total": self._n,
            "dropped_total": self.dropped,
            "collective_seq": self._collective_seq,
            "last_event_age_seconds": round(age, 3) if age is not None else None,
        }

    def snapshot(self) -> list[dict]:
        """Retained events, oldest first, as dicts — safe to call from the
        watchdog thread while producers keep appending."""
        with self._lock:
            n, cap = self._n, self.capacity
            slots = list(self._slots)
        start = max(0, n - cap)
        out = []
        for i in range(start, n):
            slot = slots[i % cap]
            if slot is None:
                continue
            seq, t, kind, fields = slot
            event = {"seq": seq, "t": round(t, 6), "kind": kind}
            if fields:
                event.update(fields)
            out.append(event)
        return out

    def to_dict(self, reason: str = "manual") -> dict:
        """The full per-rank dump payload (watchdog stall, fatal signal,
        atexit, or an explicit tool call)."""
        now_wall, now_mono = time.time(), time.monotonic()
        return {
            "kind": "blackbox",
            "reason": reason,
            "rank": resolve_rank(),
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "time_unix": round(now_wall, 3),
            "monotonic": round(now_mono, 6),
            # wall = monotonic + (anchor_wall - anchor_monotonic): lets the
            # postmortem place every event on the absolute timeline
            "anchor_wall": round(self._anchor_wall, 3),
            "anchor_monotonic": round(self._anchor_monotonic, 6),
            "collective_seq": self._collective_seq,
            "events_total": self._n,
            "dropped": self.dropped,
            "events": self.snapshot(),
        }

    def dump(self, dir_or_path: str, reason: str = "manual",
             extra: Optional[dict] = None) -> Optional[str]:
        """Write the per-rank JSON dump.  ``dir_or_path`` naming a directory
        (or ending in a separator) gets the canonical ``blackbox_rank{N}.json``
        filename appended.  Fail-soft: returns the path, or ``None`` on any
        I/O error — a postmortem writer must never crash the job it is
        documenting."""
        try:
            payload = self.to_dict(reason=reason)
            if extra:
                payload.update(extra)
            path = dir_or_path
            if path.endswith(os.sep) or os.path.isdir(path) or not path.endswith(".json"):
                os.makedirs(path, exist_ok=True)
                path = os.path.join(path, f"blackbox_rank{payload['rank']}.json")
            else:
                parent = os.path.dirname(path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
            return path
        except Exception:
            return None


# the process-wide recorder: constructed eagerly (a few KB) so the very
# first event — backend init, distributed rendezvous — is never lost
_RECORDER = FlightRecorder(capacity=_env_capacity(), enabled=_env_enabled())


def recorder() -> FlightRecorder:
    return _RECORDER


def record(kind: str, /, **fields) -> None:
    """Module-level shortcut for producers: ``flightrec.record(...)``."""
    _RECORDER.record(kind, **fields)


def note_collective(op: str, /, **fields) -> int:
    return _RECORDER.note_collective(op, **fields)
