"""Fault-tolerant serving (docs/serving.md §fault tolerance).

The acceptance contract (ISSUE 20): with a journal armed, a replica that
dies mid-decode — transient dispatch fault, SIGTERM preemption, or plain
crash — is replaced by a fresh replica whose recovered continuations are
BITWISE identical to the uninterrupted run, greedy and sampled alike,
under quantized weights, with zero requests lost.  With the journal off
(the default) the hot path is byte-identical to the pre-recovery service
and none of the new config reaches the AOT service fingerprint.
"""

import json
import os
import signal

import numpy as np
import pytest

import accelerate_tpu.nn as nn
from accelerate_tpu.models import GPTConfig, GPTLMHeadModel
from accelerate_tpu.serving import (
    DecodeService,
    QueueFullError,
    RequestJournal,
    ServingConfig,
    replay_journal,
)
from accelerate_tpu.serving.recovery import advance_rng  # noqa: F401 (API pin)


@pytest.fixture(scope="module")
def tiny_model():
    nn.manual_seed(0)
    model = GPTLMHeadModel(GPTConfig.tiny())
    model.eval()
    return model


@pytest.fixture(autouse=True)
def _restore_sigterm():
    """Journal-armed services install a PreemptionGuard SIGTERM handler;
    give every test a clean slate and never leak one into the runner."""
    saved = signal.getsignal(signal.SIGTERM)
    yield
    signal.signal(signal.SIGTERM, saved)


def _prompts(lengths, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,), dtype=np.int32) for n in lengths]


_LENGTHS = [5, 11, 17]
_BUDGETS = [8, 6, 10]


def _cfg(**kw):
    base = dict(max_slots=4, block_size=16, prompt_bucket=16)
    base.update(kw)
    return ServingConfig(**base)


def _run_all(service, prompts=None, budgets=None):
    """Submit (optional) + drive to completion; returns {rid: tokens}."""
    rids = []
    if prompts is not None:
        for p, b in zip(prompts, budgets):
            rids.append(service.submit(p, max_new_tokens=b))
    while service.has_work and not service.draining:
        service.step()
    return rids


def _outputs(service):
    return {rid: list(req.output_ids) for rid, req in service.results.items()
            if req.state == "done"}


# ---------------------------------------------------------------------------
# the request journal: WAL roundtrip, idempotent replay, bounded compaction
# ---------------------------------------------------------------------------

def test_journal_roundtrip(tmp_path):
    meta = {"temperature": 0.0, "rng_seed": 7}
    j = RequestJournal(str(tmp_path), meta=meta)
    j.log_submit(0, np.array([1, 2, 3], np.int32), 4, None)
    j.log_submit(1, np.array([9], np.int32), 2, 50)
    j.log_tokens(0, [10, 11])
    j.log_tokens(0, [12])
    j.log_tokens(1, [20])
    j.log_complete(1)
    j.close()

    state = replay_journal(str(tmp_path))
    assert state.meta["temperature"] == 0.0 and state.meta["rng_seed"] == 7
    assert not state.drained
    assert sorted(state.entries) == [0, 1]
    assert state.entries[0].tokens == [10, 11, 12]
    assert state.entries[0].open
    assert state.entries[1].done and not state.entries[1].open
    assert state.entries[1].eos_token_id == 50
    np.testing.assert_array_equal(state.entries[0].prompt, [1, 2, 3])
    # only the incomplete request is resumable, FIFO by rid
    assert [e.rid for e in state.open_requests] == [0]


def test_journal_replay_is_idempotent_and_tolerates_torn_tail(tmp_path):
    j = RequestJournal(str(tmp_path))
    j.log_submit(0, np.array([1, 2], np.int32), 6, None)
    j.log_tokens(0, [5, 6, 7])
    j.close()
    path = j.path
    # duplicate append at an already-applied offset (a crashed writer's
    # re-log): absolute `at` offsets make replay idempotent
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(
            {"ev": "tok", "rid": 0, "at": 1, "toks": [6, 7]}) + "\n")
        # an out-of-range offset (lost intermediate record) is skipped,
        # never applied with a gap
        f.write(json.dumps(
            {"ev": "tok", "rid": 0, "at": 9, "toks": [99]}) + "\n")
        # torn trailing line from a crash mid-write: dropped, not fatal
        f.write('{"ev": "tok", "rid": 0, "at"')
    state = replay_journal(path)
    assert state.entries[0].tokens == [5, 6, 7]


def test_journal_compaction_bounds_the_file(tmp_path):
    j = RequestJournal(str(tmp_path), compact_every=8)
    done_prompt = np.array([1], np.int32)
    j.log_submit(0, done_prompt, 64, None)
    j.log_submit(1, np.array([2, 3], np.int32), 4, None)
    for i in range(40):  # way past compact_every: forces rewrites
        j.log_tokens(0, [i])
    j.log_complete(0)
    j.log_tokens(1, [7])
    j.close()
    with open(j.path, encoding="utf-8") as f:
        lines = [json.loads(l) for l in f if l.strip()]
    # compaction rewrote the log down to meta + live state: far fewer
    # records than the 44+ appends, and the finished request is gone
    assert len(lines) < 20
    assert not any(r.get("rid") == 0 and r["ev"] == "submit" for r in lines)
    state = replay_journal(j.path)
    assert [e.rid for e in state.open_requests] == [1]
    assert state.entries[1].tokens == [7]


def test_journal_dir_env_arms_config(tmp_path, monkeypatch):
    monkeypatch.setenv("ACCELERATE_SERVING_JOURNAL", str(tmp_path))
    assert _cfg().journal_dir == str(tmp_path)
    monkeypatch.delenv("ACCELERATE_SERVING_JOURNAL")
    assert _cfg().journal_dir is None


# ---------------------------------------------------------------------------
# deterministic recovery: re-prefill == uninterrupted, bitwise
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("temperature", [0.0, 0.8])
@pytest.mark.parametrize("interrupt_after", [1, 2, 4])
def test_recovery_bitwise_parity(tiny_model, tmp_path, temperature,
                                 interrupt_after):
    """Kill a journaled replica after N engine steps; a fresh replica
    resumed from the journal finishes every request with tokens bitwise
    equal to an uninterrupted run — greedy AND sampled (the per-slot RNG
    stream is re-advanced through the emitted prefix)."""
    prompts = _prompts(_LENGTHS)

    ref = DecodeService(tiny_model, _cfg(temperature=temperature))
    _run_all(ref, prompts, _BUDGETS)
    want = _outputs(ref)

    jdir = str(tmp_path / "j")
    a = DecodeService(
        tiny_model, _cfg(temperature=temperature, journal_dir=jdir)
    )
    for p, b in zip(prompts, _BUDGETS):
        a.submit(p, max_new_tokens=b)
    for _ in range(interrupt_after):
        a.step()
    del a  # crash: no drain, no close — replay must cope with the raw WAL

    b_svc = DecodeService(
        tiny_model, _cfg(temperature=temperature, journal_dir=jdir)
    )
    resumed = b_svc.resume_from_journal()
    _run_all(b_svc)
    got = _outputs(b_svc)
    assert set(resumed) <= set(want)
    # zero lost: every journaled-open request completed on the new replica
    assert sorted(got) == sorted(resumed)
    for rid in got:
        np.testing.assert_array_equal(
            got[rid], want[rid],
            err_msg=f"rid {rid} diverged after recovery "
                    f"(T={temperature}, interrupted@{interrupt_after})",
        )
    assert b_svc.stats["recovered"] == len(resumed)


def test_recovery_parity_quantized(tiny_model, tmp_path):
    """Recovery composes with int8 weight quantization: the recovered
    continuation re-prefills through the SAME quantized program family."""
    prompts = _prompts(_LENGTHS)
    cfg = dict(temperature=0.0, quantize_weights=8)
    ref = DecodeService(tiny_model, _cfg(**cfg))
    _run_all(ref, prompts, _BUDGETS)
    want = _outputs(ref)

    jdir = str(tmp_path / "j")
    a = DecodeService(tiny_model, _cfg(journal_dir=jdir, **cfg))
    for p, b in zip(prompts, _BUDGETS):
        a.submit(p, max_new_tokens=b)
    a.step()
    a.step()
    del a

    b_svc = DecodeService(tiny_model, _cfg(journal_dir=jdir, **cfg))
    resumed = b_svc.resume_from_journal()
    assert resumed
    _run_all(b_svc)
    got = _outputs(b_svc)
    for rid in got:
        np.testing.assert_array_equal(got[rid], want[rid])


def test_resume_rejects_mismatched_sampling_config(tiny_model, tmp_path):
    jdir = str(tmp_path / "j")
    a = DecodeService(tiny_model, _cfg(temperature=0.8, journal_dir=jdir))
    a.submit(_prompts([5])[0], max_new_tokens=4)
    a.step()
    del a
    b_svc = DecodeService(tiny_model, _cfg(temperature=0.0, journal_dir=jdir))
    with pytest.raises(ValueError, match="temperature"):
        b_svc.resume_from_journal()


# ---------------------------------------------------------------------------
# decode-step retry: transient faults never recompile; exhaustion requeues
# ---------------------------------------------------------------------------

def test_decode_retry_reuses_compiled_program(tiny_model, monkeypatch):
    """One injected transient decode fault: retried against the same
    compiled program (zero extra compiles), tokens unchanged."""
    prompts = _prompts(_LENGTHS)
    ref = DecodeService(tiny_model, _cfg())
    _run_all(ref, prompts, _BUDGETS)
    want = _outputs(ref)

    monkeypatch.setenv("ACCELERATE_FAULT_PLAN", "decode_fault:step=1,times=1")
    svc = DecodeService(tiny_model, _cfg(retry_backoff_s=0.001))
    _run_all(svc, prompts, _BUDGETS)
    got = _outputs(svc)
    assert svc.stats["decode_retries"] == 1
    assert svc.stats["requeued"] == 0
    assert svc.recompile_events == 0
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid])
    m = svc.metrics()
    assert m["decode_retries_total"] == 1 and m["requeued_total"] == 0


def test_retry_exhaustion_evicts_and_requeues(tiny_model, monkeypatch):
    """A fault that outlives the retry budget evicts the batch and requeues
    every in-flight request; re-prefill recovery still lands bitwise parity."""
    prompts = _prompts(_LENGTHS)
    ref = DecodeService(tiny_model, _cfg())
    _run_all(ref, prompts, _BUDGETS)
    want = _outputs(ref)

    monkeypatch.setenv("ACCELERATE_FAULT_PLAN", "decode_fault:step=1,times=5")
    svc = DecodeService(
        tiny_model, _cfg(max_decode_retries=2, retry_backoff_s=0.001)
    )
    _run_all(svc, prompts, _BUDGETS)
    got = _outputs(svc)
    assert svc.stats["decode_retries"] == 2  # budget spent...
    assert svc.stats["requeued"] > 0  # ...then the batch was requeued
    assert svc.stats["recovered"] > 0  # ...and re-admitted via re-prefill
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid])


def test_non_transient_fault_raises(tiny_model, monkeypatch):
    svc = DecodeService(tiny_model, _cfg())
    svc.submit(_prompts([5])[0], max_new_tokens=4)

    def _boom(*a, **k):
        raise ValueError("shape mismatch: not retryable")

    monkeypatch.setattr("accelerate_tpu.serving.engine.run_decode", _boom)
    monkeypatch.setattr("accelerate_tpu.serving.engine.run_decode_n", _boom)
    with pytest.raises(ValueError, match="not retryable"):
        while svc.has_work:
            svc.step()


# ---------------------------------------------------------------------------
# preemption drain + resume
# ---------------------------------------------------------------------------

def test_sigterm_drains_and_fresh_replica_resumes(tiny_model, tmp_path,
                                                  monkeypatch):
    """Injected SIGTERM mid-decode: the guard's sticky flag drains the
    service (journal finalized, open rids reported); a fresh replica on the
    same journal completes every request, bitwise equal, zero lost."""
    prompts = _prompts(_LENGTHS)
    ref = DecodeService(tiny_model, _cfg())
    _run_all(ref, prompts, _BUDGETS)
    want = _outputs(ref)

    jdir = str(tmp_path / "j")
    monkeypatch.setenv("ACCELERATE_FAULT_PLAN", "serving_sigterm:step=2")
    a = DecodeService(tiny_model, _cfg(journal_dir=jdir))
    for p, b in zip(prompts, _BUDGETS):
        a.submit(p, max_new_tokens=b)
    a.run(max_steps=50)
    assert a.draining
    finished_on_a = _outputs(a)
    open_rids = a.drain()  # idempotent; returns the still-open rids
    assert open_rids and set(open_rids).isdisjoint(finished_on_a)
    state = replay_journal(jdir)
    assert state.drained
    assert [e.rid for e in state.open_requests] == open_rids

    monkeypatch.delenv("ACCELERATE_FAULT_PLAN")
    b_svc = DecodeService(tiny_model, _cfg(journal_dir=jdir))
    resumed = b_svc.resume_from_journal()
    assert resumed == open_rids
    _run_all(b_svc)
    got = _outputs(b_svc)
    # zero lost: A's completions + B's recoveries cover every submission
    assert sorted(list(finished_on_a) + list(got)) == sorted(want)
    for rid in got:
        np.testing.assert_array_equal(got[rid], want[rid])


def test_drain_stops_admission(tiny_model):
    svc = DecodeService(tiny_model, _cfg())
    svc.drain(reason="test")
    assert svc.draining
    with pytest.raises(QueueFullError, match="draining"):
        svc.submit(_prompts([5])[0], max_new_tokens=4)
    assert svc.step() == []  # draining step is a no-op, never dispatches


# ---------------------------------------------------------------------------
# deadline shedding + bounded queueing
# ---------------------------------------------------------------------------

def test_deadline_shed_at_admission(tiny_model):
    import time

    svc = DecodeService(tiny_model, _cfg())
    # backdate arrival a full second; a 100ms deadline is long dead
    rid = svc.submit(
        _prompts([5])[0], max_new_tokens=4,
        arrival_t=time.perf_counter() - 1.0, deadline_ms=100.0,
    )
    svc.step()
    req = svc.results[rid]
    assert req.state == "shed"
    assert len(req.tokens) == 0  # shed requests are never prefilled
    assert svc.stats["shed"] == 1
    assert svc.metrics()["shed_total"] == 1


def test_queue_depth_bound_rejects_with_retry_after(tiny_model):
    svc = DecodeService(tiny_model, _cfg(max_queue_depth=1))
    svc.submit(_prompts([5])[0], max_new_tokens=4)
    with pytest.raises(QueueFullError) as exc_info:
        svc.submit(_prompts([5])[0], max_new_tokens=4)
    assert exc_info.value.retry_after_ms > 0
    assert svc.stats["shed"] == 1
    _run_all(svc)  # the admitted request still completes normally
    assert svc.metrics()["completed_total"] == 1


# ---------------------------------------------------------------------------
# default-off byte-identity + fingerprint invariance
# ---------------------------------------------------------------------------

def test_journal_off_is_byte_identical_and_on_changes_tokens_nothing(
        tiny_model, tmp_path):
    """The recovery machinery is default-off dead code: journal-off output
    equals the pre-recovery service, and journal-ON output equals
    journal-off output (the WAL observes the hot path, never perturbs it)."""
    prompts = _prompts(_LENGTHS)
    off = DecodeService(tiny_model, _cfg(temperature=0.8))
    _run_all(off, prompts, _BUDGETS)
    on = DecodeService(
        tiny_model, _cfg(temperature=0.8, journal_dir=str(tmp_path / "j"))
    )
    _run_all(on, prompts, _BUDGETS)
    want, got = _outputs(off), _outputs(on)
    for rid in want:
        np.testing.assert_array_equal(got[rid], want[rid])
    assert off._journal is None and off._guard is None
    assert on.recompile_events == 0


def test_recovery_config_stays_off_aot_fingerprint(tiny_model, tmp_path):
    """None of journal_dir/max_queue_depth/max_decode_retries reach the AOT
    service fingerprint: a warm store serves journaled and journal-less
    replicas alike (no cold compiles on the recovered replica)."""
    from accelerate_tpu import CompilationCacheKwargs
    from accelerate_tpu.native.aot_cache import AOTCompilationCache

    cache = AOTCompilationCache(
        CompilationCacheKwargs(cache_dir=str(tmp_path / "aot"))
    )
    plain = DecodeService(tiny_model, _cfg(), aot_cache=cache)
    journaled = DecodeService(
        tiny_model,
        _cfg(journal_dir=str(tmp_path / "j"), max_queue_depth=8,
             max_decode_retries=5),
        aot_cache=cache,
    )
    assert plain._aot is not None and journaled._aot is not None
    assert plain._aot.service_digest == journaled._aot.service_digest


# ---------------------------------------------------------------------------
# observability: /healthz, serving_recovery telemetry, bounded metrics retry
# ---------------------------------------------------------------------------

def _get(url):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_healthz_readiness_tracks_service_lifecycle(tiny_model):
    """/healthz: 503 before programs warm, 200 while serving, 503 once
    draining — ready = programs warmed ∧ pool allocated ∧ not draining."""
    from accelerate_tpu import TelemetryKwargs
    from accelerate_tpu.telemetry import Telemetry

    hub = Telemetry(TelemetryKwargs(enabled=True))
    svc = DecodeService(tiny_model, _cfg(), telemetry=hub)
    server = hub.serve_metrics(port=0)
    try:
        url = f"http://127.0.0.1:{server.port}/healthz"
        code, body = _get(url)
        assert code == 503 and body["live"] and not body["ready"]
        assert not body["services"]["serving"]["programs_warmed"]

        _run_all(svc, _prompts([5]), [4])
        code, body = _get(url)
        assert code == 200 and body["ready"]
        assert body["services"]["serving"]["programs_warmed"]

        svc.drain(reason="test")
        code, body = _get(url)
        assert code == 503 and not body["ready"]
        assert body["services"]["serving"]["draining"]
        events = [r for r in hub.all_records()
                  if r.get("kind") == "serving_recovery"]
        assert any(e.get("event") == "drain" for e in events)
    finally:
        hub.close_metrics()


def test_metrics_snapshot_retry_is_bounded(tiny_model):
    """A completion stream hot enough to defeat every snapshot attempt must
    not spin the scrape: the cap trips, the counter + flight event land, and
    the scrape returns percentile-less but complete."""

    class _AlwaysMutating:
        def __iter__(self):
            raise RuntimeError("deque mutated during iteration")

    svc = DecodeService(tiny_model, _cfg())
    svc._latency_window = _AlwaysMutating()
    m = svc.metrics()
    assert m["latency_window"] == 0
    assert "ttft_ms_p50" not in m
    assert m["metrics_snapshot_retry_exhausted_total"] == 1
    svc.metrics()
    assert svc.stats["metrics_snapshot_retry_exhausted"] == 2
