"""Per-module call graph + traced-region reachability.

A function is a *trace root* when it is handed to a tracing transform —
decorated with ``jax.jit`` / ``partial(jax.jit, ...)``, or passed by name to
``jax.jit`` / ``shard_map`` / ``shard_map_compat`` / ``pl.pallas_call`` /
``lax.scan``-family / ``accelerator.compile_step``.  Everything reachable
from a root through same-module calls (including functions passed as
callbacks and ``self.method()`` dispatch) executes under trace, so the
trace-safety rules (host-sync, blocking) only fire inside that region.

This module is the *per-file* half of the analysis: it collects functions,
call edges (bare names, ``self.method``, and dotted ``alias.fn`` forms) and
local roots.  ``program.py`` stitches the per-module graphs into a
whole-program one — resolving ``from .x import f`` / ``import pkg.mod as m``
edges and ``__init__.py`` re-exports — and injects the extra cross-module
reachability back into each module's ``reached`` map before rules run.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Optional

# Leaves that are tracing transforms regardless of prefix (project- or
# jax-specific spellings that never collide with stdlib/user names).
_WRAPPER_LEAVES = {
    "jit",
    "pjit",
    "pmap",
    "shard_map",
    "shard_map_compat",
    "pallas_call",
    "compile_step",
    "CapturedStep",
    "remat",
    "xmap",
}
# Generic leaves that only count when the dotted path shows they come from
# jax (``lax.scan`` yes, ``self.scan`` no).
_JAX_ONLY_LEAVES = {
    "scan",
    "fori_loop",
    "while_loop",
    "cond",
    "switch",
    "associative_scan",
    "map",
    "vmap",
    "grad",
    "value_and_grad",
    "vjp",
    "jvp",
    "linearize",
    "checkpoint",
    "custom_vjp",
    "custom_jvp",
    "eval_shape",
    "make_jaxpr",
}


def is_trace_wrapper(resolved: Optional[str]) -> bool:
    if not resolved:
        return False
    parts = resolved.split(".")
    leaf = parts[-1]
    if leaf in _WRAPPER_LEAVES:
        return True
    if leaf in _JAX_ONLY_LEAVES:
        return "jax" in parts or parts[0] in ("lax", "pl", "pallas")
    return False


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.f`` for an Attribute chain bottoming at a Name, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_own_nodes(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested def/class bodies
    (those are their own call-graph nodes, reached through edges)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            # still surface the nested def's decorators/defaults — they
            # evaluate in the enclosing scope
            stack.extend(node.decorator_list)
            if not isinstance(node, ast.ClassDef):
                stack.extend(node.args.defaults + [d for d in node.args.kw_defaults if d])
            continue
        stack.extend(ast.iter_child_nodes(node))


@dataclasses.dataclass
class FunctionInfo:
    name: str
    qualname: str
    node: ast.AST
    edges: set[str] = dataclasses.field(default_factory=set)
    # borg-singleton initializer (`self.__dict__ = cls._shared_state`): its
    # body runs once per process, so constructing the class under trace does
    # NOT execute it — reachability must not propagate through it
    barrier: bool = False


def factory_returned_classes(tree: ast.AST) -> dict[str, str]:
    """``{factory function name: constructed class name}`` for every
    MODULE-LEVEL function whose returns are ALL ``SomeClass(...)`` calls of
    the SAME constructor — the receiver-type source behind factory-return
    dispatch inference (``obj = make_runner(); obj.work(x)`` →
    ``Runner.work``).

    Deliberately strict, mirroring the join-over-branches rule for direct
    constructor rebinds: one ``return`` of anything else (a bare value, a
    different constructor, ``self``/``cls``/parameter-rooted calls), or no
    return at all, leaves the function out — and two same-named functions
    that disagree on the class knock the name out entirely (the caller
    resolves factories by bare name, and a wrong guess would cross-wire
    reachability).  Only top-level defs qualify: a METHOD's bare name is
    never callable as ``name()``, and a nested def's name is only live
    inside its enclosing function — mapping either through a module-global
    table would wire edges for unrelated same-named callables (e.g. an
    injected callback parameter).  Async defs are excluded too: a bare
    call of an async factory binds a COROUTINE, not the constructed class
    (and the awaited form is an ``ast.Await``, which never consults the
    map anyway).  Decorated defs are excluded: the wrapper decides what a
    call returns (a future, a memo proxy), not the body's ``return``.
    And a name REBOUND at module level — a later same-named def that does
    not itself qualify with the same class, or any plain assignment — is
    knocked out entirely: the live binding is whatever ran last, and a
    stale mapping would be wrong, not conservative.  Same-module
    factory→factory delegation CHAINS resolve (v12): a delegating factory
    records the inner factory's name, and a cycle-guarded post-pass chases
    the map until it grounds (``make_a`` → ``make_b`` → ``Runner``).  A
    chain whose last link is not in the map (an imported factory, a
    knocked-out name) keeps that link as its ctor — program.py chases the
    cross-module half — and a delegation cycle drops its members entirely
    (no ground class exists)."""
    factories: dict[str, str] = {}
    knocked_out: set[str] = set()
    for node in getattr(tree, "body", []):
        name = None
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            name = node.name
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            # module-level rebind of the name shadows any earlier def
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    knocked_out.add(t.id)
            continue
        if name is None:
            continue
        qualifies = False
        ctor = None
        if isinstance(node, ast.FunctionDef) and not node.decorator_list:
            params = {
                a.arg for a in ast.walk(node.args) if isinstance(a, ast.arg)
            }
            returns = [
                sub for sub in iter_own_nodes(node)
                if isinstance(sub, ast.Return)
            ]
            ctors: set[str] = set()
            for ret in returns:
                c = None
                if isinstance(ret.value, ast.Call):
                    fn = ret.value.func
                    c = fn.id if isinstance(fn, ast.Name) else dotted_name(fn)
                if (
                    c is None
                    or c.split(".", 1)[0] in ("self", "cls")
                    or c.split(".", 1)[0] in params
                ):
                    ctors.clear()
                    break
                ctors.add(c)
            if len(ctors) == 1:
                qualifies = True
                ctor = ctors.pop()
        if not qualifies:
            # a non-factory def AFTER a qualifying one is the live binding
            # — the stale mapping must go.  (A non-factory def BEFORE a
            # qualifying one is simply shadowed by it: keep the later.)
            if name in factories:
                knocked_out.add(name)
            continue
        if factories.setdefault(name, ctor) != ctor:
            knocked_out.add(name)
    for name in knocked_out:
        factories.pop(name, None)
    # chase same-module delegation chains to their ground (cycle-guarded)
    resolved: dict[str, str] = {}
    for name in factories:
        seen: set[str] = set()
        tgt = name
        while tgt in factories and tgt not in seen:
            seen.add(tgt)
            tgt = factories[tgt]
        if tgt in seen:
            continue  # delegation cycle: no ground class, drop the chain
        resolved[name] = tgt
    return resolved


def _is_singleton_init(fn_node: ast.AST) -> bool:
    for sub in iter_own_nodes(fn_node):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "__dict__"
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    return True
    return False


class _Collector(ast.NodeVisitor):
    def __init__(self, factories: Optional[dict[str, str]] = None):
        self.stack: list[str] = []
        self.functions: list[FunctionInfo] = []
        # same-module factory functions (factory_returned_classes): a
        # receiver bound from `make_runner()` dispatches as the class every
        # return of make_runner constructs
        self.factories: dict[str, str] = factories or {}
        # qualnames of actual ClassDefs: instance-dispatch edges resolve
        # only through these — a factory FUNCTION with a nested def also
        # owns `outer.inner` qualnames, and treating it as a class would
        # wire phantom method edges into the nested function
        self.classes: set[str] = set()

    def _visit_fn(self, node):
        qual = ".".join(self.stack + [node.name])
        info = FunctionInfo(node.name, qual, node, barrier=_is_singleton_init(node))
        # names bound as data in this scope (params, assignments, loop vars):
        # a data binding passed as an argument is a value, not a reference to
        # a same-named module function — without this, a parameter named like
        # a method creates phantom edges
        params = {a.arg for a in ast.walk(node.args) if isinstance(a, ast.arg)}
        store_counts: dict[str, int] = {}
        for sub in iter_own_nodes(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, (ast.Store, ast.Del)):
                store_counts[sub.id] = store_counts.get(sub.id, 0) + 1
        local_data = params | set(store_counts)
        # cheap type inference over locals bound to constructor calls:
        # `obj = Ctor(...)` pins obj's type to Ctor for the whole function —
        # then `obj.method(x)` dispatches to ``Ctor.method`` (resolved by
        # qualname same-module, through the class's import in program.py).
        # Join-over-branches: a receiver rebound across branches counts too,
        # as long as EVERY binding of the name is a call of the SAME
        # constructor (`obj = Cls() if fast else Cls(opts)`) — the join of
        # identical types is that type.  Any other binding shape (a
        # parameter, a different ctor, a non-call assignment, a loop/del
        # rebind) leaves the receiver uninferred: its type is not knowable,
        # and a wrong guess would cross-wire reachability.
        ctor_assigns: dict[str, list[str]] = {}
        for sub in iter_own_nodes(node):
            if (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)
                and isinstance(sub.value, ast.Call)
            ):
                target = sub.targets[0].id
                fn = sub.value.func
                ctor = fn.id if isinstance(fn, ast.Name) else dotted_name(fn)
                if ctor and ctor.split(".", 1)[0] not in ("self", "cls"):
                    # factory-return inference (v10): a bare-name call of a
                    # same-module factory binds the CLASS the factory
                    # constructs, so it joins over branches with direct
                    # constructor binds (`r = Runner() if fast else
                    # make_runner()` is still Runner).  A locally-bound
                    # name (parameter, assignment) is DATA shadowing the
                    # module function — any callable could be injected, so
                    # the factory map must not apply (same guard the plain
                    # call edges use)
                    if (
                        isinstance(fn, ast.Name)
                        and ctor in self.factories
                        and ctor not in local_data
                    ):
                        ctor = self.factories[ctor]
                    elif isinstance(fn, ast.Name) and ctor in local_data:
                        # v11: bare-name ctor shadowed by local data — with
                        # factory maps now resolving through IMPORTS
                        # (program.py), an unresolved name edge could later
                        # mis-bind to an imported factory/class the local
                        # binding actually shadows; record nothing so the
                        # receiver stays uninferred
                        ctor = None
                    if ctor is not None:
                        ctor_assigns.setdefault(target, []).append(ctor)
        ctor_of: dict[str, str] = {}
        for target, ctors in ctor_assigns.items():
            if target in params:
                continue
            # every Store/Del of the name must be one of these ctor calls
            # (a non-call rebind wouldn't appear in ctor_assigns and makes
            # the counts disagree), and they must all name the same class
            if store_counts.get(target) == len(ctors) and len(set(ctors)) == 1:
                ctor_of[target] = ctors[0]
        for sub in iter_own_nodes(node):
            if isinstance(sub, ast.Call):
                # direct calls: f(...), self.f(...) / cls.f(...), and dotted
                # alias.f(...) — the dotted form is what program.py resolves
                # across module boundaries (``utils.sync(x)``)
                fn = sub.func
                if isinstance(fn, ast.Name):
                    info.edges.add(fn.id)
                elif isinstance(fn, ast.Attribute):
                    d = dotted_name(fn)
                    if d is None:
                        pass
                    elif isinstance(fn.value, ast.Name) and fn.value.id in ("self", "cls"):
                        info.edges.add(fn.attr)
                    elif isinstance(fn.value, ast.Name) and fn.value.id in ctor_of:
                        # inferred instance dispatch: obj = Ctor(); obj.m(x)
                        info.edges.add(f"{ctor_of[fn.value.id]}.{fn.attr}")
                    elif d.split(".", 1)[0] in ("self", "cls"):
                        # deeper chains (self.state.update()): the receiver's
                        # type is unknown — a bare-leaf edge would collide
                        # with any same-module function named `update`
                        pass
                    elif d.split(".", 1)[0] not in local_data:
                        info.edges.add(d)
                # callback pattern: names passed as arguments may be called
                # by the callee (ring hops, pipeline schedules do this).
                # Nested defs are not Store bindings, so they stay eligible.
                for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                    if isinstance(arg, ast.Name) and arg.id not in local_data:
                        info.edges.add(arg.id)
        self.functions.append(info)
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_ClassDef(self, node):
        self.classes.add(".".join(self.stack + [node.name]))
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()


class CallGraph:
    def __init__(self, module):
        self.module = module
        collector = _Collector(factories=factory_returned_classes(module.tree))
        collector.visit(module.tree)
        self.functions: dict[str, FunctionInfo] = {
            f.qualname: f for f in collector.functions
        }
        self.classes: set[str] = set(collector.classes)
        # exported for the program graph: other modules importing one of
        # these factories resolve their receivers through it (v11)
        self.factories: dict[str, str] = dict(collector.factories)
        self.by_leaf: dict[str, list[FunctionInfo]] = {}
        for f in collector.functions:
            self.by_leaf.setdefault(f.name, []).append(f)
        # reached: qualname -> human-readable reason ("root ..." / "via ...")
        self.reached: dict[str, str] = {}
        self._find_roots()
        self._propagate()

    # -- roots --------------------------------------------------------------
    def _mark(self, info: FunctionInfo, reason: str) -> None:
        self.reached.setdefault(info.qualname, reason)

    def _find_roots(self) -> None:
        mod = self.module
        for info in self.functions.values():
            for dec in getattr(info.node, "decorator_list", []):
                target = dec.func if isinstance(dec, ast.Call) else dec
                resolved = mod.resolve(target)
                if is_trace_wrapper(resolved):
                    self._mark(info, f"decorated with {resolved}")
                elif (
                    isinstance(dec, ast.Call)
                    and resolved
                    and resolved.rsplit(".", 1)[-1] == "partial"
                ):
                    for a in dec.args:
                        wr = mod.resolve(a)
                        if is_trace_wrapper(wr):
                            self._mark(info, f"decorated with partial({wr}, ...)")
        # call-form: jax.jit(f, ...), shard_map_compat(f, ...), lax.scan(f, ...)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func)
            if not is_trace_wrapper(resolved):
                continue
            # walk the whole argument expressions, not just bare Names: the
            # `shard_map_compat(partial(local_fn, ...), ...)` idiom buries the
            # traced function one call deep
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        for info in self.by_leaf.get(sub.id, []):
                            self._mark(info, f"passed to {resolved}")

    # -- reachability -------------------------------------------------------
    def _propagate(self) -> None:
        frontier = list(self.reached)
        while frontier:
            qual = frontier.pop()
            info = self.functions[qual]
            for name in info.edges:
                callees = self.by_leaf.get(name, [])
                if not callees and "." in name:
                    # instance-dispatch edge (``Cls.method``): same-module
                    # resolution is an exact qualname lookup, restricted to
                    # REAL classes — a factory function's nested defs share
                    # the qualname shape but are not dispatch targets;
                    # imported-class forms resolve in program.py
                    target = self.functions.get(name)
                    if target is not None and name.rsplit(".", 1)[0] in self.classes:
                        callees = [target]
                for callee in callees:
                    if callee.barrier:
                        continue  # singleton init: runs once, never in-trace
                    if callee.qualname not in self.reached:
                        root = self.reached[qual].split(" via ")[0]
                        self.reached[callee.qualname] = f"{root} via {qual}"
                        frontier.append(callee.qualname)

    def traced_functions(self) -> Iterator[tuple[FunctionInfo, str]]:
        for qual, reason in sorted(self.reached.items()):
            yield self.functions[qual], reason


# ---------------------------------------------------------------------------
# donation helpers (shared by rules/donation.py, rules/transitive_donation.py
# and program.py — living here keeps the import graph acyclic)
# ---------------------------------------------------------------------------

_JIT_LEAVES = {"jit", "pjit"}


def donated_positions(call: ast.Call) -> Optional[list[int]]:
    """Literal ``donate_argnums`` positions of a jit(...) call, or None."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            out = [
                e.value
                for e in elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)
            ]
            return out or None
    return None


def donating_callables(module) -> dict[str, list[int]]:
    """name -> donated positions, for `g = jax.jit(f, donate_argnums=...)`
    assignments and `@partial(jax.jit, donate_argnums=...)` decorated defs."""
    out: dict[str, list[int]] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            resolved = module.resolve(node.value.func) or ""
            if resolved.rsplit(".", 1)[-1] in _JIT_LEAVES:
                pos = donated_positions(node.value)
                if pos:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out[t.id] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                resolved = module.resolve(dec.func) or ""
                leaf = resolved.rsplit(".", 1)[-1]
                is_jit_factory = leaf in _JIT_LEAVES
                is_partial_jit = leaf == "partial" and any(
                    (module.resolve(a) or "").rsplit(".", 1)[-1] in _JIT_LEAVES
                    for a in dec.args
                )
                if is_jit_factory or is_partial_jit:
                    pos = donated_positions(dec)
                    if pos:
                        out[node.name] = pos
    return out
