"""accelerate_tpu — a TPU-native training & inference framework.

A from-scratch rebuild of the capability surface of HuggingFace Accelerate
(reference snapshot surveyed in SURVEY.md) designed for JAX/XLA/Pallas on
Cloud TPU: one SPMD program over a ``jax.sharding.Mesh`` replaces the
reference's ten process backends; FSDP/TP/SP/PP are mesh-axis layouts, not
wrapper modules; collectives are compiled into the step by XLA and ride ICI.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    # honor the standard JAX platform env var even when a container
    # sitecustomize (e.g. the axon TPU tunnel) has re-pinned the platform
    # after env processing — otherwise JAX_PLATFORMS=cpu subprocesses (test
    # launchers, example smoke runs) silently land on the TPU backend
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:  # already-initialized backend or exotic value: keep going
        pass

from .accelerator import Accelerator
from .big_modeling import (
    cpu_offload,
    cpu_offload_with_hook,
    disk_offload,
    dispatch_model,
    init_empty_weights,
    init_on_device,
    load_checkpoint_and_dispatch,
    materialize_meta_module,
    shard_for_inference,
)
from .serving import DecodeService, ServingConfig
from .state import AcceleratorState, GradientState, PartialState
from .logging import get_logger
from .data_loader import PaddingCollate, prepare_data_loader, skip_first_batches
from .utils.memory import find_executable_batch_size
from .utils.modeling import (
    find_tied_parameters,
    get_balanced_memory,
    get_max_memory,
    infer_auto_device_map,
    load_checkpoint_in_model,
    set_module_tensor_to_device,
)
from .utils.random import set_seed, synchronize_rng_states
from .utils.dataclasses import (
    CompilationCacheKwargs,
    CompressionKwargs,
    DataLoaderConfiguration,
    DataParallelPlugin,
    DistributedType,
    FleetKwargs,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    InitProcessGroupKwargs,
    KernelKwargs,
    ParallelismConfig,
    ProfileKwargs,
    ProjectConfiguration,
    ResilienceKwargs,
    SequenceParallelPlugin,
    TelemetryKwargs,
    TensorParallelPlugin,
)
