"""CLI layer tests (reference: tests/test_cli.py, 545 LoC — config/launch/env
round-trips against checked-in YAMLs)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from accelerate_tpu.commands.accelerate_cli import main as cli_main
from accelerate_tpu.commands.config.config_args import Config, load_config_from_file
from accelerate_tpu.commands.estimate import estimate_command_parser, gather_data
from accelerate_tpu.commands.launch import launch_command_parser


def test_config_yaml_roundtrip(tmp_path):
    config = Config(
        num_processes=4,
        distributed_type="MULTI_HOST",
        mixed_precision="bf16",
        main_process_ip="10.0.0.2",
        main_process_port=29501,
        fsdp_size=2,
        tp_size=2,
    )
    path = str(tmp_path / "cfg.yaml")
    config.save(path)
    loaded = load_config_from_file(path)
    assert loaded.to_dict() == config.to_dict()


def test_config_json_roundtrip(tmp_path):
    config = Config(mixed_precision="fp16", sp_size=4)
    path = str(tmp_path / "cfg.json")
    config.save(path)
    loaded = load_config_from_file(path)
    assert loaded.mixed_precision == "fp16"
    assert loaded.sp_size == 4


def test_config_rejects_unknown_keys(tmp_path):
    path = tmp_path / "bad.yaml"
    path.write_text("mixed_precision: bf16\nnum_gpus: 4\n")
    with pytest.raises(ValueError, match="num_gpus"):
        load_config_from_file(str(path))


def test_config_rejects_bad_distributed_type():
    with pytest.raises(ValueError, match="distributed_type"):
        Config(distributed_type="MULTI_GPU")


def test_launch_parser_mesh_args():
    parser = launch_command_parser()
    args = parser.parse_args(
        ["--fsdp_size", "2", "--tp_size", "4", "--mixed_precision", "bf16",
         "script.py", "--foo", "bar"]
    )
    assert args.fsdp_size == 2 and args.tp_size == 4
    assert args.training_script == "script.py"
    assert args.training_script_args == ["--foo", "bar"]


def test_launch_env_protocol():
    from accelerate_tpu.utils.launch import prepare_launch_environment

    parser = launch_command_parser()
    args = parser.parse_args(
        ["--num_processes", "4", "--machine_rank", "1",
         "--main_process_ip", "10.0.0.2", "--main_process_port", "29501",
         "--tp_size", "2", "--mixed_precision", "bf16",
         "--gradient_accumulation_steps", "8", "--seed", "7", "script.py"]
    )
    env = prepare_launch_environment(args)
    assert env["ACCELERATE_NUM_PROCESSES"] == "4"
    assert env["ACCELERATE_PROCESS_INDEX"] == "1"
    assert env["ACCELERATE_COORDINATOR_ADDRESS"] == "10.0.0.2:29501"
    assert env["TP_SIZE"] == "2"
    assert env["ACCELERATE_MIXED_PRECISION"] == "bf16"
    assert env["ACCELERATE_GRADIENT_ACCUMULATION_STEPS"] == "8"
    assert env["ACCELERATE_SEED"] == "7"


def test_launch_config_defaults_merge(tmp_path):
    Config(mixed_precision="bf16", tp_size=2, gradient_accumulation_steps=4).save(
        str(tmp_path / "cfg.yaml")
    )
    parser = launch_command_parser()
    args = parser.parse_args(
        ["--config_file", str(tmp_path / "cfg.yaml"), "--tp_size", "4", "s.py"]
    )
    from accelerate_tpu.commands.launch import _merge_config_defaults

    _merge_config_defaults(args)
    assert args.tp_size == 4  # CLI wins
    assert args.mixed_precision == "bf16"  # config fills the gap
    assert args.gradient_accumulation_steps == 4


def test_estimate_builtin_models():
    parser = estimate_command_parser()
    args = parser.parse_args(["gpt-tiny", "--dtypes", "float32", "bfloat16"])
    rows = gather_data(args)
    assert len(rows) == 2
    fp32, bf16 = rows
    assert fp32[0] == "float32" and bf16[0] == "bfloat16"
    assert fp32[2] == 2 * bf16[2]  # fp32 is exactly twice bf16
    assert fp32[3] == 4 * fp32[2]  # Adam training ≈ 4× weights
    assert fp32[4] == 2 * fp32[2]  # host-offloaded optimizer: HBM = params+grads


def test_estimate_unknown_model_raises():
    parser = estimate_command_parser()
    args = parser.parse_args(["no-such-model-xyz"])
    with pytest.raises(ValueError):
        gather_data(args)


def test_cli_env_command(capsys, monkeypatch):
    monkeypatch.setattr(sys, "argv", ["accelerate-tpu", "env"])
    cli_main()
    out = capsys.readouterr().out
    assert "`accelerate_tpu` version" in out
    assert "JAX version" in out


def test_write_basic_config(tmp_path):
    from accelerate_tpu.commands.config.default import write_basic_config

    path = str(tmp_path / "default.yaml")
    write_basic_config(mixed_precision="bf16", save_location=path)
    config = load_config_from_file(path)
    assert config.mixed_precision == "bf16"
    # second call must refuse to overwrite
    config2 = write_basic_config(mixed_precision="no", save_location=path)
    assert load_config_from_file(path).mixed_precision == "bf16"


def test_config_update_drops_legacy_keys(tmp_path):
    path = tmp_path / "old.yaml"
    path.write_text("mixed_precision: bf16\ndeepspeed_config: {stage: 3}\n")
    from accelerate_tpu.commands.config.update import update_config

    class Args:
        config_file = str(path)

    update_config(Args())
    loaded = load_config_from_file(str(path))
    assert loaded.mixed_precision == "bf16"


def test_tpu_config_debug_mode(capsys):
    from accelerate_tpu.commands.tpu import tpu_command_parser, tpu_command_launcher

    parser = tpu_command_parser()
    args = parser.parse_args(
        ["--tpu_name", "pod-1", "--tpu_zone", "us-central2-b",
         "--command", "echo hi", "--debug"]
    )
    tpu_command_launcher(args)
    out = capsys.readouterr().out
    assert "gcloud compute tpus tpu-vm ssh pod-1" in out
    assert "--worker=all" in out
