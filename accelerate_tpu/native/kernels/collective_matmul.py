"""Collective-matmul: the ZeRO-1 all-gather as a chunked ring feeding
partial matmuls as shards arrive (docs/kernels.md §collective-matmul).

The reference formulation leaves the gather to GSPMD: the updated dp-sharded
master is constrained back to the replica layout, XLA emits one monolithic
``all-gather``, and the first matmul of the step waits for the LAST chunk
before its first MAC.  The collective-matmul decomposition (the same one
behind XLA's ``--xla_tpu_enable_async_collective_fusion`` family and the
EQuARX paper's overlap analysis) ring-passes the shards instead: on hop
``t`` every device computes the partial product for the chunk it currently
holds while the next chunk is in flight, so the interconnect and the MXU
run concurrently and the exposed gather cost is ONE hop, not ``dp``.

Two lowerings behind one call:

* ``interpret=True`` (any non-TPU backend, tier-1): the per-hop transport
  is ``jax.lax.ppermute`` under ``shard_map`` and the partial matmul is a
  Pallas kernel in interpreter mode — plain partitionable StableHLO, which
  is what makes the fusion *inspectable* (``inspect.py``: no ``all_gather``
  op, chunked ``collective_permute`` + per-chunk dots instead) and the data
  movement bitwise-testable;
* ``interpret=False`` (TPU): one Pallas kernel per shard holds the ring in
  VMEM — ``make_async_remote_copy`` RDMA with explicit send/recv semaphores
  double-buffers the neighbour chunk behind the current hop's
  ``jnp.dot`` (SNIPPETS.md [1] pattern).

``ring_all_gather`` is the matmul-free version of the same ring (pure data
movement, bitwise-identical to the reference gather by construction) — the
transport ``Optimizer.step`` routes the ZeRO-1 param writeback through when
the policy arms ``collective_matmul``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental import shard_map

__all__ = [
    "collective_matmul",
    "reference_collective_matmul",
    "ring_all_gather",
    "zero1_gather_eligible",
    "zero1_all_gather",
]


def _ring_perm(n: int) -> list:
    """The +1 ring: device i sends to (i+1) % n."""
    return [(i, (i + 1) % n) for i in range(n)]


# ---------------------------------------------------------------------------
# ring all-gather (pure transport — the ZeRO-1 writeback wire)
# ---------------------------------------------------------------------------
def _ring_gather_local(shard, *, n: int, axis: int, axis_name: str):
    """shard_map body: my shard + n-1 ppermute hops → the full axis,
    chunk-ordered by source device so the concatenation equals the
    reference gather bitwise (movement only, no arithmetic)."""
    lead = jnp.moveaxis(shard, axis, 0)
    idx = jax.lax.axis_index(axis_name)
    out = jnp.zeros((n,) + lead.shape, lead.dtype)
    out = out.at[idx].set(lead)
    chunk = lead
    perm = _ring_perm(n)
    for hop in range(n - 1):
        chunk = jax.lax.ppermute(chunk, axis_name, perm)
        src = (idx - hop - 1) % n
        out = out.at[src].set(chunk)
    full = out.reshape((n * lead.shape[0],) + lead.shape[1:])
    return jnp.moveaxis(full, 0, axis)


def ring_all_gather(arr, sharding, axis: int, *, axis_name: str = "dp"):
    """Gather ``arr`` (globally shaped, dp-sharded at ``axis`` under
    ``sharding``) onto the same layout with the dp entry dropped, through an
    explicit chunked ring instead of GSPMD's monolithic all-gather.

    Pure data movement — bitwise-identical values to the reference
    constraint-based gather; what changes is the schedule the IR commits to
    (per-hop ``collective-permute`` the compiler can overlap with the
    consuming matmuls, asserted by ``inspect.check_collective_matmul``).
    Composable inside a captured jit trace (``shard_map`` nests in ``jit``).
    """
    mesh = sharding.mesh
    n = mesh.shape[axis_name]
    if n <= 1:
        return arr
    in_spec = _padded_spec(sharding.spec, getattr(arr, "ndim", len(arr.shape)))
    out_entries = list(in_spec)
    out_entries[axis] = None
    out_spec = jax.sharding.PartitionSpec(*out_entries)
    body = functools.partial(
        _ring_gather_local, n=n, axis=axis, axis_name=axis_name
    )
    return shard_map.shard_map(
        body, mesh=mesh, in_specs=in_spec, out_specs=out_spec, check_rep=False
    )(arr)


def _padded_spec(spec, ndim: int) -> jax.sharding.PartitionSpec:
    entries = list(spec) + [None] * (ndim - len(spec))
    return jax.sharding.PartitionSpec(*entries[:ndim])


def zero1_gather_eligible(sharding, axis, *, axis_name: str = "dp") -> bool:
    """The ring handles the plain ZeRO-1 layout: a NamedSharding whose
    ``axis`` entry is exactly the dp mesh axis (tuple entries — dp nested
    with another axis — keep the reference constraint gather)."""
    if axis is None or not isinstance(sharding, jax.sharding.NamedSharding):
        return False
    spec = list(sharding.spec)
    if axis >= len(spec) or spec[axis] != axis_name:
        return False
    return sharding.mesh.shape.get(axis_name, 1) > 1


def zero1_all_gather(arr, sharding, axis: int, *, interpret: bool = True):
    """The ZeRO-1 writeback wire: ``Optimizer.step`` hands the updated
    param (already cast to the param dtype, still on the dp-sharded state
    layout) to this instead of the GSPMD layout constraint when the kernel
    policy arms ``collective_matmul``.  ``interpret`` is accepted for
    signature parity with the other kernels — the transport itself is
    backend-agnostic (``ppermute`` lowers to ICI RDMA on TPU natively)."""
    del interpret  # transport-only entry: no pallas body to interpret
    return ring_all_gather(arr, sharding, axis)


# ---------------------------------------------------------------------------
# collective matmul (the first-matmul-of-the-step fusion)
# ---------------------------------------------------------------------------
def _partial_dot_kernel(x_ref, w_ref, o_ref):
    o_ref[:] = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)


def _partial_dot(xs, chunk, *, interpret: bool):
    return pl.pallas_call(
        _partial_dot_kernel,
        out_shape=jax.ShapeDtypeStruct((xs.shape[0], chunk.shape[1]), jnp.float32),
        interpret=interpret,
    )(xs, chunk)


def _cm_interpret_body(x_full, w_shard, *, n: int, axis_name: str,
                       interpret: bool):
    """shard_map body, interpreter/off-TPU lowering: hop the weight shards
    around the ring, multiplying the chunk in hand each hop — the chunk for
    hop t+1 is in flight while hop t's partial dot runs, which is exactly
    the schedule the monolithic all-gather forbids.

    Each device meets the chunks in a DIFFERENT ring order (device idx
    holds chunk idx−t at hop t), so the partials are buffered per source
    chunk and summed in fixed chunk order 0..n−1 at the end — the declared
    replicated output must be bitwise-consistent across devices (fp32
    addition is not associative; a running per-hop accumulation would make
    'replicated' replicas disagree in the last bits)."""
    idx = jax.lax.axis_index(axis_name)
    kc = w_shard.shape[0]
    chunk = w_shard
    partials = jnp.zeros((n, x_full.shape[0], w_shard.shape[1]), jnp.float32)
    perm = _ring_perm(n)
    for hop in range(n):
        src = (idx - hop) % n
        xs = jax.lax.dynamic_slice_in_dim(x_full, src * kc, kc, axis=1)
        partials = jax.lax.dynamic_update_index_in_dim(
            partials, _partial_dot(xs, chunk, interpret=interpret), src, axis=0
        )
        if hop < n - 1:
            chunk = jax.lax.ppermute(chunk, axis_name, perm)
    acc = partials[0]
    for src in range(1, n):
        acc = acc + partials[src]
    return acc


def _cm_rdma_kernel(x_ref, w_ref, o_ref, comm_buf, partials, send_sem,
                    recv_sem, *, n_devices: int, chunk_k: int,
                    axis_name: str):
    """TPU lowering: the whole ring in ONE Pallas kernel.  The neighbour's
    chunk streams into the spare comm-buffer slot over RDMA while the MXU
    consumes the chunk in hand; explicit send/recv semaphores sequence the
    double buffer (SNIPPETS.md [1]; guide §ring collectives).  Partials
    buffer per SOURCE chunk and sum in fixed chunk order at the end — same
    cross-replica bitwise-consistency argument as the interpret body."""
    from jax.experimental.pallas import tpu as pltpu

    my_id = jax.lax.axis_index(axis_name)
    right = (my_id + 1) % n_devices
    comm_buf[0] = w_ref[:]
    for hop in range(n_devices):
        slot = hop % 2
        if hop < n_devices - 1:
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[slot],
                dst_ref=comm_buf.at[(hop + 1) % 2],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[(hop + 1) % 2],
                device_id=(right,),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
        src = (my_id - hop) % n_devices
        xs = x_ref[:, pl.ds(src * chunk_k, chunk_k)]
        partials[src] = jnp.dot(
            xs, comm_buf[slot], preferred_element_type=jnp.float32
        )
        if hop < n_devices - 1:
            rdma.wait()
    o_ref[:] = partials[0]
    for src in range(1, n_devices):
        o_ref[:] += partials[src]


def _cm_tpu_body(x_full, w_shard, *, n: int, axis_name: str):
    from jax.experimental.pallas import tpu as pltpu

    # jax 0.4.x spells it TPUCompilerParams; newer releases CompilerParams.
    # collective_id sequences the RDMA ring; no has_side_effects needed —
    # the kernel has a real output, so it cannot be DCE'd.
    params_cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    kc, nc = w_shard.shape
    return pl.pallas_call(
        functools.partial(
            _cm_rdma_kernel, n_devices=n, chunk_k=kc, axis_name=axis_name
        ),
        out_shape=jax.ShapeDtypeStruct((x_full.shape[0], nc), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, kc, nc), w_shard.dtype),
            pltpu.VMEM((n, x_full.shape[0], nc), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=params_cls(collective_id=0),
        interpret=False,
    )(x_full, w_shard)


def collective_matmul(x, w, *, mesh, axis_name: str = "dp",
                      interpret: bool = True):
    """``x @ w`` where ``w`` arrives sharded along its contraction (first)
    axis over ``axis_name`` and ``x`` is replicated — WITHOUT ever
    materializing the gathered ``w``.

    This is the "first matmul of the step" primitive: fed the ZeRO-1
    dp-sharded updated weight directly, it subsumes the update's exposed
    all-gather into the matmul's own schedule.  Partials are summed in
    fixed chunk order 0..dp−1 on every device (bitwise-consistent across
    replicas, deterministic for a fixed mesh) — but that reduction ORDER
    still differs from the monolithic dot's, so parity with the reference
    is allclose, not bitwise (docs/kernels.md §numerics); the ZeRO-1
    writeback itself uses :func:`ring_all_gather`, which IS bitwise.
    """
    n = mesh.shape[axis_name]
    if n <= 1:
        return jnp.dot(x, w, preferred_element_type=jnp.float32)
    P = jax.sharding.PartitionSpec
    body = functools.partial(
        _cm_interpret_body if interpret else _cm_tpu_body,
        n=n,
        axis_name=axis_name,
        **({"interpret": True} if interpret else {}),
    )
    return shard_map.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(axis_name, None)),
        out_specs=P(),
        check_rep=False,
    )(x, w)


def reference_collective_matmul(x, w):
    """The unfused reference: plain dot on the logically-full ``w`` — GSPMD
    partitions it as all-gather-then-dot when ``w`` is committed dp-sharded
    (the contrast half of ``inspect.check_collective_matmul``)."""
    return jnp.dot(x, w, preferred_element_type=jnp.float32)
