"""GPT-2-family causal LM on accelerate_tpu.nn — the throughput flagship.

Decoder-only transformer with pre-norm blocks, learned positions, weight-tied
LM head, causal SDPA routed to the Pallas flash kernel.  Carries the TP plan
(qkv/ffn column-parallel, proj row-parallel) so pjit lays it out on any mesh.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax.numpy as jnp

from .. import nn
from ..nn import F, Tensor


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304  # padded to a 128 multiple for the MXU
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5

    @classmethod
    def small(cls) -> "GPTConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "GPTConfig":
        return cls(vocab_size=1024, n_positions=256, n_embd=128, n_layer=2, n_head=4)

    @classmethod
    def medium(cls) -> "GPTConfig":
        return cls(n_embd=1024, n_layer=24, n_head=16)


def _gpt2_init(model: nn.Module, config: GPTConfig) -> None:
    """GPT-2 init: N(0, 0.02) weights, zero biases, residual-proj scaling."""
    import jax

    from ..nn import random as nn_random

    scale = 0.02
    resid_scale = scale / math.sqrt(2 * config.n_layer)
    from ..nn.meta import is_meta

    for name, p in model.named_parameters():
        if is_meta(p.data):
            continue  # init_empty_weights: nothing to initialise
        if name.endswith(".bias") or ".ln" in name or "ln_" in name:
            if p.ndim == 1 and name.endswith("weight"):
                continue  # LN weight stays ones
            if name.endswith("bias"):
                p.data = jnp.zeros_like(p.data)
            continue
        if p.ndim >= 2:
            std = resid_scale if "c_proj" in name else scale
            p.data = std * jax.random.normal(
                nn_random.next_key(), p.shape, dtype=p.dtype
            )


class CausalSelfAttention(nn.Module):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.n_head = config.n_head
        self.head_dim = config.n_embd // config.n_head
        self.c_attn = nn.Linear(config.n_embd, 3 * config.n_embd)
        self.c_proj = nn.Linear(config.n_embd, config.n_embd)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x):
        b, s, c = x.shape
        qkv = self.c_attn(x).reshape(b, s, 3, self.n_head, self.head_dim)
        qkv = qkv.transpose(2, 0, 3, 1, 4)  # (3, b, h, s, d)
        q, k, v = qkv[0], qkv[1], qkv[2]
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, c)
        return self.dropout(self.c_proj(out))


class MLP(nn.Module):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.c_fc = nn.Linear(config.n_embd, 4 * config.n_embd)
        self.c_proj = nn.Linear(4 * config.n_embd, config.n_embd)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x):
        return self.dropout(self.c_proj(F.gelu(self.c_fc(x))))


class Block(nn.Module):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.n_embd, eps=config.layer_norm_eps)
        self.attn = CausalSelfAttention(config)
        self.ln_2 = nn.LayerNorm(config.n_embd, eps=config.layer_norm_eps)
        self.mlp = MLP(config)

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        return x + self.mlp(self.ln_2(x))


class GPTLMHeadModel(nn.Module):
    _no_split_modules = ["Block"]  # device_map units must keep residual adds intact
    tp_plan = {
        r".*\.c_attn\.weight": ("tp", None),
        r".*\.c_attn\.bias": ("tp",),
        r".*\.c_fc\.weight": ("tp", None),
        r".*\.c_fc\.bias": ("tp",),
        r".*\.c_proj\.weight": (None, "tp"),
        r"wte\.weight": ("tp", None),
    }

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.n_embd)
        self.wpe = nn.Embedding(config.n_positions, config.n_embd)
        self.drop = nn.Dropout(config.dropout)
        self.h = nn.ModuleList([Block(config) for _ in range(config.n_layer)])
        self.ln_f = nn.LayerNorm(config.n_embd, eps=config.layer_norm_eps)
        # LM head weight-tied to wte by Parameter-object sharing (reference
        # find_tied_parameters semantics, utils/modeling.py:559); a real
        # module (not an inline matmul) so device_map hooks cover it; built
        # under meta so the discarded weight never allocates or consumes RNG
        from ..nn.meta import meta_init

        with meta_init():
            self.lm_head = nn.Linear(config.n_embd, config.vocab_size, bias=False)
        self.lm_head.weight = self.wte.weight
        _gpt2_init(self, config)

    def forward(self, input_ids, labels=None):
        from ..parallel.sharding import constrain_activation

        ids = jnp.asarray(input_ids.data if isinstance(input_ids, Tensor) else input_ids)
        b, s = ids.shape
        pos = jnp.arange(s)[None, :]
        x = self.drop(self.wte(ids) + self.wpe(pos))
        # pin the activation layout at every layer boundary: batch stays on
        # (dp, fsdp) exactly as the loader placed it, so GSPMD never reshards
        # the residual stream (round-1 dryrun hit involuntary full remats)
        x = constrain_activation(x)
        for block in self.h:
            x = constrain_activation(block(x))
        x = self.ln_f(x)
        logits = self.lm_head(x)  # tied head: x @ wte^T
        if labels is not None:
            lab = jnp.asarray(labels.data if isinstance(labels, Tensor) else labels)
            shift_logits = logits[:, :-1, :].reshape(-1, self.config.vocab_size)
            shift_labels = lab[:, 1:].reshape(-1)
            loss = F.cross_entropy(shift_logits, shift_labels)
            return {"loss": loss, "logits": logits}
        return {"logits": logits}

    @property
    def num_flops_per_token(self) -> float:
        """Approximate training FLOPs/token (6N + attention term)."""
        n = self.num_parameters
        c = self.config
        attn = 12 * c.n_layer * c.n_embd * c.n_positions
        return 6 * n + attn
