"""Pillar 2 — preemption-safe checkpointing.

TPU fleets are preemptible by design: maintenance events and spot
reclamation deliver SIGTERM and expect the process gone shortly after.  The
standard answer (PyTorch/XLA's preemption handling, torchelastic's
checkpoint-on-signal — PAPERS.md) is a *sticky flag*, not an exception: the
signal handler must do nothing but record, because the training loop may be
mid-dispatch, mid-collective, or mid-checkpoint when it fires.  The loop
then reads the flag at its own safe point (``resilience.should_save`` /
``should_exit``, the ``accelerator.check_trigger()`` idiom) and drains
through the existing async ``save_state``/``wait_for_checkpoint`` machinery
so the run always exits with a COMPLETE checkpoint.

An optional wall-clock deadline covers scheduled maintenance windows ("save
and exit N seconds from now") with the same flags — no signal needed.
"""

from __future__ import annotations

import signal
import time
from typing import Callable, Iterable, Optional

# the installed guard (latest-wins, like telemetry's _ACTIVE slot): a later
# Accelerator's guard replaces — and uninstalls — the previous one, so the
# chain of "previous handlers" never points into a dead hub
_INSTALLED: Optional["PreemptionGuard"] = None


class PreemptionGuard:
    """Sticky-flag signal handler + optional wall-clock deadline."""

    def __init__(
        self,
        signals: Optional[Iterable[int]] = None,
        deadline_s: Optional[float] = None,
        on_trigger: Optional[Callable[[int], None]] = None,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        self.signals = tuple(signals) if signals else (signal.SIGTERM, signal.SIGINT)
        self._time = time_fn
        self._deadline_at = (
            self._time() + float(deadline_s) if deadline_s is not None else None
        )
        self._on_trigger = on_trigger
        self._triggered = False
        self._signum: Optional[int] = None
        self._prev: dict[int, object] = {}
        self.installed = False

    # -- lifecycle -----------------------------------------------------------
    def install(self) -> bool:
        """Register the handlers; returns False (and stays inert) off the
        main thread, where CPython forbids ``signal.signal``."""
        global _INSTALLED
        if self.installed:
            return True
        if _INSTALLED is not None:
            _INSTALLED.uninstall()
        try:
            for signum in self.signals:
                self._prev[signum] = signal.signal(signum, self._handle)
        except ValueError:  # not the main thread
            for signum, prev in self._prev.items():
                try:  # pragma: no cover — restore is also main-thread-only
                    signal.signal(signum, prev)
                except ValueError:
                    pass
            self._prev.clear()
            return False
        self.installed = True
        _INSTALLED = self
        return True

    def uninstall(self) -> None:
        global _INSTALLED
        if not self.installed:
            return
        for signum, prev in self._prev.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, TypeError):
                pass
        self._prev.clear()
        self.installed = False
        if _INSTALLED is self:
            _INSTALLED = None

    def _handle(self, signum, frame) -> None:
        # record-only: the loop may be mid-dispatch/mid-collective — raising
        # here would corrupt the very state the drain exists to save
        repeat = self._triggered and self._signum == signum
        self._triggered = True
        self._signum = signum
        try:
            # flight event + best-effort blackbox dump (docs/telemetry.md):
            # a preempted process may never reach its drain point, so the
            # forensic record is written the moment the signal lands.  Both
            # are rank-local and async-signal-tolerant (pure python, no
            # collectives); any failure must not eat the sticky flag.
            from ..telemetry import flightrec, watchdog

            flightrec.record("signal", signum=int(signum), repeat=repeat)
            wd = watchdog.current_watchdog()
            if wd is not None:
                wd.dump_now(reason="preemption_signal")
        except Exception:
            pass
        if self._on_trigger is not None:
            try:
                self._on_trigger(signum)
            except Exception:  # a telemetry hiccup must not eat the flag
                pass
        if repeat and signum == signal.SIGINT:
            # a second Ctrl-C means NOW: a loop that never polls the sticky
            # flag (or a wedged dispatch) must still be interruptible
            raise KeyboardInterrupt

    # -- flags ---------------------------------------------------------------
    @property
    def triggered(self) -> bool:
        return self._triggered

    @property
    def signal_name(self) -> Optional[str]:
        if self._signum is None:
            return None
        try:
            return signal.Signals(self._signum).name
        except ValueError:
            return str(self._signum)

    def deadline_reached(self) -> bool:
        return self._deadline_at is not None and self._time() >= self._deadline_at

    def seconds_to_deadline(self) -> Optional[float]:
        if self._deadline_at is None:
            return None
        return max(0.0, self._deadline_at - self._time())
