"""``accelerate-tpu launch`` — validate args, pick a launcher, spawn.

Counterpart of ``/root/reference/src/accelerate/commands/launch.py``
(launch_command :1169, launcher selection :1169-1194, config-default merge
:988-1166).  The reference multiplexes over 7 launchers (torchrun elastic,
deepspeed pdsh, xmp.spawn, SSH pod fan-out, SageMaker, ...); the TPU-native
set is three:

* ``simple_launcher``    — one process on this host driving all local chips
  (the common case: SPMD replaces per-GPU process fan-out);
* ``multihost_launcher`` — N processes rendezvousing through
  ``jax.distributed`` (on one dev box this doubles as the CPU-simulation
  distributed mode, reference debug/notebook Pattern-3 analog);
* ``tpu_pod_launcher``   — ``gcloud compute tpus tpu-vm ssh --worker=all``
  fan-out that re-runs the command on every pod worker (reference
  tpu_pod_launcher launch.py:909).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time
from typing import Optional

from ..utils.launch import (
    prepare_multihost_worker_env,
    prepare_simple_launcher_cmd_env,
)

__all__ = ["launch_command", "launch_command_parser", "main"]


def launch_command_parser(subparsers: Optional[argparse._SubParsersAction] = None):
    description = "Launch a training script on TPU (or the CPU simulator)"
    if subparsers is not None:
        parser = subparsers.add_parser(
            "launch", help=description, allow_abbrev=False
        )
    else:
        parser = argparse.ArgumentParser(
            "accelerate-tpu launch", description=description, allow_abbrev=False
        )

    parser.add_argument("--config_file", default=None, help="Config YAML/JSON to use")
    # hardware / processes
    hw = parser.add_argument_group("Hardware and process topology")
    hw.add_argument("--cpu", action="store_true", help="Force the CPU backend")
    hw.add_argument(
        "--num_processes",
        type=int,
        default=None,
        help="Number of host processes (one per TPU VM worker)",
    )
    hw.add_argument(
        "--machine_rank", type=int, default=None, help="This host's process index"
    )
    hw.add_argument("--main_process_ip", default=None, help="Coordinator IP (worker 0)")
    hw.add_argument(
        "--main_process_port", type=int, default=None, help="Coordinator port"
    )
    hw.add_argument(
        "--num_virtual_devices",
        type=int,
        default=None,
        help="CPU simulation: per-process virtual XLA device count",
    )
    hw.add_argument(
        "--local_ranks",
        action="store_true",
        help="Multihost on ONE machine (CPU simulation): spawn all ranks locally",
    )
    hw.add_argument(
        "--max_restarts",
        type=int,
        default=0,
        help="Gang restarts after a worker failure (torchrun elastic-agent "
        "parity; SPMD restarts the WHOLE gang — partial restarts cannot "
        "rejoin a compiled collective program)",
    )
    hw.add_argument(
        "--monitor_interval",
        type=float,
        default=0.2,
        help="Seconds between worker liveness polls (torchrun parity)",
    )
    # mesh layout
    mesh = parser.add_argument_group("Mesh layout (SPMD parallelism axes)")
    for axis, doc in (
        ("dp", "data-parallel"),
        ("fsdp", "parameter-sharding (ZeRO/FSDP)"),
        ("tp", "tensor-parallel"),
        ("sp", "sequence-parallel (ring attention)"),
        ("ep", "expert-parallel (MoE)"),
        ("pp", "pipeline-parallel"),
    ):
        mesh.add_argument(
            f"--{axis}_size",
            type=int,
            default=None,
            help=f"{doc} mesh-axis size",
        )
    mesh.add_argument("--use_fsdp", action="store_true")
    mesh.add_argument("--fsdp_sharding_strategy", default=None)
    mesh.add_argument("--fsdp_state_dict_type", default=None)
    mesh.add_argument("--fsdp_transformer_layer_cls_to_wrap", default=None)
    mesh.add_argument("--fsdp_activation_checkpointing", action="store_true")
    mesh.add_argument("--fsdp_offload_params", action="store_true")
    # training knobs carried by env
    tr = parser.add_argument_group("Training")
    tr.add_argument(
        "--mixed_precision", default=None, choices=["no", "bf16", "fp16", "fp8"]
    )
    tr.add_argument("--gradient_accumulation_steps", type=int, default=None)
    tr.add_argument("--seed", type=int, default=None)
    tr.add_argument("--debug", action="store_true")
    # pod fan-out
    pod = parser.add_argument_group("TPU pod")
    pod.add_argument("--tpu_use_cluster", action="store_true")
    pod.add_argument("--tpu_name", default=None)
    pod.add_argument("--tpu_zone", default=None)
    # script
    parser.add_argument(
        "-m",
        "--module",
        action="store_true",
        help="Interpret training_script as a python module (python -m)",
    )
    parser.add_argument(
        "--no_python",
        action="store_true",
        help="Run training_script directly (it is not a python file)",
    )
    parser.add_argument("training_script", help="Script (or module) to launch")
    parser.add_argument(
        "training_script_args", nargs=argparse.REMAINDER, help="Script arguments"
    )
    if subparsers is not None:
        parser.set_defaults(func=launch_command)
    return parser


def _merge_config_defaults(args) -> None:
    """Fill unset CLI args from the config file (reference
    _validate_launch_command launch.py:988-1166: CLI > config > default)."""
    from .config import load_config_from_file
    from .config.config_args import default_config_file

    config_file = args.config_file
    if config_file is None:
        candidate = os.environ.get("ACCELERATE_CONFIG_FILE", default_config_file)
        if not os.path.isfile(candidate):
            return
        config_file = candidate
    config = load_config_from_file(config_file)
    mapping = {
        "num_processes": config.num_processes,
        "machine_rank": config.machine_rank,
        "main_process_ip": config.main_process_ip,
        "main_process_port": config.main_process_port,
        "mixed_precision": config.mixed_precision,
        "gradient_accumulation_steps": config.gradient_accumulation_steps,
        "dp_size": config.dp_size or None,
        "fsdp_size": config.fsdp_size,
        "tp_size": config.tp_size,
        "sp_size": config.sp_size,
        "ep_size": config.ep_size,
        "pp_size": config.pp_size,
        "num_virtual_devices": config.num_virtual_devices or None,
        "tpu_name": config.tpu_name,
        "tpu_zone": config.tpu_zone,
    }
    for key, value in mapping.items():
        # value-typed keys: only None means "unset" — 0 is a legitimate
        # explicit value (e.g. --machine_rank 0 must beat the config file)
        if getattr(args, key, None) is None:
            setattr(args, key, value)
    if config.use_cpu:
        args.cpu = True
    if config.debug:
        args.debug = True
    if config.tpu_use_cluster:
        args.tpu_use_cluster = True
    if config.fsdp_config:
        args.use_fsdp = True
        for k, v in config.fsdp_config.items():
            attr = k if k.startswith("fsdp_") else f"fsdp_{k}"
            cur = getattr(args, attr, None)
            # store_true flags default to False; value-typed args default None
            if cur is None or (cur is False and isinstance(v, bool)):
                setattr(args, attr, v)


def _supervise(run_once, max_restarts: int, cmd, what: str) -> None:
    """Elastic gang supervision (torchrun-agent parity): re-run ``run_once``
    after failures, up to ``max_restarts`` times, with exponential backoff so
    an import-time crash cannot burn every restart in milliseconds.
    Startup-time RuntimeErrors (e.g. a coordinator port still draining from
    the killed gang) count as retryable failures, not aborts."""
    restarts_left = max(0, max_restarts or 0)
    attempt = 0
    while True:
        failure: object
        try:
            rc = run_once()
            if rc == 0:
                return
            failure = rc
        except RuntimeError as exc:
            failure = exc
        if restarts_left <= 0:
            if isinstance(failure, BaseException):
                raise failure
            raise subprocess.CalledProcessError(failure, cmd)
        restarts_left -= 1
        attempt += 1
        delay = min(5.0, 0.5 * (2 ** (attempt - 1)))
        print(
            f"[accelerate-tpu launch] {what} failed ({failure}); restarting "
            f"in {delay:.1f}s ({restarts_left} restart(s) left)",
            file=sys.stderr,
        )
        time.sleep(delay)


def _is_multi_machine(args) -> bool:
    return bool(
        (getattr(args, "num_machines", None) or 1) > 1
        or getattr(args, "main_process_ip", None)
        not in (None, "", "127.0.0.1", "localhost")
    )


def simple_launcher(args) -> None:
    """Single process on this host (reference simple_launcher launch.py:773),
    re-launched up to ``--max_restarts`` times on failure.

    Restarts apply only to SINGLE-machine jobs: one member of a multi-host
    ``jax.distributed`` gang cannot rejoin a coordinator that still holds
    its dead slot, so a host-local restart would hang — pod-level gang
    restarts live in tpu_pod_launcher (the whole SSH fan-out reruns)."""
    cmd, env = prepare_simple_launcher_cmd_env(args)
    max_restarts = getattr(args, "max_restarts", 0) or 0
    if max_restarts and _is_multi_machine(args):
        print(
            "[accelerate-tpu launch] --max_restarts ignored for a multi-host "
            "member: a lone restarted worker cannot rejoin the gang (use the "
            "pod launcher's gang restart)",
            file=sys.stderr,
        )
        max_restarts = 0

    def run_once() -> int:
        process = subprocess.Popen(cmd, env=env)
        process.wait()
        return process.returncode

    _supervise(run_once, max_restarts, cmd, "worker")


def _wait_port_free(port: int, host: str = "127.0.0.1") -> None:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind((host, port))
        except OSError as e:
            raise RuntimeError(
                f"coordinator port {port} is busy; pass --main_process_port"
            ) from e


def multihost_launcher(args) -> None:
    """Spawn all ranks on THIS machine, rendezvoused via jax.distributed.

    This is the CPU-simulation distributed mode (reference debug_launcher
    Pattern 3, launchers.py:268): genuine multi-process collectives with no
    accelerator attached.  On a real pod each worker runs its own single
    process instead (see tpu_pod_launcher).
    """
    num_processes = args.num_processes
    port = args.main_process_port or 29500
    coordinator = f"127.0.0.1:{port}"

    cmd = []
    if args.module:
        cmd.extend([sys.executable, "-m"])
    elif not args.no_python:
        cmd.append(sys.executable)
    cmd.append(args.training_script)
    cmd.extend(args.training_script_args or [])

    interval = getattr(args, "monitor_interval", None)
    # 0 is a legitimate explicit value (tightest poll) — clamp, don't default
    interval = 0.2 if interval is None else max(0.01, interval)
    restarts_left = max(0, getattr(args, "max_restarts", 0) or 0)

    def run_gang() -> int:
        """Spawn the full rank gang; 0 on success, else the first bad rc.
        Any failure kills the remaining ranks — a compiled SPMD program
        cannot make progress (or be rejoined) with a member missing, so
        gang-restart is the only sound elastic unit."""
        _wait_port_free(port)
        processes = []
        for rank in range(num_processes):
            env = prepare_multihost_worker_env(args, rank, num_processes, coordinator)
            env.setdefault("JAX_PLATFORMS", "cpu")
            processes.append(subprocess.Popen(cmd, env=env))
        try:
            while processes:
                time.sleep(interval)
                for p in list(processes):
                    rc = p.poll()
                    if rc is None:
                        continue
                    processes.remove(p)
                    if rc != 0:
                        return rc
            return 0
        finally:
            for p in processes:
                p.terminate()
            for p in processes:
                p.wait()

    _supervise(run_gang, restarts_left, cmd, "gang")


def tpu_pod_launcher(args) -> None:
    """SSH fan-out over all pod workers (reference tpu_pod_launcher
    launch.py:909): each worker re-runs ``accelerate-tpu launch`` locally with
    its own machine_rank discovered from TPU metadata."""
    if not args.tpu_name:
        raise ValueError("--tpu_use_cluster requires --tpu_name (and --tpu_zone)")
    inner = ["accelerate-tpu", "launch"]
    for flag in ("mixed_precision", "gradient_accumulation_steps", "seed"):
        value = getattr(args, flag, None)
        if value is not None:
            inner += [f"--{flag}", str(value)]
    for axis in ("dp", "fsdp", "tp", "sp", "ep", "pp"):
        value = getattr(args, f"{axis}_size", None)
        if value and value > 1:
            inner += [f"--{axis}_size", str(value)]
    inner.append(args.training_script)
    inner += args.training_script_args or []
    command = " ".join(inner)
    gcloud_cmd = [
        "gcloud",
        "compute",
        "tpus",
        "tpu-vm",
        "ssh",
        args.tpu_name,
        "--worker=all",
        f"--command={command}",
    ]
    if args.tpu_zone:
        gcloud_cmd.insert(5, f"--zone={args.tpu_zone}")
    print(f"Running: {' '.join(gcloud_cmd)}")
    # gang restart = rerun the WHOLE fan-out: every worker restarts together
    # so the jax.distributed coordinator comes up fresh.  --max_restarts is
    # deliberately NOT forwarded to the inner per-worker launches — a lone
    # worker restarting inside a live gang could never rejoin (see
    # simple_launcher).

    def run_once() -> int:
        return subprocess.run(gcloud_cmd).returncode

    _supervise(
        run_once, getattr(args, "max_restarts", 0) or 0, gcloud_cmd, "pod gang"
    )


def launch_command(args) -> None:
    _merge_config_defaults(args)
    if getattr(args, "tpu_use_cluster", False):
        tpu_pod_launcher(args)
    elif (
        args.num_processes
        and args.num_processes > 1
        and (args.local_ranks or args.cpu or not args.main_process_ip)
    ):
        multihost_launcher(args)
    else:
        simple_launcher(args)


def main():
    args = launch_command_parser().parse_args()
    launch_command(args)


if __name__ == "__main__":
    main()
