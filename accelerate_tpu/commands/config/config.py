"""Interactive questionnaire for ``accelerate-tpu config``.

Counterpart of ``/root/reference/src/accelerate/commands/config/cluster.py:55``
+ ``commands/config/config.py``.  The reference's 800-line questionnaire
mostly disambiguates ten process backends; here the questions collapse to:
where do you run (local host / TPU pod / CPU simulation), how many hosts, the
mesh layout, and precision.  Plain ``input()`` prompts instead of the arrow-key
menu TUI (commands/menu/) so the flow works over any terminal (incl. ssh'd pod
workers); every question accepts an empty answer for its default.
"""

from __future__ import annotations

import argparse
from typing import Callable, Optional

from .config_args import Config, default_config_file


def _ask_field(
    prompt: str,
    convert: Callable = str,
    default=None,
    error_message: str = "invalid input",
):
    """Reference: _ask_field commands/config/config_utils.py:33."""
    while True:
        raw = input(prompt)
        if not raw.strip():
            return default
        try:
            return convert(raw.strip())
        except ValueError:
            print(error_message)


def _ask_choice(prompt: str, choices: list[str], default: str) -> str:
    """Arrow-key bullet menu on a TTY (reference commands/menu); on piped
    stdin fall back to the classic typed prompt so scripted config works."""
    from ..menu import BulletMenu

    # BulletMenu renders arrows on a TTY and falls back to a numbered
    # prompt (accepting index, name, or empty-for-default) on piped stdin
    idx = BulletMenu(prompt, choices).run(default=choices.index(default))
    return choices[idx]


def _yes_no(prompt: str, default: bool = False) -> bool:
    answer = _ask_choice(prompt, ["yes", "no"], "yes" if default else "no")
    return answer == "yes"


def get_user_input() -> Config:
    """Run the questionnaire and return the resulting Config."""
    env = _ask_choice(
        "In which compute environment are you running?",
        ["local_machine", "tpu_pod", "cpu_simulation"],
        "local_machine",
    )
    config = Config()
    if env == "cpu_simulation":
        config.use_cpu = True
        config.distributed_type = "NO"
        config.num_virtual_devices = _ask_field(
            "How many virtual devices should XLA create? [8]: ", int, 8
        )
    else:
        config.compute_environment = (
            "TPU_POD" if env == "tpu_pod" else "LOCAL_MACHINE"
        )
        config.num_processes = _ask_field(
            "How many host processes (one per TPU VM worker)? [1]: ", int, 1
        )
        config.distributed_type = "MULTI_HOST" if config.num_processes > 1 else "TPU"
        if config.num_processes > 1:
            config.main_process_ip = _ask_field(
                "What is the coordinator (worker 0) IP address? ", str, None
            )
            config.main_process_port = _ask_field(
                "What is the coordinator port? [29500]: ", int, 29500
            )
        if env == "tpu_pod":
            config.tpu_name = _ask_field("What is the TPU name? ", str, None)
            config.tpu_zone = _ask_field("What is the GCP zone? ", str, None)
            config.tpu_use_cluster = True
    config.fsdp_size = _ask_field(
        "FSDP (parameter-sharding) axis size? [1 = off]: ", int, 1
    )
    config.tp_size = _ask_field("Tensor-parallel axis size? [1 = off]: ", int, 1)
    config.sp_size = _ask_field(
        "Sequence-parallel (ring attention) axis size? [1 = off]: ", int, 1
    )
    config.gradient_accumulation_steps = _ask_field(
        "Gradient accumulation steps? [1]: ", int, 1
    )
    config.mixed_precision = _ask_choice(
        "Mixed precision?", ["no", "bf16", "fp16", "fp8"], "bf16"
    )
    return config


def config_command_parser(subparsers: Optional[argparse._SubParsersAction] = None):
    description = "Launch configuration questionnaire"
    if subparsers is not None:
        parser = subparsers.add_parser("config", description=description)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu config", description=description)
    parser.add_argument(
        "--config_file",
        default=None,
        help=f"Where to save the config (default {default_config_file})",
    )
    if subparsers is not None:
        parser.set_defaults(func=config_command)
    return parser


def config_command(args) -> None:
    config = get_user_input()
    path = config.save(args.config_file)
    print(f"accelerate-tpu configuration saved at {path}")


def main():
    args = config_command_parser().parse_args()
    config_command(args)


if __name__ == "__main__":
    main()
