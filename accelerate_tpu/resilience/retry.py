"""Pillar 3 — step retry with rollback.

A captured-step dispatch can fail for two very different reasons and they
must not be handled alike:

* **transient runtime faults** — the PJRT/XLA runtime path to the device
  hiccuped (UNAVAILABLE, DEADLINE_EXCEEDED, a dropped tunnel connection).
  The program and its inputs are fine; trying again is both safe and the
  right move.  Safe because of the donation guarantee the capture layer
  already relies on (capture.py ``_dispatch_aot``): argument validation —
  where these failures surface — happens BEFORE any buffer is donated, so a
  failed call leaves every donated leaf intact for the retry.
* **user/program errors** — a shape mismatch, a NaN assert, an OOM
  (RESOURCE_EXHAUSTED).  Retrying re-runs the same wrong program; these
  propagate immediately.

On retry exhaustion the step is rolled back: restore the last good
checkpoint (``Resilience.note_checkpoint`` records every successful
``save_state``), rebind the freshly restored state into the SAME compiled
entry (the cache key didn't change, so zero extra recompiles), and replay
the dispatch.  Every attempt/rollback is a kind-tagged telemetry event.

Two hard edges, handled explicitly:

* a fault that fires MID-EXECUTION (past argument validation) may already
  have consumed donated input buffers — re-invoking with the same leaves
  would die on "Array has been deleted".  The loop checks for deleted
  donated leaves before retrying and escalates straight to rollback (the
  restore rebinds fresh buffers) instead of burning retries it cannot win;
* rollback on a multi-process run needs COORDINATION: ``load_state`` is
  collective, and one rank restoring while its peers proceed to the next
  step's collectives would deadlock the mesh.  With the elastic fleet
  runtime armed (``accelerator.fleet``, docs/elastic.md) exhaustion enters
  the all-ranks restore protocol instead — every rank offers its visible
  complete checkpoints to a gather/vote barrier, all ranks agree on the
  newest all-ranks-visible restore point, and only then does every rank
  issue the collective ``load_state`` together (a dispatch fault is SPMD —
  it surfaces on every rank's dispatch of the same call, so all retriers
  exhaust and vote in lockstep).  Without the fleet, multi-process
  exhaustion propagates exactly as before.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional

from .backend import backoff_delay
from .inject import InjectedTransientError

# substrings of transient PJRT/XLA status codes and transport failures; a
# dispatch error carrying one of these is worth retrying.  RESOURCE_EXHAUSTED
# (OOM) is deliberately absent — the same program will exhaust the same HBM.
TRANSIENT_MARKERS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "aborted",
    "cancelled",
    "connection reset",
    "socket closed",
    "failed to connect",
    "transient",
)

# errors that are the user's program talking, never the runtime flaking
_USER_ERROR_TYPES = (TypeError, ValueError, KeyError, AttributeError, AssertionError)


def _multi_process() -> bool:
    """Module-level so tests can pin the world-size read without touching
    the Borg PartialState."""
    from ..state import PartialState

    return bool(PartialState._shared_state and PartialState().num_processes > 1)


def classify_failure(exc: BaseException) -> str:
    """``"transient"`` (retry) or ``"user"`` (propagate)."""
    if isinstance(exc, InjectedTransientError):
        return "transient"
    if isinstance(exc, _USER_ERROR_TYPES):
        return "user"
    text = f"{type(exc).__name__}: {exc}".lower()
    if any(marker in text for marker in TRANSIENT_MARKERS):
        return "transient"
    return "user"


class StepRetrier:
    """Bounded-backoff retry around a captured-step dispatch, with one
    checkpoint rollback when retries run dry."""

    def __init__(
        self,
        hub,
        max_retries: int = 2,
        backoff_s: float = 0.5,
        backoff_cap_s: float = 8.0,
        jitter: float = 0.25,
        rollback: bool = True,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ):
        self.hub = hub
        self.max_retries = max(0, int(max_retries))
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.jitter = float(jitter)
        self.rollback = bool(rollback)
        self.sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        self.retries_total = 0
        self.rollbacks_total = 0
        # backoff sleep spent inside the most recent run_dispatch, so the
        # capture layer can split retry waits out of dispatch_ms (telemetry
        # StepRecord.retry_wait_ms) — retries must not inflate A/B timings
        self.last_wait_ms = 0.0

    def _delay(self, attempt: int) -> float:
        return backoff_delay(
            attempt, self.backoff_s, self.backoff_cap_s, self.jitter, self._rng
        )

    def _coordinator(self):
        """The enabled Fleet hub when this is a multi-process run that must
        (and can) coordinate its restore; None on single-process runs —
        where the local rollback needs no vote."""
        if not _multi_process():
            return None
        fleet = getattr(self.hub, "fleet", None)
        if fleet is not None and fleet.enabled and fleet.handler.coordinate_rollback:
            return fleet
        return None

    def _rollback_allowed(self) -> bool:
        if not self.rollback:
            return False
        if _multi_process():
            # load_state is collective; a single rank restoring while its
            # peers run the next step's collectives would hang the mesh —
            # only the fleet's all-ranks vote protocol makes it safe
            return self._coordinator() is not None
        return True

    def run_dispatch(self, step, dispatch, entry, dev_leaves, host_leaves, host_mask):
        """Drive ``dispatch(dev_leaves, host_leaves, entry)`` to completion.

        ``dispatch`` returns the capture layer's ``(new_state, out, entry,
        rebuilt)`` tuple.  ``step`` is the owning CapturedStep — needed to
        re-collect state after a rollback restore.  The injector's dispatch
        faults fire inside this loop so retries are exercised end-to-end.
        """
        hub = self.hub
        call_index = hub.dispatch_calls - 1  # begin_dispatch already counted
        attempt = 0
        rolled_back = False
        self.last_wait_ms = 0.0
        while True:
            try:
                if hub.injector is not None:
                    hub.injector.maybe_dispatch_fault(call_index)
                return dispatch(dev_leaves, host_leaves, entry)
            except Exception as exc:  # noqa: BLE001 — classified right below
                if classify_failure(exc) != "transient":
                    raise
                error = f"{type(exc).__name__}: {exc}"[:200]
                # a mid-execution fault may have consumed the donated input
                # buffers (validation-time faults never do) — re-invoking
                # with deleted leaves cannot succeed, so skip the retry
                # budget and go straight to the rollback decision
                consumed = any(
                    leaf.is_deleted()
                    for leaf in dev_leaves
                    if hasattr(leaf, "is_deleted")
                )
                if attempt < self.max_retries and not consumed:
                    delay = self._delay(attempt)
                    attempt += 1
                    self.retries_total += 1
                    hub.record_event(
                        "dispatch_retry",
                        step=call_index,
                        attempt=attempt,
                        max_retries=self.max_retries,
                        delay_s=round(delay, 3),
                        error=error,
                    )
                    t_sleep = time.perf_counter()
                    self.sleep(delay)
                    self.last_wait_ms += (time.perf_counter() - t_sleep) * 1e3
                    continue
                checkpoint = hub.last_checkpoint
                coordinator = self._coordinator()
                if (
                    not self._rollback_allowed()
                    or rolled_back
                    or (checkpoint is None and coordinator is None)
                ):
                    hub.record_event(
                        "dispatch_exhausted",
                        step=call_index,
                        attempts=attempt + 1,
                        rolled_back=rolled_back,
                        donated_consumed=consumed,
                        error=error,
                    )
                    raise
                if coordinator is not None:
                    # coordinated restore (docs/elastic.md): all ranks reach
                    # this vote together (the fault is SPMD), agree on the
                    # newest all-ranks-visible complete checkpoint, and only
                    # then issue the collective load_state below in lockstep
                    from ..fleet.coordinate import vote_restore_point

                    agreed = vote_restore_point(
                        step.accelerator, fleet=coordinator
                    )
                    if agreed is None:
                        hub.record_event(
                            "dispatch_exhausted",
                            step=call_index,
                            attempts=attempt + 1,
                            rolled_back=False,
                            donated_consumed=consumed,
                            error=error,
                            restore_vote="no all-ranks-visible checkpoint",
                        )
                        raise
                    checkpoint = agreed["path"]
                # rollback: restore the last good checkpoint and replay this
                # call against the SAME compiled entry — the cache key is a
                # function of arg shapes and flags, none of which the restore
                # moved, so the replay costs zero recompiles
                self.rollbacks_total += 1
                hub.record_event(
                    "rollback",
                    step=call_index,
                    checkpoint=checkpoint,
                    coordinated=coordinator is not None,
                    donated_consumed=consumed,
                    error=error,
                )
                # zero-cold-start coupling: load_state warms the AOT
                # executable cache before restoring, so even a rollback that
                # somehow lost the in-memory entry (a state-structure change
                # popped it) replays the serialized executable instead of
                # recompiling; record how many entries the warm staged
                cache = getattr(step.accelerator, "aot_cache", None)
                step.accelerator.load_state(checkpoint)
                if coordinator is not None:
                    # the collective restore landed on every rank — the
                    # event docs/elastic.md promises operators can grep for
                    coordinator.record_event(
                        "coordinated_rollback",
                        checkpoint=checkpoint,
                        dispatch_index=call_index,
                    )
                if cache is not None and cache.enabled and cache.warm_on_restore:
                    # warm_on_restore off means load_state ran NO prefetch —
                    # reporting a stale count would claim a warm that never
                    # happened on this restore
                    hub.record_event(
                        "aot_cache_warm",
                        step=call_index,
                        entries=cache.last_prefetch_count,
                    )
                import jax

                flat_state, _ = jax.tree_util.tree_flatten(step._collect_state())
                dev_leaves = tuple(
                    x for x, h in zip(flat_state, host_mask) if not h
                )
                host_leaves = tuple(x for x, h in zip(flat_state, host_mask) if h)
                rolled_back = True
                attempt = 0
