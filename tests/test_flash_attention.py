"""Flash-attention kernel parity tests (interpret mode on CPU).

The Pallas kernels are grid-for-grid the programs that run on TPU; interpret
mode executes the same block schedule on CPU so forward/backward parity is CI
coverage, not TPU-only hope.  Reference: the kernels replace the vendored
fused attention the torch world gets from TE/Megatron (SURVEY.md §2.7.3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import accelerate_tpu.ops.flash_attention as fa
from accelerate_tpu.ops.attention import sdpa_reference


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setattr(fa, "_INTERPRET", True)


def _rand_qkv(b=1, h=2, s=256, d=64, dtype=jnp.float32, seed=0):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(kq, (b, h, s, d), dtype)
    k = jax.random.normal(kk, (b, h, s, d), dtype)
    v = jax.random.normal(kv, (b, h, s, d), dtype)
    return q, k, v


@pytest.mark.parametrize("is_causal", [False, True])
def test_forward_matches_reference(is_causal):
    q, k, v = _rand_qkv()
    out = fa.flash_attention(q, k, v, is_causal)
    ref = sdpa_reference(q, k, v, is_causal=is_causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("is_causal", [False, True])
def test_backward_matches_reference(is_causal):
    q, k, v = _rand_qkv()

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, is_causal)
        return jnp.sum(o * jnp.cos(o))  # non-trivial cotangent

    def loss_ref(q, k, v):
        o = sdpa_reference(q, k, v, is_causal=is_causal)
        return jnp.sum(o * jnp.cos(o))

    gq, gk, gv = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    rq, rk, rv = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(rq), atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(rk), atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(rv), atol=2e-4, rtol=2e-4)


def test_backward_never_materializes_s2(monkeypatch):
    """The backward jaxpr must contain no (sq, sk) = O(S²) intermediate."""
    q, k, v = _rand_qkv(b=1, h=1, s=256, d=64)

    def loss(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, True))

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    s2 = 256 * 256
    for eqn in jaxpr.jaxpr.eqns:
        for var in eqn.outvars:
            shape = getattr(var.aval, "shape", ())
            # pallas_call outputs/inputs stay blocked; no full S×S tensor
            assert not (
                len(shape) >= 2 and shape[-1] * shape[-2] >= s2
            ), f"O(S²) intermediate {shape} from {eqn.primitive}"


def test_bf16_forward_close():
    q, k, v = _rand_qkv(dtype=jnp.bfloat16)
    out = fa.flash_attention(q, k, v, True)
    ref = sdpa_reference(q, k, v, is_causal=True)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(ref, dtype=np.float32),
        atol=3e-2,
        rtol=3e-2,
    )
