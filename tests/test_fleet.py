"""Elastic fleet runtime (docs/elastic.md): restore-point vote agreement,
coordinated multi-process rollback replacing the resilience refusal,
host-lost-driven dp resize with bitwise state after reshard and
zero-recompile resume off the AOT-cache prewarm, periodic mid-run fleet
aggregation, and the default-off path touching nothing."""

import json
import os

import jax
import numpy as np
import pytest

import accelerate_tpu.nn as nn
import accelerate_tpu.optim as optim
from accelerate_tpu import (
    Accelerator,
    CompilationCacheKwargs,
    FleetKwargs,
    ResilienceKwargs,
    TelemetryKwargs,
)
from accelerate_tpu.checkpointing import is_complete_checkpoint
from accelerate_tpu.data_loader import batch_to_global_array
from accelerate_tpu.fleet import (
    agree_restore_point,
    local_restore_candidates,
    surviving_mesh,
)
from accelerate_tpu.fleet import coordinate as fleet_coordinate
from accelerate_tpu.nn import Tensor
from accelerate_tpu.resilience import FaultPlan
from accelerate_tpu.resilience import retry as res_retry


def _num_devices():
    return len(jax.devices())


def _make_step(handlers=None, seed=0):
    nn.manual_seed(seed)
    acc = Accelerator(kwargs_handlers=handlers or None)
    model = nn.Linear(8, 4)
    opt = optim.AdamW(model.parameters(), lr=1e-2)
    model, opt = acc.prepare(model, opt)

    def step_fn(x):
        opt.zero_grad()
        loss = model(Tensor(x)).sum()
        acc.backward(loss)
        opt.step()
        return loss

    return acc, model, opt, acc.compile_step(step_fn)


def _batches(acc, n, batch=8):
    rng = np.random.default_rng(0)
    return [
        batch_to_global_array(
            np.asarray(rng.normal(size=(batch, 8)), np.float32), mesh=acc.mesh
        )
        for _ in range(n)
    ]


def _write_complete_checkpoint(path, step):
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "accelerator_meta.json"), "w") as f:
        json.dump({"step": step}, f)
    return str(path)


# ---------------------------------------------------------------------------
# fault-plan verb
# ---------------------------------------------------------------------------

def test_host_lost_verb_parses_and_fires_once():
    plan = FaultPlan.parse("host_lost:step=2")
    assert [(d.kind, d.step, d.times) for d in plan.directives] == [
        ("host_lost", 2, 1)
    ]
    from accelerate_tpu.resilience import FaultInjector

    inj = FaultInjector(plan)
    assert not inj.maybe_host_lost(1)  # wrong step
    assert inj.maybe_host_lost(2)
    assert not inj.maybe_host_lost(2)  # times exhausted


def test_host_lost_verb_needs_step():
    with pytest.raises(ValueError):
        FaultPlan.parse("host_lost")


# ---------------------------------------------------------------------------
# pillar 1: restore-point vote
# ---------------------------------------------------------------------------

def test_agree_restore_point_newest_common(tmp_path):
    """The agreement is the HIGHEST-step offer visible to every rank — a
    newer checkpoint only some ranks drained must lose, or the losers'
    collective load_state would hang on its missing shards."""
    a = {"path": "/ckpt/a", "step": 1}
    b = {"path": "/ckpt/b", "step": 2}
    c = {"path": "/ckpt/c", "step": 3}  # rank 0 only: never eligible
    assert agree_restore_point([[c, b, a], [b, a]]) == b
    assert agree_restore_point([[a], [a]]) == a
    assert agree_restore_point([[a, b], [c]]) is None  # disjoint: no vote
    assert agree_restore_point([]) is None
    # world=1 degenerates to the rank's own newest
    assert agree_restore_point([[a, b]]) == b


def test_agree_restore_point_tie_breaks_deterministically():
    """Equal steps must break ties identically on every rank (path order),
    or ranks would load different folders and deadlock."""
    x = {"path": "/ckpt/x", "step": 2}
    y = {"path": "/ckpt/y", "step": 2}
    assert agree_restore_point([[x, y], [y, x]]) == y
    assert agree_restore_point([[y, x], [x, y]]) == y


def test_local_restore_candidates_orders_and_filters(tmp_path):
    acc, _, _, step = _make_step()
    complete_new = _write_complete_checkpoint(tmp_path / "new", step=5)
    incomplete = str(tmp_path / "torn")
    os.makedirs(incomplete)  # no sentinel: killed mid-write
    acc.resilience.enabled = True
    acc.resilience.last_checkpoint = complete_new
    offers = local_restore_candidates(acc)
    assert [o["path"] for o in offers] == [os.path.abspath(complete_new)]
    assert offers[0]["step"] == 5


def test_vote_restore_point_simulated_two_ranks(tmp_path, monkeypatch):
    """The all-ranks agreement pin: simulate the gather of two ranks'
    offers — the newest all-ranks-visible checkpoint wins and the ballot
    lands as a restore_vote fleet event."""
    acc, _, _, _ = _make_step(
        [FleetKwargs(enabled=True), ResilienceKwargs(enabled=True, preemption=False)]
    )
    shared_old = _write_complete_checkpoint(tmp_path / "shared", step=1)
    local_new = _write_complete_checkpoint(tmp_path / "local", step=7)
    acc.resilience.last_checkpoint = local_new
    peer_offers = [{"path": os.path.abspath(shared_old), "step": 1}]
    real_gather = fleet_coordinate.gather_object

    def fake_gather(payload):
        # rank 0 = this process's real offers; rank 1 = a peer that only
        # ever saw the shared checkpoint (its host missed the local drain)
        local = real_gather(payload)
        local.append(peer_offers)
        return local

    monkeypatch.setattr(fleet_coordinate, "gather_object", fake_gather)
    # make this rank ALSO offer the shared checkpoint (both visible here)
    acc.project_configuration.automatic_checkpoint_naming = False
    offers = local_restore_candidates(acc)
    assert len(offers) == 1  # only local_new — shared isn't in this rank's view
    acc.resilience.last_checkpoint = None

    def fake_candidates(accelerator):
        return [
            {"path": os.path.abspath(local_new), "step": 7},
            {"path": os.path.abspath(shared_old), "step": 1},
        ]

    monkeypatch.setattr(fleet_coordinate, "local_restore_candidates", fake_candidates)
    agreed = fleet_coordinate.vote_restore_point(acc, fleet=acc.fleet)
    # local_new (step 7) is NOT in the peer's offers → the shared step-1
    # checkpoint is the only safe restore point
    assert agreed == {"path": os.path.abspath(shared_old), "step": 1}
    votes = [e for e in acc.fleet.events if e["event"] == "restore_vote"]
    assert len(votes) == 1 and votes[0]["ranks"] == 2
    assert votes[0]["agreed"] == os.path.abspath(shared_old)


def test_multiprocess_rollback_refused_without_fleet(monkeypatch):
    """The historical refusal stands when the fleet is off: a lone rank's
    collective load_state would deadlock the mesh."""
    acc, _, _, step = _make_step(
        [ResilienceKwargs(enabled=True, preemption=False)]
    )
    monkeypatch.setattr(res_retry, "_multi_process", lambda: True)
    retrier = acc.resilience.retrier
    assert retrier._rollback_allowed() is False
    assert retrier._coordinator() is None


def test_multiprocess_rollback_coordinated_with_fleet(monkeypatch):
    """ISSUE acceptance: coordinated multi-process rollback replaces the
    single-process refusal — with the fleet armed, a multi-process retrier
    routes exhaustion through the vote protocol instead of refusing."""
    acc, _, _, step = _make_step(
        [
            FleetKwargs(enabled=True),
            ResilienceKwargs(enabled=True, preemption=False),
        ]
    )
    monkeypatch.setattr(res_retry, "_multi_process", lambda: True)
    retrier = acc.resilience.retrier
    assert retrier._coordinator() is acc.fleet
    assert retrier._rollback_allowed() is True
    # opting out of coordination restores the refusal
    acc.fleet.handler.coordinate_rollback = False
    assert retrier._coordinator() is None
    assert retrier._rollback_allowed() is False


def test_coordinated_rollback_end_to_end(tmp_path, monkeypatch):
    """Exhausted retries on a 'multi-process' run vote, agree, restore and
    replay — bitwise — where the pre-fleet retrier raised."""
    acc, _, _, step = _make_step(
        [
            FleetKwargs(enabled=True),
            ResilienceKwargs(
                enabled=True, preemption=False, max_retries=1,
                fault_plan="dispatch:step=3,times=3", retry_backoff_s=0.0,
            ),
        ]
    )
    x = _batches(acc, 1)[0]
    for _ in range(2):
        float(step(x))
    acc.save_state(str(tmp_path / "good"))
    monkeypatch.setattr(res_retry, "_multi_process", lambda: True)
    l2 = float(step(x))
    l3 = float(step(x))  # exhausts → vote → coordinated restore → replay
    assert l3 == l2
    rollbacks = [e for e in acc.resilience.events if e["event"] == "rollback"]
    assert len(rollbacks) == 1 and rollbacks[0]["coordinated"] is True
    assert any(e["event"] == "restore_vote" for e in acc.fleet.events)


# ---------------------------------------------------------------------------
# pillar 2: elastic dp resize
# ---------------------------------------------------------------------------

def test_surviving_mesh_shrinks_dp_only():
    acc, _, _, _ = _make_step()
    mesh = acc.mesh
    dp = dict(mesh.shape)["dp"]
    if dp < 2:
        pytest.skip("needs dp >= 2")
    new = surviving_mesh(mesh, dp // 2)
    assert dict(new.shape)["dp"] == dp // 2
    assert [dict(new.shape)[a] for a in new.axis_names if a != "dp"] == [
        dict(mesh.shape)[a] for a in mesh.axis_names if a != "dp"
    ]
    # survivors are the leading dp blocks: inner-axis neighborhoods intact
    assert new.devices.tolist() == np.take(
        mesh.devices, range(dp // 2), axis=mesh.axis_names.index("dp")
    ).tolist()
    with pytest.raises(ValueError):
        surviving_mesh(mesh, dp * 2)  # growing is a relaunch, not a resize
    with pytest.raises(ValueError):
        surviving_mesh(mesh, 0)


def test_surviving_mesh_honors_lost_blocks():
    """Review-pinned: when the reclamation notice names WHICH dp block
    died, the survivors — not the dead host's devices — make the mesh."""
    acc, _, _, _ = _make_step()
    mesh = acc.mesh
    dp = dict(mesh.shape)["dp"]
    if dp < 2:
        pytest.skip("needs dp >= 2")
    dp_index = mesh.axis_names.index("dp")
    new = surviving_mesh(mesh, dp // 2, lost_blocks=[0])
    # block 0 is gone: the kept blocks start at 1
    expect = np.take(
        mesh.devices, range(1, dp // 2 + 1), axis=dp_index
    ).tolist()
    assert new.devices.tolist() == expect
    with pytest.raises(ValueError):
        surviving_mesh(mesh, dp // 2, lost_blocks=[dp + 3])  # outside axis
    with pytest.raises(ValueError):
        # too many dead blocks for the requested extent
        surviving_mesh(mesh, dp, lost_blocks=[0])


def test_checkpoint_step_fail_soft_on_foreign_meta(tmp_path):
    """Review-pinned: a corrupt/foreign sentinel (non-object JSON) must be
    a skipped candidate, never a crash inside the restore vote."""
    from accelerate_tpu.checkpointing import checkpoint_step

    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "accelerator_meta.json").write_text("[]")
    assert checkpoint_step(str(bad)) is None
    good = tmp_path / "good"
    good.mkdir()
    (good / "accelerator_meta.json").write_text('{"step": 4}')
    assert checkpoint_step(str(good)) == 4


def test_host_lost_injection_trips_should_resize(tmp_path):
    acc, _, _, step = _make_step(
        [FleetKwargs(enabled=True, fault_plan="host_lost:step=1")]
    )
    x = _batches(acc, 1)[0]
    float(step(x))
    assert not acc.fleet.should_resize
    float(step(x))
    assert acc.fleet.should_resize
    assert acc.fleet.should_resize  # sticky
    assert any(e["event"] == "host_lost" for e in acc.fleet.events)


def test_resize_consumes_should_resize_flag(tmp_path):
    """Review-pinned: the documented `if should_resize: resize()` loop must
    not re-drain/re-mesh every later step — resize() consumes the flag it
    handled (a LATER host loss re-trips it)."""
    if _num_devices() < 2:
        pytest.skip("needs >= 2 devices")
    acc, _, _, step = _make_step(
        [FleetKwargs(enabled=True, fault_plan="host_lost:step=0")]
    )
    dp = dict(acc.mesh.shape)["dp"]
    float(step(_batches(acc, 1)[0]))
    assert acc.fleet.should_resize
    acc.fleet.resize(acc, target_dp=dp // 2, output_dir=str(tmp_path / "d"))
    assert not acc.fleet.should_resize
    assert acc.fleet.resizes_total == 1


def test_resize_reshards_bitwise_and_resumes(tmp_path):
    """The acceptance row: a dp=N run with an injected host loss drains a
    complete checkpoint, re-meshes at dp=N/2, reshards ZeRO-1 masters and
    moments BITWISE from the spec-carrying checkpoint, and resumes within
    loss parity of the uninterrupted run."""
    if _num_devices() < 2:
        pytest.skip("needs >= 2 devices")
    steps_total = 5
    lost_at = 2

    # uninterrupted reference at full dp
    Accelerator._reset_state()
    acc_ref, _, _, step_ref = _make_step()
    ref = [float(step_ref(b)) for b in _batches(acc_ref, steps_total)]

    Accelerator._reset_state()
    acc, model, opt, step = _make_step(
        [FleetKwargs(enabled=True, fault_plan=f"host_lost:step={lost_at}")]
    )
    dp = dict(acc.mesh.shape)["dp"]
    assert acc.state.zero1_enabled  # dp > 1, no fsdp owner
    batches = _batches(acc, steps_total)
    losses = []
    resized = None
    i = 0
    while i < len(batches):
        losses.append(float(step(batches[i])))
        i += 1
        if resized is None and acc.fleet.should_resize:
            masters = [
                np.asarray(m) for m in opt.optimizer.master_params if m is not None
            ]
            moments = [
                np.asarray(leaf)
                for leaf in jax.tree_util.tree_leaves(opt.optimizer.capture_state())
            ]
            resized = acc.fleet.resize(
                acc, target_dp=dp // 2, output_dir=str(tmp_path / "drain")
            )
            # drain → COMPLETE checkpoint
            assert is_complete_checkpoint(resized["checkpoint"])
            # re-mesh at the surviving topology
            assert dict(acc.mesh.shape)["dp"] == dp // 2
            assert resized["old_dp"] == dp and resized["dp"] == dp // 2
            # ZeRO-1 masters + moments resharded BITWISE, and actually
            # laid out on the new mesh
            masters_after = [
                np.asarray(m) for m in opt.optimizer.master_params if m is not None
            ]
            for before, after in zip(masters, masters_after):
                assert (before == after).all()
            moments_after = [
                np.asarray(leaf)
                for leaf in jax.tree_util.tree_leaves(opt.optimizer.capture_state())
            ]
            for before, after in zip(moments, moments_after):
                if before.dtype == np.float32 and before.shape:
                    assert (before == after).all()
            for m in opt.optimizer.master_params:
                if m is not None and hasattr(m, "sharding"):
                    assert m.sharding.mesh.shape == acc.mesh.shape
            # surviving batches re-laid on the new mesh
            batches = batches[:i] + [
                batch_to_global_array(np.asarray(b), mesh=acc.mesh)
                for b in batches[i:]
            ]
    assert resized is not None, "host loss never tripped"
    assert len(losses) == steps_total
    # exact through the loss step, loss-parity after the dp change (the
    # reduce order moves with dp; docs/elastic.md documents the tolerance)
    assert losses[: lost_at + 1] == ref[: lost_at + 1]
    np.testing.assert_allclose(losses, ref, rtol=1e-3)
    events = [e["event"] for e in acc.fleet.events]
    assert events.count("host_lost") == 1
    assert events.count("drain") == 1
    assert events.count("resize") == 1


def test_resize_prewarm_zero_recompiles(tmp_path):
    """Acceptance: zero recompiles for programs served by the AOT-cache
    prewarm — a run whose resized topology was already compiled (a prior
    fleet at that dp, same store) resumes with the post-resize first step
    deserialized, not traced."""
    if _num_devices() < 2:
        pytest.skip("needs >= 2 devices")
    cache_dir = str(tmp_path / "aot")
    steps = 3

    def handlers(plan=None):
        out = [
            CompilationCacheKwargs(cache_dir=cache_dir),
            TelemetryKwargs(enabled=True),
            FleetKwargs(enabled=True, fault_plan=plan),
        ]
        return out

    # phase 1 (the "prior fleet"): resize immediately, train at the small
    # topology so its program lands in the store
    Accelerator._reset_state()
    acc, _, _, step = _make_step(handlers())
    dp = dict(acc.mesh.shape)["dp"]
    target = dp // 2
    acc.fleet.resize(acc, target_dp=target, output_dir=str(tmp_path / "seed"))
    for b in _batches(acc, 2):
        float(step(b))
    assert acc.aot_cache.stores >= 1

    # phase 2: fresh run at full dp, host lost at step 1, resize → the
    # post-resize build must be a cache hit (zero trace, zero compile)
    Accelerator._reset_state()
    acc, _, _, step = _make_step(handlers("host_lost:step=1"))
    batches = _batches(acc, steps)
    i = 0
    resized = None
    while i < len(batches):
        float(step(batches[i]))
        i += 1
        if resized is None and acc.fleet.should_resize:
            resized = acc.fleet.resize(
                acc, target_dp=target, output_dir=str(tmp_path / "drain")
            )
            assert resized["aot_prewarmed"] >= 1
            batches = batches[:i] + [
                batch_to_global_array(np.asarray(b), mesh=acc.mesh)
                for b in batches[i:]
            ]
    assert resized is not None
    # the post-resize first call rebuilt (new topology) but deserialized
    # the stored executable: its build phases read zero
    records = acc.telemetry.timeline.records()
    post = [r for r in records if r.built][-1]
    assert post.trace_ms == 0.0 and post.compile_ms == 0.0, (
        post.trace_ms, post.compile_ms,
    )
    hits = [e for e in acc.telemetry.aot_cache_events if e["event"] == "hit"]
    assert len(hits) >= 1


# ---------------------------------------------------------------------------
# pillar 3: periodic fleet aggregation (the resize signal)
# ---------------------------------------------------------------------------

def test_periodic_aggregation_records_fleet_signal():
    acc, _, _, step = _make_step(
        [FleetKwargs(enabled=True, aggregate_every_n=2), TelemetryKwargs(enabled=True)]
    )
    assert acc.fleet.fleet_signal() is None
    for b in _batches(acc, 4):
        float(step(b))
    signals = [
        r for r in acc.telemetry.fleet_events if r.get("kind") == "fleet"
    ]
    assert len(signals) == 2  # cadence 2 over 4 dispatches
    latest = acc.fleet.fleet_signal()
    assert latest is signals[-1]
    assert latest["periodic"] is True and latest["ranks"] == 1
    assert latest["per_rank"][0]["replay_steps"] >= 1
    # the signal rides the retained history → JSONL dump schema
    kinds = {r.get("kind") for r in acc.telemetry.all_records()}
    assert "fleet" in kinds


def test_fleet_events_reach_telemetry_export():
    acc, _, _, step = _make_step(
        [
            FleetKwargs(enabled=True, fault_plan="host_lost:step=0"),
            TelemetryKwargs(enabled=True),
        ]
    )
    float(step(_batches(acc, 1)[0]))
    assert acc.fleet.should_resize
    records = [
        r for r in acc.telemetry.all_records() if r.get("kind") == "fleet_event"
    ]
    assert any(r["event"] == "host_lost" for r in records)


# ---------------------------------------------------------------------------
# default-off
# ---------------------------------------------------------------------------

def test_fleet_default_off_touches_nothing(tmp_path):
    acc, _, _, step = _make_step()
    assert not acc.fleet.enabled
    assert acc.resilience.fleet is None
    assert step._fleet is None  # capture path: one None-check, no hooks
    float(step(_batches(acc, 1)[0]))
    assert acc.fleet.dispatch_calls == 0
    assert acc.fleet.events == []
    with pytest.raises(RuntimeError):
        acc.fleet.resize(acc)


def test_resize_respects_min_dp_floor():
    acc, _, _, _ = _make_step([FleetKwargs(enabled=True, min_dp=4)])
    with pytest.raises(ValueError):
        acc.fleet.resize(acc, target_dp=1)
