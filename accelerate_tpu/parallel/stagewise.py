"""Per-stage captured programs for the interleaved 1F1B schedule.

The lockstep SPMD rehearsal (``_interleaved_1f1b_local``) runs every
(stage, chunk) slot on every device every tick and masks the inactive
ones — the right shape for a shard_map parity rehearsal on virtual CPU
devices, but not the execution model the schedule targets.  On MPMD
hardware each pp stage runs its OWN program, self-clocked: a stage fires
a chunk's forward the moment its activation arrives and a chunk's
backward the moment the cotangent does, with no global barrier per tick.

This module is that execution model, split into two halves:

* :class:`StagewisePrograms` — one captured program per
  ``(stage_id, virtual chunk, role)`` where role is ``fwd`` /
  ``bwd_mid`` / ``bwd_last``, lowered with ``jit().lower().compile()``
  and keyed in the AOT store under a digest of
  ``(plan describe(), stage_id, chunk, role, avals)`` plus the store's
  pinned topology fingerprint.  A warm process deserializes every stage
  program off disk — zero trace, zero XLA compile — before its first
  microbatch moves (the ``loaded`` / ``compiled`` counters are the
  smoke-test surface).
* :func:`stagewise_train_1f1b` — a self-clocked host dispatcher driven
  by :func:`tick_schedule`: the same slot formulas as the lockstep loop
  (forward of chunk ``k``, microbatch ``m`` on device ``d`` at tick
  ``t = d + j`` with ``j = (k + (m//S)·V)·S + (m%S)``; the backward
  mirrored with chunk order reversed, offset ``(S−1−d) + S·V − 1``),
  but executing ONLY the active slots and handing activations /
  cotangents through one-tick delivery queues.  A slot that fires
  before its input arrived raises — the dispatcher doubles as a
  machine-checked proof that the tick schedule is self-consistent.

The params are consumed in the COMMITTED layout (the layout of record:
``Accelerator.prepare()`` permuted the stack once, block ``d·V + k`` =
device ``d``'s chunk ``k`` = global virtual stage ``k·S + d``), and
gradients come back in the same committed order — like the lockstep
path, zero permutation bytes anywhere.

Scope: the pp schedule only, one process (the MPMD dispatch rehearsal).
Stage bodies must be mesh-free — a stage_fn that needs named axes (ring
attention over ``sp``) stays on the lockstep path.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from .pipeline import _apply_local_layers, schedule_ticks


def tick_schedule(num_microbatches: int, num_stages: int, virtual: int):
    """Per-tick event lists of the interleaved 1F1B schedule.

    Returns ``events[t] = [("fwd"|"bwd", device, chunk, microbatch), ...]``
    for ``t`` in ``range(schedule_ticks(M, S, virtual=V))`` — the exact
    active slots the lockstep loop's masks select, enumerated host-side.
    Every (chunk, microbatch) pair appears exactly once per direction per
    device: ``2·M·V`` events per device, ``2·M·V·S`` total.
    """
    M, S, V = num_microbatches, num_stages, virtual
    if M % S:
        raise ValueError(
            f"interleaved 1F1B needs num_microbatches ({M}) divisible by "
            f"the pipeline size ({S})"
        )
    T = schedule_ticks(M, S, virtual=V)
    # two passes so every tick lists its forward slots BEFORE its backward
    # slots — the lockstep loop's within-tick order, and load-bearing: the
    # last virtual stage seeds its backward in the SAME tick as its forward
    # (the window it reads is written by that forward)
    events = [[] for _ in range(T)]
    for d in range(S):
        for j in range(M * V):
            B, i = divmod(j, S)
            events[d + j].append(("fwd", d, B % V, (B // V) * S + i))
    for d in range(S):
        for j in range(M * V):
            B, i = divmod(j, S)
            k_b = (V - 1) - (B % V)
            events[j + (S - 1 - d) + S * V - 1].append(
                ("bwd", d, k_b, (B // V) * S + i)
            )
    return events


class StagewisePrograms:
    """The per-(stage, chunk, role) captured programs of one geometry.

    ``stage_fn(layer_params, h) -> h`` and ``loss_fn(out, labels, extra)
    -> (loss_sum, weight)`` follow the pipeline contracts.  Programs are
    lowered lazily on first dispatch and served from the AOT ``cache``
    when one is armed (scope ``"stagewise"``; a layout/plan flip moves
    the ``plan_desc`` inside the variant digest AND the store's pinned
    fingerprint, so stale entries are loud misses, never wrong
    dispatches).
    """

    def __init__(self, stage_fn: Callable, loss_fn: Callable, *,
                 num_stages: int, virtual: int, cache=None,
                 plan_desc: Optional[dict] = None):
        self.stage_fn = stage_fn
        self.loss_fn = loss_fn
        self.num_stages = num_stages
        self.virtual = virtual
        self.cache = cache
        self.plan_desc = plan_desc or {}
        self.compiled = 0  # programs built by lower().compile() here
        self.loaded = 0  # programs deserialized from the AOT store
        self._programs: dict = {}

    # -- role bodies ---------------------------------------------------------
    def _role_fn(self, role: str) -> Callable:
        stage_fn, loss_fn = self.stage_fn, self.loss_fn

        if role == "fwd":
            def fwd(p_chunk, h):
                return _apply_local_layers(stage_fn, p_chunk, h)

            return fwd
        if role == "bwd_mid":
            def bwd_mid(p_chunk, saved_in, cot):
                _, vjp = jax.vjp(
                    lambda p, i: _apply_local_layers(stage_fn, p, i),
                    p_chunk, saved_in,
                )
                return vjp(cot)

            return bwd_mid
        if role == "bwd_last":
            def bwd_last(p_chunk, saved_in, lbl, extra):
                def f_last(p, inp, ep):
                    return loss_fn(
                        _apply_local_layers(stage_fn, p, inp), lbl, ep
                    )

                lsum, vjp, w = jax.vjp(
                    f_last, p_chunk, saved_in, extra, has_aux=True
                )
                dp, dinp, dep = vjp(jnp.float32(1.0))
                return lsum, jnp.asarray(w, jnp.float32), dp, dinp, dep

            return bwd_last
        raise ValueError(f"unknown stagewise role {role!r}")

    # -- AOT keying ----------------------------------------------------------
    def _variant_digest(self, stage_id: int, chunk: int, role: str,
                        args) -> str:
        from ..native.aot_cache import _digest, _leaf_aval

        return _digest({
            "plan": self.plan_desc,
            "stage": stage_id,
            "chunk": chunk,
            "role": role,
            "avals": [_leaf_aval(x) for x in jax.tree_util.tree_leaves(args)],
        })

    def program(self, stage_id: int, chunk: int, role: str, args):
        """The compiled program for one ``(stage, chunk, role)`` slot —
        memory, then AOT store, then a fresh ``lower().compile()`` (stored
        back when a cache is armed).  ``args`` are example/abstract inputs
        of the role's signature."""
        key = (stage_id, chunk, role)
        compiled = self._programs.get(key)
        if compiled is not None:
            return compiled
        key_desc = f"stagewise:s{stage_id}c{chunk}:{role}"
        cache = self.cache if (self.cache is not None
                               and self.cache.enabled) else None
        variant = self._variant_digest(stage_id, chunk, role, args)
        if cache is not None:
            entry = cache.lookup(variant, cache.fingerprint(), "stagewise",
                                 key_desc, defer_hit=True)
            if entry is not None:
                try:
                    from jax.experimental import serialize_executable

                    compiled = serialize_executable.deserialize_and_load(
                        entry["payload"], entry["in_tree"], entry["out_tree"]
                    )
                except Exception as exc:
                    cache.record_miss(
                        "stagewise", key_desc,
                        f"deserialize failed "
                        f"({type(exc).__name__}: {exc})"[:200],
                    )
                else:
                    cache.commit_hit(entry, "stagewise", key_desc)
                    self.loaded += 1
                    self._programs[key] = compiled
                    return compiled
        t0 = time.perf_counter()
        lowered = jax.jit(self._role_fn(role)).lower(*args)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        self.compiled += 1
        if cache is not None:
            cache.store(
                variant, cache.fingerprint(), compiled, {"sig": key_desc},
                "stagewise", key_desc,
                trace_ms=(t1 - t0) * 1e3, compile_ms=(t2 - t1) * 1e3,
            )
        self._programs[key] = compiled
        return compiled


def stagewise_train_1f1b(
    stage_fn: Callable,
    committed_params,
    x: jax.Array,
    labels: jax.Array,
    extra_params,
    loss_fn: Callable,
    num_microbatches: int,
    *,
    num_stages: int,
    virtual: int,
    programs: Optional[StagewisePrograms] = None,
    cache=None,
    plan_desc: Optional[dict] = None,
):
    """Self-clocked per-stage dispatch of one interleaved 1F1B step.

    ``committed_params``: the stacked layer tree ALREADY in the committed
    layout (block ``d·V + k`` of the leading axis = device ``d``'s chunk
    ``k``).  Returns ``(loss, dcommitted_params, dx, dextra_params)`` with
    gradients in the same committed order and identical normalisation to
    the lockstep path (global token mean) — the parity contract the tests
    pin.  Pass a :class:`StagewisePrograms` to reuse programs across
    steps; otherwise one is built (and returned state discarded).
    """
    M, S, V = num_microbatches, num_stages, virtual
    if programs is None:
        programs = StagewisePrograms(
            stage_fn, loss_fn, num_stages=S, virtual=V,
            cache=cache, plan_desc=plan_desc,
        )
    leaves = jax.tree_util.tree_leaves(committed_params)
    L = leaves[0].shape[0]
    if L % (S * V):
        raise ValueError(
            f"num_layers {L} not divisible by num_stages×virtual = {S}×{V}"
        )
    if x.shape[0] % M:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by num_microbatches {M}"
        )
    c = L // (S * V)
    mb = x.shape[0] // M
    x_mb = x.reshape(M, mb, *x.shape[1:])
    labels_mb = labels.reshape(M, mb, *labels.shape[1:])

    def chunk_params(d, k):
        b = d * V + k
        return jax.tree_util.tree_map(
            lambda p: jax.lax.slice_in_dim(p, b * c, (b + 1) * c, axis=0),
            committed_params,
        )

    p_chunks = {(d, k): chunk_params(d, k)
                for d in range(S) for k in range(V)}

    acts: dict = {}  # (consumer virtual stage, microbatch) -> activation
    cots: dict = {}  # (consumer virtual stage, microbatch) -> cotangent
    windows: dict = {}  # (device, chunk, microbatch) -> saved stage input
    dchunks = {b: None for b in range(S * V)}  # committed-block grad accum
    dextra = jax.tree_util.tree_map(jnp.zeros_like, extra_params)
    dx_mb = [None] * M
    loss_sum = jnp.zeros((), jnp.float32)
    weight_sum = jnp.zeros((), jnp.float32)

    def add(acc, g):
        return g if acc is None else jax.tree_util.tree_map(
            lambda a, b: a + b, acc, g
        )

    from ..telemetry import flightrec

    for tick_index, tick_events in enumerate(tick_schedule(M, S, V)):
        # flight event per tick (docs/telemetry.md §flight recorder): in a
        # postmortem the last recorded tick names exactly which (stage,
        # chunk, microbatch) slots the dispatcher died between
        flightrec.record(
            "pipeline_tick", tick=tick_index, slots=len(tick_events)
        )
        arriving_acts: dict = {}
        arriving_cots: dict = {}
        for role, d, k, m in tick_events:
            v = k * S + d  # global virtual stage of this slot
            if role == "fwd":
                # v=0 reads its microbatch; everyone else consumes the
                # activation delivered by v−1 — pop() raising KeyError IS
                # the self-clocking check (input must exist by this tick)
                my_in = x_mb[m] if v == 0 else acts.pop((v, m))
                windows[(d, k, m)] = my_in
                out = programs.program(d, k, "fwd", (p_chunks[(d, k)], my_in))(
                    p_chunks[(d, k)], my_in
                )
                if v < S * V - 1:
                    arriving_acts[(v + 1, m)] = out
                # the last virtual stage's forward output is dropped: its
                # backward recomputes through the loss head (stage-granular
                # activation checkpointing, exactly the lockstep policy)
            else:
                saved_in = windows.pop((d, k, m))
                if v == S * V - 1:
                    args = (p_chunks[(d, k)], saved_in, labels_mb[m],
                            extra_params)
                    lsum, w, dp, dinp, dep = programs.program(
                        d, k, "bwd_last", args
                    )(*args)
                    loss_sum = loss_sum + lsum
                    weight_sum = weight_sum + w
                    dextra = jax.tree_util.tree_map(
                        lambda a, g: a + g, dextra, dep
                    )
                else:
                    cot = cots.pop((v, m))
                    args = (p_chunks[(d, k)], saved_in, cot)
                    dp, dinp = programs.program(d, k, "bwd_mid", args)(*args)
                dchunks[d * V + k] = add(dchunks[d * V + k], dp)
                if v == 0:
                    dx_mb[m] = dinp
                else:
                    arriving_cots[(v - 1, m)] = dinp
        # one-tick delivery: what this tick produced becomes visible next
        # tick (the host image of the lockstep loop's ppermute hand-off)
        acts.update(arriving_acts)
        cots.update(arriving_cots)

    if acts or cots or windows:
        raise AssertionError(
            f"self-clocked schedule left undelivered state: "
            f"{len(acts)} acts, {len(cots)} cots, {len(windows)} windows"
        )

    total_w = jnp.maximum(weight_sum, 1e-9)
    loss = loss_sum / total_w
    inv_w = 1.0 / total_w
    dcommitted = jax.tree_util.tree_map(
        lambda *gs: jnp.concatenate(gs, axis=0)
        * inv_w.astype(gs[0].dtype),
        *[dchunks[b] for b in range(S * V)],
    )
    dextra = jax.tree_util.tree_map(
        lambda g: g * inv_w.astype(g.dtype), dextra
    )
    dx = (jnp.stack(dx_mb) * inv_w).astype(x.dtype).reshape(x.shape)
    return loss, dcommitted, dx, dextra


__all__ = [
    "StagewisePrograms",
    "stagewise_train_1f1b",
    "tick_schedule",
]
