"""Disk offload store — numpy memmaps + index.json.

Capability parity with the reference's ``utils/offload.py``
(``offload_weight`` :25, ``load_offloaded_weight`` :46,
``OffloadedWeightsLoader`` :127): weights that don't fit in HBM+host RAM live
as raw little-endian ``.dat`` files described by one ``index.json``; readers
get zero-copy ``np.memmap`` views, so streaming a layer to the TPU is one
disk→HBM DMA with no host staging copy.
"""

from __future__ import annotations

import json
import os
from collections.abc import Mapping
from typing import Optional

import numpy as np


def offload_weight(weight, weight_name: str, offload_folder: str, index: Optional[dict] = None) -> dict:
    """Write one weight to ``<folder>/<name>.dat`` and record it in index."""
    weight = np.asarray(weight)
    dtype = str(weight.dtype)
    if dtype.startswith("bfloat16"):
        # numpy has no bfloat16: store the raw 16-bit pattern, remember tag
        weight = weight.view(np.uint16) if weight.dtype.itemsize == 2 else weight
        dtype = "bfloat16"
    os.makedirs(offload_folder, exist_ok=True)
    array_path = os.path.join(offload_folder, f"{weight_name}.dat")
    if index is not None:
        index[weight_name] = {"dtype": dtype, "shape": list(weight.shape)}
    if weight.ndim == 0:
        weight = weight[None]
    file_array = np.memmap(array_path, dtype=weight.dtype, mode="w+", shape=weight.shape)
    file_array[:] = weight[:]
    file_array.flush()
    return index if index is not None else {}


def load_offloaded_weight(weight_file: str, weight_info: dict) -> np.ndarray:
    shape = tuple(weight_info["shape"])
    if len(shape) == 0:
        shape = (1,)
    dtype = weight_info["dtype"]
    if dtype == "bfloat16":
        import jax.numpy as jnp

        raw = np.memmap(weight_file, dtype=np.uint16, mode="r", shape=shape)
        arr = np.asarray(raw)
        if not tuple(weight_info["shape"]):
            arr = arr[0]
        return arr.view(np.dtype(jnp.bfloat16))
    weight = np.memmap(weight_file, dtype=dtype, mode="r", shape=shape)
    if not tuple(weight_info["shape"]):
        weight = weight[0]
    return weight


def save_offload_index(index: dict, offload_folder: str) -> None:
    if not index:
        return
    os.makedirs(offload_folder, exist_ok=True)
    with open(os.path.join(offload_folder, "index.json"), "w") as f:
        json.dump(index, f, indent=2)


def load_offload_index(offload_folder: str) -> dict:
    path = os.path.join(offload_folder, "index.json")
    if not os.path.isfile(path):
        return {}
    with open(path) as f:
        return json.load(f)


def offload_state_dict(save_dir: str, state_dict: Mapping) -> None:
    """Offload a whole state dict (reference: utils/offload.py:80)."""
    index: dict = {}
    for name, value in state_dict.items():
        index = offload_weight(value, name, save_dir, index)
    save_offload_index(index, save_dir)


class OffloadedWeightsLoader(Mapping):
    """Unified lazy view over in-memory and on-disk weights
    (reference: utils/offload.py:127)."""

    def __init__(
        self,
        state_dict: Optional[dict] = None,
        save_folder: Optional[str] = None,
        index: Optional[Mapping] = None,
    ):
        if state_dict is None and save_folder is None and index is None:
            raise ValueError("need state_dict and/or save_folder/index")
        self.state_dict = dict(state_dict or {})
        self.save_folder = save_folder
        if index is None and save_folder is not None:
            index = load_offload_index(save_folder)
        self.index = dict(index or {})
        self.all_keys = list(self.state_dict.keys())
        self.all_keys.extend(k for k in self.index if k not in self.all_keys)

    def __getitem__(self, key: str):
        if key in self.state_dict:
            return self.state_dict[key]
        weight_info = self.index[key]
        weight_file = os.path.join(self.save_folder, f"{key}.dat")
        return load_offloaded_weight(weight_file, weight_info)

    def __iter__(self):
        return iter(self.all_keys)

    def __len__(self):
        return len(self.all_keys)


def extract_submodules_state_dict(state_dict: Mapping, submodule_names: list[str]) -> dict:
    """Sub-view of a state dict for the given module prefixes
    (reference: utils/offload.py:205)."""
    out = {}
    for name in submodule_names:
        out.update(
            {
                key: value
                for key, value in state_dict.items()
                if key == name or key.startswith(name + ".")
            }
        )
    return out


class PrefixedDataset(Mapping):
    """Mapping view that prepends/strips a prefix (reference: utils/offload.py:96)."""

    def __init__(self, dataset: Mapping, prefix: str):
        self.dataset = dataset
        self.prefix = prefix

    def __getitem__(self, key):
        return self.dataset[f"{self.prefix}{key}"]

    def __iter__(self):
        return iter(k for k in self.dataset if k.startswith(self.prefix))

    def __len__(self):
        return len(self.dataset)
